"""Fault-injection harness for the fleet merge path.

Every degradation path in docs/RESILIENCE.md is exercisable on the
8-device CPU mesh in CI by arming faults at named *sites* — the
instrumented choke points of the device pipeline:

- ``launch``       — DeviceSupervisor.launch: raise before the device
                     call (transient ``UNAVAILABLE`` or fatal)
- ``fetch``        — DeviceSupervisor.fetch/drain: slow fetch (delay)
- ``decode``       — native explode entries: truncate / bit-flip the
                     wire bytes before the C++ parser sees them
- ``poison_doc``   — ResidentServer.ingest: corrupt one doc's payload
                     in a round (per-doc isolation test)
- ``backend_init`` — resilience.probe subprocesses: hang or raise
                     during backend init (the TPU-pool lottery)
- ``wal_write``    — persist.wal append: raise/delay before the frame
                     reaches disk (durability-path failures)
- ``wal_torn_tail``— persist.wal append: mangle the frame bytes on
                     their way to disk (truncate = a genuinely torn
                     write for the reopen-tolerance tests)
- ``ckpt_corrupt`` — persist.checkpoints save: mangle the framed blob
                     (recovery must fall back down the ladder)
- ``sync_push``    — sync.SyncServer push entry: raise/delay before the
                     fan-in queue, or mangle the client's update bytes
                     (typed PushRejected / poison-ticket paths)
- ``sync_pull``    — sync.Session.pull: raise/delay before the delta
                     export (client-visible read-path failures)
- ``read_batch``   — sync.ReadBatcher window worker: fires before any
                     device work on a drained pull window — the whole
                     window degrades to per-doc oracle pulls (typed,
                     counted, invisible to sessions)
- ``export_launch``— the batched delta-export selection launch (fleet
                     export_select thunk, inside the supervisor): a
                     transient UNAVAILABLE retries like any launch, a
                     terminal error becomes DeviceFailure and degrades
                     ONLY that window to the oracle
- ``session_stall``— sync fan-out delivery: delay one session's
                     notification slot (slow-consumer backpressure and
                     the soak's stalled-session churn)
- ``evict_flush``  — residency.TieredBatch eviction: fires after the
                     warm mirror is built but before any tier state
                     mutates — a failure here must leave the doc HOT
                     (no torn tier state), surfaced as a typed
                     ResidencyError
- ``revive_replay``— residency.TieredBatch revive: fires after the
                     mirror/history export but before the slot landing
                     — a failure fails only the triggering round or
                     ticket (typed ResidencyError), the doc stays
                     warm/cold and the server stays healthy
- ``repl_ship``    — replication.WalShipper.read: every shipped byte
                     crosses it — raise/delay = a mid-ship crash (the
                     follower resumes from its acked offset);
                     truncate/bitflip = a genuinely torn shipped tail
                     the follower truncates like a WAL reopen
- ``repl_apply``   — replication.Follower apply loop: fires before
                     each shipped round applies to the follower batch
- ``repl_promote`` — replication.Follower.promote entry: fires before
                     the fencing token bump (promotion races / crash-
                     before-fence; a retried promote starts clean)
- ``net_accept``   — net.NetServer accept path: refuse the next
                     accepted connection(s) typed — live connections
                     and their sessions keep serving
- ``net_frame``    — net.NetServer frame reader: mangle one received
                     frame's bytes before the crc gate (typed
                     CodecDecodeError fails ONLY that connection)
- ``conn_stall``   — net.NetServer per-connection writer: delay = a
                     stalled/slow reader socket (bounded send-queue
                     backpressure); raise = typed teardown of that
                     one connection

Arm programmatically::

    from loro_tpu.resilience import faultinject as fi
    fi.inject("launch", exc=RuntimeError("UNAVAILABLE: injected"), times=2)
    try:
        ...  # exercised path
    finally:
        fi.clear()

or from the environment (processes you can't reach, e.g. probe
subprocesses): ``LORO_FAULT="launch:raise:times=2;decode:truncate=16"``.
Entries are ``;``-separated ``site:action[:k=v]*`` specs; actions are
``raise`` (optional ``msg=``, default transient ``UNAVAILABLE``),
``delay`` (``s=`` seconds), ``hang`` (delay with a 60s safety clamp),
``truncate`` (``=N`` bytes to keep, default half), ``bitflip``
(``=OFFSET``, default middle byte), and ``poison`` (``docs=1+3``).

Every fire ticks ``faultinject.fired_total{site=...}`` in the obs
registry.  Tier-1 hygiene: tests arming faults carry the
``faultinject`` marker and the conftest guard asserts ``active()`` is
empty after every test — a leaked fault fails the leaking test's
teardown, not some unrelated test three files later.

**Site registry.**  Every instrumented module declares its sites at
import time (``register_site(name, help)`` next to the ``check()``/
``mangle()`` call sites); ``sites()`` returns the full catalogue
(importing the known instrumented modules first, so the answer does
not depend on what the caller happened to import).  ``inject()`` and
``LORO_FAULT`` entries naming an unknown site raise a typed
``errors.ConfigError`` at first use — a typo'd
``LORO_FAULT="wal_wirte:raise"`` used to be a silent no-op, which is
the worst possible failure mode for a fault you believed you were
testing under.  Malformed entries (unknown action, bad ``k=v``) raise
typed the same way instead of being skipped.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..errors import ConfigError
from ..obs import flight as _flight
from ..obs import metrics as _obs

# -- fault-site registry ----------------------------------------------
# modules that own check()/mangle() call sites; sites() imports them so
# the catalogue is complete even before the stack is built.  A module
# added here registers its sites at import; the docs/registry
# cross-check test (tests/test_chaos.py) catches drift in BOTH
# directions (a site documented but never registered, or registered
# but undocumented).
_SITE_MODULES = (
    "loro_tpu.resilience.supervisor",
    "loro_tpu.resilience.probe",
    "loro_tpu.native",
    "loro_tpu.parallel.fleet",
    "loro_tpu.parallel.server",
    "loro_tpu.parallel.residency",
    "loro_tpu.persist.wal",
    "loro_tpu.persist.checkpoints",
    "loro_tpu.sync.server",
    "loro_tpu.sync.session",
    "loro_tpu.sync.presence",
    "loro_tpu.sync.readbatch",
    "loro_tpu.replication.shipper",
    "loro_tpu.replication.follower",
    "loro_tpu.obs.health",
    "loro_tpu.net.server",
)

_ACTIONS = ("raise", "delay", "hang", "truncate", "bitflip", "poison")

_registry: Dict[str, dict] = {}


def register_site(name: str, help: str = "") -> str:
    """Declare a fault site (call at module import, next to the
    ``check()``/``mangle()`` call sites it covers).  Idempotent — a
    site instrumented at several choke points (``session_stall``,
    ``export_launch``) registers once per module, first help text
    wins.  Returns the name so call sites can bind it."""
    import sys

    mod = sys._getframe(1).f_globals.get("__name__", "?")
    with _lock:
        info = _registry.get(name)
        if info is None:
            _registry[name] = {"help": help, "modules": [mod]}
        elif mod not in info["modules"]:
            info["modules"].append(mod)
    return name


def _load_site_modules() -> None:
    """Complete the registry by importing every instrumented module
    (idempotent; already-imported modules are sys.modules hits)."""
    import importlib

    for m in _SITE_MODULES:
        importlib.import_module(m)


def sites() -> Dict[str, dict]:
    """The full site catalogue: ``{name: {"help": ..., "modules":
    [...]}}``.  Imports the instrumented modules first so the answer
    is complete regardless of what the caller loaded."""
    _load_site_modules()
    with _lock:
        return {k: dict(v) for k, v in sorted(_registry.items())}


def _require_site(site: str, knob: str) -> None:
    """Typed rejection of unknown site names.  Cheap when the site is
    already registered (no imports); the full module sweep runs only
    to prove a name genuinely unknown (and name the accepted set)."""
    with _lock:
        if site in _registry:
            return
    _load_site_modules()
    with _lock:
        if site in _registry:
            return
        known = ", ".join(sorted(_registry))
    raise ConfigError(knob, site, f"registered fault sites: {known}")


class InjectedFault(Exception):
    """Default exception for ``raise`` faults.  The message decides
    transience the same way real backend errors do (the supervisor
    greps for ``UNAVAILABLE``-class markers)."""


@dataclass
class Fault:
    site: str
    action: str = "raise"          # raise | delay | hang | truncate | bitflip | poison
    exc: Optional[BaseException] = None   # for raise: exception instance to throw
    exc_factory: Optional[Callable[[], BaseException]] = None
    delay_s: float = 0.0           # for delay/hang
    keep_bytes: Optional[int] = None      # for truncate: prefix length to keep
    flip_at: Optional[int] = None  # for bitflip: byte offset (None = middle)
    docs: Optional[frozenset] = None      # for poison: doc indexes to hit
    times: Optional[int] = None    # fire at most N times (None = unlimited)
    fired: int = 0


_lock = threading.Lock()
_faults: Dict[str, List[Fault]] = {}
_sleep: Callable[[float], None] = None  # injectable for tests (None = time.sleep)
_env_loaded = False


def set_sleep(fn: Optional[Callable[[float], None]]) -> None:
    """Replace the sleeper delay/hang faults use (fake clocks in tests;
    None restores time.sleep)."""
    global _sleep
    _sleep = fn


def _do_sleep(s: float) -> None:
    if s <= 0:
        return
    if _sleep is not None:
        _sleep(s)
    else:
        import time

        time.sleep(s)


def inject(site: str, *, action: str = "raise", exc: Optional[BaseException] = None,
           exc_factory: Optional[Callable[[], BaseException]] = None,
           delay_s: float = 0.0, keep_bytes: Optional[int] = None,
           flip_at: Optional[int] = None, docs=None,
           times: Optional[int] = None) -> Fault:
    """Arm one fault.  Returns the Fault (its ``fired`` counter is
    live).  Unknown site names and actions raise typed ConfigError —
    an armed-but-misspelled fault that can never fire is worse than a
    crash (the test it was guarding passes vacuously)."""
    _require_site(site, "faultinject.inject site")
    if action not in _ACTIONS:
        raise ConfigError(
            "faultinject.inject action", action,
            "one of: " + ", ".join(_ACTIONS),
        )
    f = Fault(
        site=site, action=action, exc=exc, exc_factory=exc_factory,
        delay_s=delay_s, keep_bytes=keep_bytes, flip_at=flip_at,
        docs=frozenset(docs) if docs is not None else None, times=times,
    )
    with _lock:
        _faults.setdefault(site, []).append(f)
    return f


def clear(site: Optional[str] = None) -> None:
    with _lock:
        if site is None:
            _faults.clear()
        else:
            _faults.pop(site, None)


def active() -> Dict[str, int]:
    """Armed (non-exhausted) fault counts per site — the conftest
    leak guard's view."""
    with _lock:
        out = {}
        for site, fs in _faults.items():
            n = sum(1 for f in fs if f.times is None or f.fired < f.times)
            if n:
                out[site] = n
        return out


def fired(site: str) -> int:
    with _lock:
        return sum(f.fired for f in _faults.get(site, ()))


# actions that only have an effect where bytes flow (mangle); check()
# must leave them armed so a site instrumented with BOTH calls — e.g.
# replication's ``repl_ship`` (check before the read, mangle on the
# streamed bytes) — delivers them to the mangle that can apply them
_MANGLE_ACTIONS = ("truncate", "bitflip", "poison")


def _take(site: str, doc: Optional[int] = None,
          skip_mangle: bool = False) -> Optional[Fault]:
    """First armed fault at `site` that matches `doc`; ticks counters.

    Disarmed fast path: with the env parsed and no faults in the
    table, return without touching the lock — production ingest calls
    mangle() once per doc per round and must pay ~nothing when
    LORO_FAULT is unset (reading a dict's truthiness is atomic in
    CPython)."""
    if _env_loaded and not _faults:
        return None
    _load_env()
    with _lock:
        for f in _faults.get(site, ()):
            if f.times is not None and f.fired >= f.times:
                continue
            if f.docs is not None and (doc is None or doc not in f.docs):
                continue
            if skip_mangle and f.action in _MANGLE_ACTIONS:
                continue
            f.fired += 1
            _obs.counter("faultinject.fired_total").inc(site=site, action=f.action)
            _flight.record("fault.fired", site=site, action=f.action,
                           doc=doc)
            return f
    return None


def _hang_delay(f: Fault) -> float:
    """A 'hang' with no explicit delay must actually hang (clamped to
    the 60s safety cap), not no-op — a vacuous hang fault would let
    every init-hang degradation test pass without exercising anything."""
    return min(f.delay_s, 60.0) if f.delay_s > 0 else 60.0


def check(site: str, doc: Optional[int] = None, **ctx) -> bool:
    """Called at instrumented sites.  Raises / sleeps per the armed
    fault; returns True iff a fault fired (False = clean pass).
    Mangle-class faults (truncate/bitflip/poison) are left armed for
    the site's ``mangle()`` call — check can't apply them."""
    f = _take(site, doc, skip_mangle=True)
    if f is None:
        return False
    if f.action in ("delay", "hang"):
        _do_sleep(_hang_delay(f) if f.action == "hang" else f.delay_s)
        return True
    if f.action == "raise":
        if f.exc_factory is not None:
            raise f.exc_factory()
        raise (f.exc if f.exc is not None else InjectedFault(
            f"UNAVAILABLE: injected fault at {site}"))
    return True  # truncate/bitflip/poison fire through mangle()


def mangle(site: str, payload, doc: Optional[int] = None):
    """Corrupt wire bytes at an instrumented decode site.  Non-bytes
    payloads and clean passes come back unchanged."""
    if not isinstance(payload, (bytes, bytearray)):
        return payload
    f = _take(site, doc)
    if f is None:
        return payload
    b = bytes(payload)
    if f.action == "truncate":
        keep = f.keep_bytes if f.keep_bytes is not None else len(b) // 2
        return b[: max(0, min(keep, len(b)))]
    if f.action in ("bitflip", "poison"):
        if not b:
            return b
        at = f.flip_at if f.flip_at is not None else len(b) // 2
        at = max(0, min(at, len(b) - 1))
        return b[:at] + bytes([b[at] ^ 0x5A]) + b[at + 1:]
    if f.action == "raise":
        if f.exc_factory is not None:
            raise f.exc_factory()
        raise (f.exc if f.exc is not None else InjectedFault(
            f"UNAVAILABLE: injected fault at {site}"))
    if f.action in ("delay", "hang"):
        _do_sleep(_hang_delay(f) if f.action == "hang" else f.delay_s)
    return b


# -- env wiring (LORO_FAULT) -------------------------------------------
def _load_env() -> None:
    """Parse LORO_FAULT once per process (probe subprocesses and CI
    runs arm faults without touching Python)."""
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
    spec = os.environ.get("LORO_FAULT", "").strip()
    if spec:
        for entry in spec.replace(",", ";").split(";"):
            entry = entry.strip()
            if entry:
                # a typo'd site/action/k=v raises typed ConfigError at
                # the FIRST instrumented call — the old behavior
                # (silently skip the entry) meant the fault you thought
                # you were testing under never existed
                _install_env_entry(entry)


def _install_env_entry(entry: str) -> None:
    parts = entry.split(":")
    site = parts[0]
    action = parts[1] if len(parts) > 1 else "raise"
    kw: dict = {}
    base, _, val = action.partition("=")
    try:
        if base == "truncate":
            kw["keep_bytes"] = int(val) if val else None
        elif base == "bitflip":
            kw["flip_at"] = int(val) if val else None
        elif val:
            raise ValueError(f"action {base!r} takes no =value")
        for p in parts[2:]:
            k, _, v = p.partition("=")
            if k == "times":
                kw["times"] = int(v)
            elif k in ("s", "delay"):
                kw["delay_s"] = float(v)
            elif k == "msg":
                kw["exc"] = InjectedFault(v)
            elif k == "docs":
                kw["docs"] = frozenset(int(x) for x in v.split("+") if x)
            else:
                raise ValueError(f"unknown key {k!r}")
    except ValueError as e:
        if isinstance(e, ConfigError):
            raise
        raise ConfigError(
            "LORO_FAULT", entry,
            "site:action[:k=v]* with action in "
            f"{'/'.join(_ACTIONS)} and keys times=/s=/delay=/msg=/docs= "
            f"({e})",
        ) from e
    inject(site, action=base, **kw)


def _reset_env_cache_for_tests() -> None:
    global _env_loaded
    _env_loaded = False
