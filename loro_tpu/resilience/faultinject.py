"""Fault-injection harness for the fleet merge path.

Every degradation path in docs/RESILIENCE.md is exercisable on the
8-device CPU mesh in CI by arming faults at named *sites* — the
instrumented choke points of the device pipeline:

- ``launch``       — DeviceSupervisor.launch: raise before the device
                     call (transient ``UNAVAILABLE`` or fatal)
- ``fetch``        — DeviceSupervisor.fetch/drain: slow fetch (delay)
- ``decode``       — native explode entries: truncate / bit-flip the
                     wire bytes before the C++ parser sees them
- ``poison_doc``   — ResidentServer.ingest: corrupt one doc's payload
                     in a round (per-doc isolation test)
- ``backend_init`` — resilience.probe subprocesses: hang or raise
                     during backend init (the TPU-pool lottery)
- ``wal_write``    — persist.wal append: raise/delay before the frame
                     reaches disk (durability-path failures)
- ``wal_torn_tail``— persist.wal append: mangle the frame bytes on
                     their way to disk (truncate = a genuinely torn
                     write for the reopen-tolerance tests)
- ``ckpt_corrupt`` — persist.checkpoints save: mangle the framed blob
                     (recovery must fall back down the ladder)
- ``sync_push``    — sync.SyncServer push entry: raise/delay before the
                     fan-in queue, or mangle the client's update bytes
                     (typed PushRejected / poison-ticket paths)
- ``sync_pull``    — sync.Session.pull: raise/delay before the delta
                     export (client-visible read-path failures)
- ``read_batch``   — sync.ReadBatcher window worker: fires before any
                     device work on a drained pull window — the whole
                     window degrades to per-doc oracle pulls (typed,
                     counted, invisible to sessions)
- ``export_launch``— the batched delta-export selection launch (fleet
                     export_select thunk, inside the supervisor): a
                     transient UNAVAILABLE retries like any launch, a
                     terminal error becomes DeviceFailure and degrades
                     ONLY that window to the oracle
- ``session_stall``— sync fan-out delivery: delay one session's
                     notification slot (slow-consumer backpressure and
                     the soak's stalled-session churn)
- ``evict_flush``  — residency.TieredBatch eviction: fires after the
                     warm mirror is built but before any tier state
                     mutates — a failure here must leave the doc HOT
                     (no torn tier state), surfaced as a typed
                     ResidencyError
- ``revive_replay``— residency.TieredBatch revive: fires after the
                     mirror/history export but before the slot landing
                     — a failure fails only the triggering round or
                     ticket (typed ResidencyError), the doc stays
                     warm/cold and the server stays healthy
- ``repl_ship``    — replication.WalShipper.read: every shipped byte
                     crosses it — raise/delay = a mid-ship crash (the
                     follower resumes from its acked offset);
                     truncate/bitflip = a genuinely torn shipped tail
                     the follower truncates like a WAL reopen
- ``repl_apply``   — replication.Follower apply loop: fires before
                     each shipped round applies to the follower batch
- ``repl_promote`` — replication.Follower.promote entry: fires before
                     the fencing token bump (promotion races / crash-
                     before-fence; a retried promote starts clean)

Arm programmatically::

    from loro_tpu.resilience import faultinject as fi
    fi.inject("launch", exc=RuntimeError("UNAVAILABLE: injected"), times=2)
    try:
        ...  # exercised path
    finally:
        fi.clear()

or from the environment (processes you can't reach, e.g. probe
subprocesses): ``LORO_FAULT="launch:raise:times=2;decode:truncate=16"``.
Entries are ``;``-separated ``site:action[:k=v]*`` specs; actions are
``raise`` (optional ``msg=``, default transient ``UNAVAILABLE``),
``delay`` (``s=`` seconds), ``hang`` (delay with a 60s safety clamp),
``truncate`` (``=N`` bytes to keep, default half), ``bitflip``
(``=OFFSET``, default middle byte), and ``poison`` (``docs=1+3``).

Every fire ticks ``faultinject.fired_total{site=...}`` in the obs
registry.  Tier-1 hygiene: tests arming faults carry the
``faultinject`` marker and the conftest guard asserts ``active()`` is
empty after every test — a leaked fault fails the leaking test's
teardown, not some unrelated test three files later.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..obs import metrics as _obs


class InjectedFault(Exception):
    """Default exception for ``raise`` faults.  The message decides
    transience the same way real backend errors do (the supervisor
    greps for ``UNAVAILABLE``-class markers)."""


@dataclass
class Fault:
    site: str
    action: str = "raise"          # raise | delay | hang | truncate | bitflip | poison
    exc: Optional[BaseException] = None   # for raise: exception instance to throw
    exc_factory: Optional[Callable[[], BaseException]] = None
    delay_s: float = 0.0           # for delay/hang
    keep_bytes: Optional[int] = None      # for truncate: prefix length to keep
    flip_at: Optional[int] = None  # for bitflip: byte offset (None = middle)
    docs: Optional[frozenset] = None      # for poison: doc indexes to hit
    times: Optional[int] = None    # fire at most N times (None = unlimited)
    fired: int = 0


_lock = threading.Lock()
_faults: Dict[str, List[Fault]] = {}
_sleep: Callable[[float], None] = None  # injectable for tests (None = time.sleep)
_env_loaded = False


def set_sleep(fn: Optional[Callable[[float], None]]) -> None:
    """Replace the sleeper delay/hang faults use (fake clocks in tests;
    None restores time.sleep)."""
    global _sleep
    _sleep = fn


def _do_sleep(s: float) -> None:
    if s <= 0:
        return
    if _sleep is not None:
        _sleep(s)
    else:
        import time

        time.sleep(s)


def inject(site: str, *, action: str = "raise", exc: Optional[BaseException] = None,
           exc_factory: Optional[Callable[[], BaseException]] = None,
           delay_s: float = 0.0, keep_bytes: Optional[int] = None,
           flip_at: Optional[int] = None, docs=None,
           times: Optional[int] = None) -> Fault:
    """Arm one fault.  Returns the Fault (its ``fired`` counter is live)."""
    f = Fault(
        site=site, action=action, exc=exc, exc_factory=exc_factory,
        delay_s=delay_s, keep_bytes=keep_bytes, flip_at=flip_at,
        docs=frozenset(docs) if docs is not None else None, times=times,
    )
    with _lock:
        _faults.setdefault(site, []).append(f)
    return f


def clear(site: Optional[str] = None) -> None:
    with _lock:
        if site is None:
            _faults.clear()
        else:
            _faults.pop(site, None)


def active() -> Dict[str, int]:
    """Armed (non-exhausted) fault counts per site — the conftest
    leak guard's view."""
    with _lock:
        out = {}
        for site, fs in _faults.items():
            n = sum(1 for f in fs if f.times is None or f.fired < f.times)
            if n:
                out[site] = n
        return out


def fired(site: str) -> int:
    with _lock:
        return sum(f.fired for f in _faults.get(site, ()))


# actions that only have an effect where bytes flow (mangle); check()
# must leave them armed so a site instrumented with BOTH calls — e.g.
# replication's ``repl_ship`` (check before the read, mangle on the
# streamed bytes) — delivers them to the mangle that can apply them
_MANGLE_ACTIONS = ("truncate", "bitflip", "poison")


def _take(site: str, doc: Optional[int] = None,
          skip_mangle: bool = False) -> Optional[Fault]:
    """First armed fault at `site` that matches `doc`; ticks counters.

    Disarmed fast path: with the env parsed and no faults in the
    table, return without touching the lock — production ingest calls
    mangle() once per doc per round and must pay ~nothing when
    LORO_FAULT is unset (reading a dict's truthiness is atomic in
    CPython)."""
    if _env_loaded and not _faults:
        return None
    _load_env()
    with _lock:
        for f in _faults.get(site, ()):
            if f.times is not None and f.fired >= f.times:
                continue
            if f.docs is not None and (doc is None or doc not in f.docs):
                continue
            if skip_mangle and f.action in _MANGLE_ACTIONS:
                continue
            f.fired += 1
            _obs.counter("faultinject.fired_total").inc(site=site, action=f.action)
            return f
    return None


def _hang_delay(f: Fault) -> float:
    """A 'hang' with no explicit delay must actually hang (clamped to
    the 60s safety cap), not no-op — a vacuous hang fault would let
    every init-hang degradation test pass without exercising anything."""
    return min(f.delay_s, 60.0) if f.delay_s > 0 else 60.0


def check(site: str, doc: Optional[int] = None, **ctx) -> bool:
    """Called at instrumented sites.  Raises / sleeps per the armed
    fault; returns True iff a fault fired (False = clean pass).
    Mangle-class faults (truncate/bitflip/poison) are left armed for
    the site's ``mangle()`` call — check can't apply them."""
    f = _take(site, doc, skip_mangle=True)
    if f is None:
        return False
    if f.action in ("delay", "hang"):
        _do_sleep(_hang_delay(f) if f.action == "hang" else f.delay_s)
        return True
    if f.action == "raise":
        if f.exc_factory is not None:
            raise f.exc_factory()
        raise (f.exc if f.exc is not None else InjectedFault(
            f"UNAVAILABLE: injected fault at {site}"))
    return True  # truncate/bitflip/poison fire through mangle()


def mangle(site: str, payload, doc: Optional[int] = None):
    """Corrupt wire bytes at an instrumented decode site.  Non-bytes
    payloads and clean passes come back unchanged."""
    if not isinstance(payload, (bytes, bytearray)):
        return payload
    f = _take(site, doc)
    if f is None:
        return payload
    b = bytes(payload)
    if f.action == "truncate":
        keep = f.keep_bytes if f.keep_bytes is not None else len(b) // 2
        return b[: max(0, min(keep, len(b)))]
    if f.action in ("bitflip", "poison"):
        if not b:
            return b
        at = f.flip_at if f.flip_at is not None else len(b) // 2
        at = max(0, min(at, len(b) - 1))
        return b[:at] + bytes([b[at] ^ 0x5A]) + b[at + 1:]
    if f.action == "raise":
        if f.exc_factory is not None:
            raise f.exc_factory()
        raise (f.exc if f.exc is not None else InjectedFault(
            f"UNAVAILABLE: injected fault at {site}"))
    if f.action in ("delay", "hang"):
        _do_sleep(_hang_delay(f) if f.action == "hang" else f.delay_s)
    return b


# -- env wiring (LORO_FAULT) -------------------------------------------
def _load_env() -> None:
    """Parse LORO_FAULT once per process (probe subprocesses and CI
    runs arm faults without touching Python)."""
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
    spec = os.environ.get("LORO_FAULT", "").strip()
    if spec:
        for entry in spec.replace(",", ";").split(";"):
            entry = entry.strip()
            if entry:
                try:
                    _install_env_entry(entry)
                except Exception:  # tpulint: disable=LT-EXC(a typo'd LORO_FAULT spec must not take the process down)
                    pass


def _install_env_entry(entry: str) -> None:
    parts = entry.split(":")
    site = parts[0]
    action = parts[1] if len(parts) > 1 else "raise"
    kw: dict = {}
    base, _, val = action.partition("=")
    if base == "truncate":
        kw["keep_bytes"] = int(val) if val else None
    elif base == "bitflip":
        kw["flip_at"] = int(val) if val else None
    for p in parts[2:]:
        k, _, v = p.partition("=")
        if k == "times":
            kw["times"] = int(v)
        elif k in ("s", "delay"):
            kw["delay_s"] = float(v)
        elif k == "msg":
            kw["exc"] = InjectedFault(v)
        elif k == "docs":
            kw["docs"] = frozenset(int(x) for x in v.split("+") if x)
    inject(site, action=base, **kw)


def _reset_env_cache_for_tests() -> None:
    global _env_loaded
    _env_loaded = False
