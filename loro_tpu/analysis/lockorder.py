"""Declared partial lock order for the threaded fleet planes.

PRs 5–8 grew four interacting thread planes — PipelinedIngest
stage/commit, ShardedPipeline fan-out/collector, the sync FanIn, and
epoch subscribers — whose locks nest across module boundaries.  This
module is the single written-down order; the runtime witness
(``lockwitness.py``) checks every observed acquisition against it and
the static rule LT-LOCK flags inverted ``with`` nestings at lint time.

Levels are OUTERMOST-FIRST: a thread holding lock A may acquire lock B
iff ``level(A) < level(B)`` (or the pair is explicitly allowed).
Same-name reentrant acquisition (RLocks) is always allowed.  Locks not
named here (obs registry, native decoder, tracing, faultinject — all
strict leaves that call nothing while held) are outside the witness on
purpose; add them the day they stop being leaves.

The order, with the paths that establish each edge:

- ``repl.follower``    — replication Follower catch_up/promote RLock
  (loro_tpu/replication/follower.py), the outermost spine of the
  standby plane: one pass holds it across the shipped-round replay
  (→ ``fleet.dev``/``supervisor.state`` through the resident) and the
  read-only sync feed (→ ``sync.server`` → ``sync.readplane``).
  Nothing acquires it while holding anything below.
- ``net.accept``       — NetServer connection registry + pending-poll
  slots (loro_tpu/net/server.py): taken from the asyncio loop thread
  (accept/teardown), the notifier thread (claim a pending poll, then
  RELEASE before ``session.poll`` → ``sync.server``) and the acker
  thread (report snapshots).  Nothing is held while acquiring it, and
  every session call under it is made AFTER release — the declared
  edge net.accept→sync.server exists only for the teardown path that
  snapshots the registry before disconnecting sessions.
- ``sync.server``      — SyncServer session/oracle lock; a root for
  everything below: _commit_batch submits to the pipeline BEFORE
  taking it and epoch subscribers are lock-free by contract.  The
  read batcher's degraded-window fallback acquires it from a bare
  worker (queue and plane locks RELEASED), so nothing below ever
  holds while acquiring it.
- ``sync.readbatch``   — ReadBatcher pull queue/cv (sync/readbatch.
  py); sessions submit under ``sync.server`` (server→readbatch), the
  window worker drains it then RELEASES before touching the plane.
- ``sync.readplane``   — read-plane index + changelog; the commit
  path feeds it under ``sync.server`` (server→readplane), the window
  worker holds it across the selection launch (readplane→fleet.dev).
- ``fanin.queue``      — FanIn intake; the drain worker runs the
  commit callback with it RELEASED, so it orders before everything the
  callback touches.
- ``sharded.route``    — ShardedResidentServer placement/routing
  RLock; held across per-shard fan-out (→ pipeline/collect/dev/epoch).
- ``sharded.collect``  — ShardedPipeline collector queue
  (route→collect in submit()).
- ``pipeline.queue``   — PipelinedIngest queue/cv (route→queue when a
  sharded submit feeds per-shard pipes; stage/commit workers run
  server calls with it RELEASED).
- ``residency.plan``   — TieredBatch/ResidencyManager tier state
  (parallel/residency.py): held across revive landings and slot
  releases, which acquire the device lock beneath it (plan→dev); the
  pipeline workers call the tiered server with ``pipeline.queue``
  released, and a sharded fan-out reaches it under ``sharded.route``
  (route→…→plan→dev).
- ``fleet.dev``        — per-batch device RLock (serializes grow vs
  in-flight commit; wraps supervised launches).
- ``sharded.epoch``    — the global epoch/_EpochMap lock
  (route→dev→…→epoch on every fleet commit).
- ``supervisor.state`` — DeviceSupervisor counters; a strict leaf
  under every launch (dev→supervisor).
- ``obs.health``       — heat accountant + health plane state
  (obs/heat.py, obs/health.py): ``heat.tick_doc/tick_shard`` is called
  from the serving hot paths while their locks are held
  (sync.server→…→health, residency.plan→health, sharded.route→health)
  and the accountant calls nothing while holding it; the health
  plane's detector/ring mutations share the level and may
  ``flight.record`` beneath it (health→obs.flight), while attachment
  ``report()`` calls and registry sampling run with it RELEASED.
- ``obs.flight``       — the flight-recorder ring (obs/flight.py); the
  innermost level by construction: ``flight.record()`` is called from
  every plane (WAL appends, supervised launches, commit hooks) while
  their locks are held, and the recorder calls nothing while holding
  it (a thread-local reentrancy guard drops nested records, so even
  the lock witness observing this lock cannot re-enter it).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Set, Tuple

LEVELS: Dict[str, int] = {
    "repl.follower": 5,
    "net.accept": 8,
    "sync.server": 10,
    "sync.readbatch": 14,
    "sync.readplane": 16,
    "fanin.queue": 20,
    "sharded.route": 30,
    "sharded.collect": 40,
    "pipeline.queue": 50,
    "residency.plan": 55,
    "fleet.dev": 60,
    "sharded.epoch": 70,
    "supervisor.state": 80,
    "obs.health": 85,
    "obs.flight": 90,
}

# explicitly-allowed extra edges that the pure level order forbids —
# each entry carries its justification in a comment.  Empty today:
# keep it that way unless a post-mortem proves an edge safe.
ALLOWED_EXTRA: Set[Tuple[str, str]] = set()

# attribute-name -> lock-name map for the STATIC rule (LT-LOCK).  Only
# attributes whose name is unambiguous across the codebase belong
# here; generic `_lock`/`_cv` attributes are witnessed at runtime
# instead (their identity depends on the owning class).
STATIC_ATTR_LOCKS: Dict[str, str] = {
    "_dev_lock": "fleet.dev",
    "_route_lock": "sharded.route",
    "_epoch_lock": "sharded.epoch",
    "_plan_lock": "residency.plan",
}


def level(name: str):
    return LEVELS.get(name)


def allowed(outer: str, inner: str) -> bool:
    """May a thread holding ``outer`` acquire ``inner``?  Unknown lock
    names are permitted (the witness records them; the declaration
    only constrains the names it knows)."""
    if outer == inner:
        return True  # reentrant
    if (outer, inner) in ALLOWED_EXTRA:
        return True
    lo, li = LEVELS.get(outer), LEVELS.get(inner)
    if lo is None or li is None:
        return True
    return lo < li


def check_edges(edges: Iterable[Tuple[str, str]]) -> List[str]:
    """Violation strings for every witnessed edge the declaration
    forbids (empty = conformant)."""
    out = []
    for a, b in edges:
        if not allowed(a, b):
            out.append(
                f"{a!r} (level {LEVELS.get(a)}) held while acquiring "
                f"{b!r} (level {LEVELS.get(b)}) — declared order forbids it"
            )
    return out


def find_cycle(edges: Iterable[Tuple[str, str]]):
    """A witnessed-lock-graph cycle as a node list (closed: first ==
    last), or None.  Any cycle — declared locks or not — is a latent
    deadlock."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        if a != b:
            adj.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(adj) | {b for bs in adj.values() for b in bs}}
    stack: List[str] = []

    def dfs(n: str):
        color[n] = GREY
        stack.append(n)
        for m in adj.get(n, ()):
            if color[m] == GREY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        color[n] = BLACK
        stack.pop()
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None
