"""tpulint core: findings, the rule registry, pragmas, the baseline.

A *rule* is a function over one parsed module (``ModuleSource``) that
yields ``Finding``s.  Rules declare their own path *scope* — the
project invariants are path-shaped (pad-bucket applies to fleet
paths, keyed-hash to placement/wire paths, device-routing to
everything EXCEPT the blessed kernel modules) — so a rule never fires
where its post-mortem does not apply, and the scope is documented per
rule in docs/ANALYSIS.md rather than hidden in pragma noise.

Suppression is per line and must carry a reason::

    except Exception:  # tpulint: disable=LT-EXC(subscriber isolation)

A pragma on its own line suppresses the NEXT line (for statements that
do not fit a trailing comment).  A reasonless or unknown-rule pragma
does not suppress anything and is itself reported (rule LT-PRAGMA) —
"every suppression carries a reason" is enforced, not hoped for.

The *baseline* (``baseline.json`` next to this file, or ``--baseline``)
tolerates known findings by ``(rule, path, stripped source line)`` so
line drift does not churn it; the checked-in baseline is empty — it
exists so a future emergency landing can be staged, not so debt can
hide.
"""
from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# a pragma may share its comment with other markers (noqa, prose), so
# the marker is matched anywhere inside the comment text
PRAGMA_RE = re.compile(r"#.*?tpulint:\s*disable=(.*)$")
# one pragma entry: RULE-ID(reason...)  — reason runs to the matching
# close paren (no nesting needed in practice; greedy-to-last-paren
# keeps parenthesised prose intact)
ENTRY_RE = re.compile(r"(LT-[A-Z]+)\s*(?:\((.*?)\))?\s*(?:,|$)")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    source_line: str = ""
    suppressed: bool = False
    reason: str = ""
    baselined: bool = False

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: survives line-number drift."""
        return (self.rule, self.path, self.source_line.strip())

    def to_json(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.suppressed:
            d["suppressed"] = True
            d["reason"] = self.reason
        if self.baselined:
            d["baselined"] = True
        return d

    def render(self) -> str:
        tag = ""
        if self.suppressed:
            tag = f"  [suppressed: {self.reason}]"
        elif self.baselined:
            tag = "  [baselined]"
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} {self.message}{tag}"
        )


class ModuleSource:
    """One parsed module: path (repo-relative, posix), source, AST."""

    def __init__(self, path: str, source: str):
        self.path = path.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)

    def line(self, n: int) -> str:
        return self.lines[n - 1] if 0 < n <= len(self.lines) else ""


@dataclass
class Rule:
    id: str
    name: str
    summary: str
    post_mortem: str
    scope: Callable[[str], bool]
    check: Callable[[ModuleSource], Iterable[Finding]] = field(repr=False,
                                                              default=None)


_RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Callable:
    """Decorator: attach a check function to ``rule`` and register it."""
    def deco(fn: Callable[[ModuleSource], Iterable[Finding]]):
        rule.check = fn
        if rule.id in _RULES:
            raise ValueError(f"duplicate rule id {rule.id}")
        _RULES[rule.id] = rule
        return fn
    return deco


def all_rules() -> List[Rule]:
    # import side effect: the rule definitions live in rules.py
    from . import rules  # noqa: F401

    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str) -> Rule:
    from . import rules  # noqa: F401

    return _RULES[rule_id]


def known_rule_ids() -> List[str]:
    from . import rules  # noqa: F401

    return sorted(_RULES) + ["LT-PRAGMA"]


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


def parse_pragmas(mod: ModuleSource) -> Tuple[Dict[int, Dict[str, str]],
                                              List[Finding]]:
    """Per-line suppression map ``{line: {rule_id: reason}}`` plus the
    LT-PRAGMA findings for malformed pragmas (no reason / unknown
    rule).  A pragma on a line whose code is only the comment applies
    to the next line."""
    import io
    import tokenize

    supp: Dict[int, Dict[str, str]] = {}
    bad: List[Finding] = []
    ids = set(known_rule_ids())
    # real COMMENT tokens only: a pragma example inside a docstring or
    # string literal is prose, not a suppression
    comments: List[Tuple[int, int, str]] = []  # (line, col, text)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(mod.source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass  # unparseable tails: the ast parse already succeeded
    for i, col, text in comments:
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        target = i
        if mod.line(i)[:col].strip() == "":
            target = i + 1  # comment-only line: suppress the next line
        entries = list(ENTRY_RE.finditer(m.group(1)))
        if not entries:
            bad.append(Finding(
                "LT-PRAGMA", mod.path, i, col + m.start() + 1,
                "unparseable tpulint pragma (expected "
                "disable=LT-RULE(reason))", source_line=mod.line(i),
            ))
            continue
        for e in entries:
            rid, reason = e.group(1), (e.group(2) or "").strip()
            if rid not in ids:
                bad.append(Finding(
                    "LT-PRAGMA", mod.path, i, col + m.start() + 1,
                    f"pragma names unknown rule {rid!r}", source_line=mod.line(i),
                ))
                continue
            if not reason:
                bad.append(Finding(
                    "LT-PRAGMA", mod.path, i, col + m.start() + 1,
                    f"pragma for {rid} carries no reason — every "
                    "suppression must say why", source_line=mod.line(i),
                ))
                continue  # reasonless pragma does NOT suppress
            supp.setdefault(target, {})[rid] = reason
    return supp, bad


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """``{(rule, path, line_text): allowance}`` from a baseline file;
    empty when the file does not exist."""
    import os

    if not os.path.isfile(path):
        return {}
    with open(path, "r") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for row in data.get("findings", []):
        k = (row["rule"], row["path"], row["line_text"])
        out[k] = out.get(k, 0) + int(row.get("count", 1))
    return out


def baseline_payload(findings: List[Finding]) -> dict:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    return {
        "comment": "tpulint baseline: tolerated findings by "
                   "(rule, path, stripped line). Keep this EMPTY; it "
                   "exists for staged emergency landings only.",
        "findings": [
            {"rule": r, "path": p, "line_text": t, "count": n}
            for (r, p, t), n in sorted(counts.items())
        ],
    }


# ---------------------------------------------------------------------------
# result
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]          # everything, suppressed included
    files: int = 0

    @property
    def active(self) -> List[Finding]:
        """Unsuppressed, unbaselined — what fails the build."""
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def baselined(self) -> List[Finding]:
        return [f for f in self.findings if f.baselined]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.active:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "version": 1,
            "files": self.files,
            "active": [f.to_json() for f in self.active],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "counts": self.counts(),
            "ok": not self.active,
        }


# ---------------------------------------------------------------------------
# shared AST helpers (used by rules.py)
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ImportMap(ast.NodeVisitor):
    """name -> dotted module/object path, from import statements."""

    def __init__(self, tree: ast.AST):
        self.names: Dict[str, str] = {}
        self.visit(tree)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.names[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is None or node.level:
            return  # relative imports never alias jax/time/etc
        for a in node.names:
            self.names[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Fully-resolved dotted path of an expression, through the
        module's import aliases (``jnp.zeros`` -> ``jax.numpy.zeros``)."""
        d = dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        base = self.names.get(head)
        if base is None:
            return d
        return f"{base}.{rest}" if rest else base
