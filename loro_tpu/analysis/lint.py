"""tpulint engine + CLI: ``python -m loro_tpu.analysis.lint [paths...]``.

Runs the rule catalogue (``rules.py``) over the given files/dirs,
applies per-line pragmas and the baseline, and exits non-zero on any
active finding.  Pure stdlib — no jax import — so it runs in
milliseconds as a pre-commit hook or the tier-1 gate test.

    python -m loro_tpu.analysis.lint loro_tpu bench.py
    python -m loro_tpu.analysis.lint --format=json loro_tpu
    python -m loro_tpu.analysis.lint --write-baseline loro_tpu bench.py

Every active finding feeds the obs registry
(``analysis.findings_total{rule=...}`` / ``analysis.suppressed_total``)
so lint health rides the same metrics sidecar as everything else.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from .core import (
    Finding,
    LintResult,
    ModuleSource,
    all_rules,
    baseline_payload,
    load_baseline,
    parse_pragmas,
)

# repo root = parent of the loro_tpu package: scope predicates match
# repo-relative posix paths ("loro_tpu/sync/server.py", "bench.py")
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def _relpath(path: str) -> str:
    """Repo-relative posix path for scope matching.  Files outside the
    repo root re-anchor at their last ``loro_tpu`` component (or a
    ``bench.py`` basename) so linting a DIFFERENT checkout of this
    project still applies every rule — a silent all-scopes-miss
    "clean" on a foreign tree would be worse than any finding."""
    ap = os.path.abspath(path)
    try:
        rel = os.path.relpath(ap, _REPO_ROOT)
    except ValueError:  # different drive (windows)
        rel = path
    if rel.startswith(".."):
        parts = ap.replace(os.sep, "/").split("/")
        if "loro_tpu" in parts:
            last = len(parts) - 1 - parts[::-1].index("loro_tpu")
            return "/".join(parts[last:])
        if parts[-1] == "bench.py":
            return "bench.py"
        rel = path
    return rel.replace(os.sep, "/")


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_source(source: str, path: str = "loro_tpu/_memory.py",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one in-memory module (fixture tests).  ``path`` selects the
    rule scopes that apply.  Returns ALL findings, suppressed ones
    flagged — no baseline."""
    mod = ModuleSource(path, source)
    supp, bad_pragmas = parse_pragmas(mod)
    findings: List[Finding] = list(bad_pragmas)
    for rule in all_rules():
        if rules is not None and rule.id not in rules:
            continue
        if not rule.scope(mod.path):
            continue
        for f in rule.check(mod):
            reason = supp.get(f.line, {}).get(f.rule)
            if reason is not None:
                f.suppressed = True
                f.reason = reason
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(paths: Sequence[str], rules: Optional[Sequence[str]] = None,
               baseline_path: Optional[str] = None) -> LintResult:
    """Lint files/dirs.  ``baseline_path=None`` uses the checked-in
    default when present; pass "" to disable the baseline."""
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE
    baseline = load_baseline(baseline_path) if baseline_path else {}
    budget = dict(baseline)
    findings: List[Finding] = []
    files = 0
    for fp in iter_py_files(paths):
        with open(fp, "r", encoding="utf-8") as f:
            src = f.read()
        files += 1
        for fnd in lint_source(src, path=_relpath(fp), rules=rules):
            if not fnd.suppressed and budget.get(fnd.key(), 0) > 0:
                budget[fnd.key()] -= 1
                fnd.baselined = True
            findings.append(fnd)
    res = LintResult(findings=findings, files=files)
    _feed_obs(res)
    return res


def _feed_obs(res: LintResult) -> None:
    try:
        from ..obs import metrics as obs

        for rule, n in res.counts().items():
            obs.counter(
                "analysis.findings_total",
                "active tpulint findings by rule",
            ).inc(n, rule=rule)
        for f in res.suppressed:
            obs.counter(
                "analysis.suppressed_total",
                "pragma-suppressed tpulint findings by rule",
            ).inc(rule=f.rule)
        for f in res.baselined:
            obs.counter(
                "analysis.baselined_total",
                "baseline-tolerated tpulint findings by rule",
            ).inc(rule=f.rule)
    except Exception:  # tpulint: disable=LT-EXC(lint must work without the obs package, e.g. vendored)
        pass


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m loro_tpu.analysis.lint",
        description="project-invariant static analysis (docs/ANALYSIS.md)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: loro_tpu bench.py)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "analysis/baseline.json; pass '' to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current active findings as the baseline")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:10s} {r.name}: {r.summary}")
        return 0

    paths = args.paths or [
        os.path.join(_REPO_ROOT, "loro_tpu"),
        os.path.join(_REPO_ROOT, "bench.py"),
    ]
    rules = args.rules.split(",") if args.rules else None
    res = lint_paths(paths, rules=rules, baseline_path=args.baseline)

    if args.write_baseline:
        out = args.baseline or DEFAULT_BASELINE
        with open(out, "w") as f:
            json.dump(baseline_payload(res.active), f, indent=1)
            f.write("\n")
        print(f"baseline: {len(res.active)} finding(s) -> {out}")
        return 0

    if args.format == "json":
        print(json.dumps(res.to_json(), indent=1))
    else:
        for f in res.findings:
            if not f.suppressed:
                print(f.render())
        counts = res.counts()
        summary = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        print(
            f"tpulint: {len(res.active)} active finding(s) in {res.files} "
            f"file(s) ({summary or 'clean'}); "
            f"{len(res.suppressed)} suppressed, {len(res.baselined)} baselined"
        )
    return 1 if res.active else 0


if __name__ == "__main__":
    sys.exit(main())
