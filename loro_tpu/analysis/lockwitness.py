"""Runtime lock-order witness: named locks + the acquisition graph.

The threaded fleet planes construct their locks through
``named_lock(name)`` / ``named_rlock(name)`` instead of bare
``threading.Lock()``.  The wrapper is inert by default (one module
flag check per acquire — the hot callers are per-round, never per-op);
``witness().enable()`` (or ``LORO_LOCK_WITNESS=1``) turns on
recording:

- every acquisition taken while other named locks are held records an
  edge ``held -> acquired`` into a process-global graph, keyed by lock
  NAME (all ``fleet.dev`` batch locks are one node — the order is a
  property of the code paths, not the instances);
- ``check_declared()`` verifies every edge against the declared
  partial order in ``lockorder.py``; ``assert_acyclic()`` proves
  deadlock freedom of the witnessed graph (any cycle is a latent
  deadlock, declared or not); ``enable(strict=True)`` raises typed
  ``errors.LockOrderViolation`` AT the offending acquire (tests);
- ``dump(path)`` writes the witnessed graph as a JSON artifact.

The wrapper implements the private ``_release_save`` /
``_acquire_restore`` / ``_is_owned`` protocol, so
``threading.Condition(named_lock(...))`` works for both Lock and RLock
and the witness stays consistent across ``wait()`` (a wait fully
releases the lock; the bookkeeping follows).

Reentrant same-name acquisition never records an edge: two different
``fleet.dev`` instances nested would be a same-name self-edge, which
the sequential per-shard loops legitimately produce — cross-NAME order
is what deadlocks are made of here.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import LockOrderViolation
from . import lockorder


class _Held(threading.local):
    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.order: List[str] = []  # acquisition order, distinct names


class LockWitness:
    """Process-global acquisition graph + enable/strict switches."""

    def __init__(self):
        self._glock = threading.Lock()
        self.enabled = False
        self.strict = False
        self._edges: Dict[Tuple[str, str], int] = {}
        self._first_thread: Dict[Tuple[str, str], str] = {}
        self._violations: List[str] = []
        self._held = _Held()

    # -- lifecycle -----------------------------------------------------
    def enable(self, strict: bool = False) -> None:
        with self._glock:
            self.enabled = True
            self.strict = strict

    def disable(self) -> None:
        with self._glock:
            self.enabled = False
            self.strict = False

    def reset(self) -> None:
        with self._glock:
            self._edges.clear()
            self._first_thread.clear()
            self._violations.clear()

    # -- recording (called from NamedLock with the lock HELD) ----------
    def note_acquire(self, name: str) -> None:
        held = self._held
        if held.counts.get(name, 0):
            held.counts[name] += 1
            return  # reentrant: no edge, no order change
        new_edges: List[Tuple[str, str]] = []
        bad: List[str] = []
        for h in held.order:
            if h != name:
                new_edges.append((h, name))
                if not lockorder.allowed(h, name):
                    bad.append(
                        f"{h!r} held while acquiring {name!r} "
                        f"(thread {threading.current_thread().name})"
                    )
        held.counts[name] = 1
        held.order.append(name)
        if new_edges or bad:
            tname = threading.current_thread().name
            first: List[Tuple[str, str]] = []
            with self._glock:
                for e in new_edges:
                    n = self._edges.get(e, 0)
                    if n == 0:
                        first.append(e)
                    self._edges[e] = n + 1
                    self._first_thread.setdefault(e, tname)
                self._violations.extend(bad)
            self._obs_update(len(bad))
            # flight recorder: first-seen edges are rare, structural
            # events — exactly what a post-mortem wants.  Fired OUTSIDE
            # _glock; the recorder's reentrancy guard drops the nested
            # record its own lock acquisition would otherwise produce.
            if first or bad:
                try:
                    from ..obs import flight

                    for a, b in first:
                        flight.record("lock.edge", held=a, acquired=b)
                    for msg in bad:
                        flight.record("lock.violation", detail=msg)
                except Exception:  # tpulint: disable=LT-EXC(the flight ring must never break a lock acquire)
                    pass
        if bad and self.strict:
            raise LockOrderViolation("; ".join(bad))

    def note_release(self, name: str) -> None:
        held = self._held
        n = held.counts.get(name, 0)
        if n > 1:
            held.counts[name] = n - 1
        elif n == 1:
            del held.counts[name]
            try:
                held.order.remove(name)
            except ValueError:
                pass
        # n == 0: enable() happened mid-hold; nothing to unwind

    def note_release_all(self, name: str) -> int:
        """Condition.wait path: the lock is fully released regardless
        of recursion depth.  Returns the count to restore."""
        held = self._held
        n = held.counts.pop(name, 0)
        if n:
            try:
                held.order.remove(name)
            except ValueError:
                pass
        return n

    def note_acquire_restore(self, name: str, count: int) -> None:
        held = self._held
        if count:
            held.counts[name] = count
            held.order.append(name)

    def _obs_update(self, new_violations: int) -> None:
        try:
            from ..obs import metrics as obs

            obs.gauge(
                "analysis.witness_edges",
                "distinct witnessed lock-order edges",
            ).set(len(self._edges))
            if new_violations:
                obs.counter(
                    "analysis.lock_order_violations_total",
                    "witnessed acquisitions the declared order forbids",
                ).inc(new_violations)
        except Exception:  # tpulint: disable=LT-EXC(metrics must never break a lock acquire)
            pass

    # -- reads ---------------------------------------------------------
    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._glock:
            return dict(self._edges)

    def violations(self) -> List[str]:
        with self._glock:
            return list(self._violations)

    def check_declared(self) -> List[str]:
        """Every witnessed edge checked against lockorder.LEVELS."""
        return lockorder.check_edges(self.edges())

    def assert_acyclic(self) -> None:
        cyc = lockorder.find_cycle(self.edges())
        if cyc is not None:
            raise LockOrderViolation(
                "witnessed lock graph has a cycle (latent deadlock): "
                + " -> ".join(cyc)
            )

    def dump(self, path: Optional[str] = None) -> str:
        """Write the witnessed graph artifact; returns the path."""
        if path is None:
            path = os.environ.get("LORO_LOCK_WITNESS_DUMP",
                                  ".lockwitness.json")
        with self._glock:
            data = {
                "levels": dict(lockorder.LEVELS),
                "edges": [
                    {"from": a, "to": b, "count": n,
                     "first_thread": self._first_thread.get((a, b), "")}
                    for (a, b), n in sorted(self._edges.items())
                ],
                "violations": list(self._violations),
            }
        data["cycle"] = lockorder.find_cycle(
            (e["from"], e["to"]) for e in data["edges"]
        )
        with open(path, "w") as f:
            json.dump(data, f, indent=1)
        return path


_witness = LockWitness()


def witness() -> LockWitness:
    return _witness


class NamedLock:
    """A threading.Lock/RLock with a witness name.  API-compatible as a
    context manager, via acquire/release, and as the lock of a
    ``threading.Condition`` (the private protocol below)."""

    __slots__ = ("name", "_lk", "_reentrant")

    def __init__(self, name: str, lock, reentrant: bool):
        self.name = name
        self._lk = lock
        self._reentrant = reentrant

    def __repr__(self) -> str:
        return f"<NamedLock {self.name} {self._lk!r}>"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok and _witness.enabled:
            try:
                _witness.note_acquire(self.name)
            except BaseException:
                # strict-mode violation: leave the system consistent —
                # undo the bookkeeping AND the physical acquire before
                # surfacing the typed error
                _witness.note_release(self.name)
                self._lk.release()
                raise
        return ok

    def release(self) -> None:
        # unwind by RECORDED state, not the enabled flag: disabling the
        # witness while a worker thread is mid-critical-section must
        # not leak the name into its held-set forever (note_release is
        # a no-op when nothing was recorded)
        _witness.note_release(self.name)
        self._lk.release()

    def __enter__(self) -> "NamedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- threading.Condition private protocol --------------------------
    def _release_save(self):
        cnt = _witness.note_release_all(self.name)  # no-op when unrecorded
        if self._reentrant:
            state = self._lk._release_save()
        else:
            self._lk.release()
            state = None
        return (state, cnt)

    def _acquire_restore(self, saved) -> None:
        state, cnt = saved
        if self._reentrant:
            self._lk._acquire_restore(state)
        else:
            self._lk.acquire()
        if _witness.enabled:
            _witness.note_acquire_restore(self.name, max(cnt, 1))

    def _is_owned(self) -> bool:
        if self._reentrant:
            return self._lk._is_owned()
        # plain-lock emulation (CPython Condition fallback)
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True


def named_lock(name: str) -> NamedLock:
    return NamedLock(name, threading.Lock(), reentrant=False)


def named_rlock(name: str) -> NamedLock:
    return NamedLock(name, threading.RLock(), reentrant=True)


if os.environ.get("LORO_LOCK_WITNESS", "") == "1":
    _witness.enable()
