"""Project-invariant static analysis + runtime lock-order witness.

The repo's survival rules used to live only as prose (CLAUDE.md tunnel
post-mortems, the pad-bucket jit-cache invariant, "every device call
routes through DeviceSupervisor", keyed-blake2b-never-``hash()``
placement, the typed-error discipline).  This package turns them into
CI failures instead of post-mortems:

- **tpulint** (``python -m loro_tpu.analysis.lint loro_tpu bench.py``):
  an AST-based rule registry (``rules.py``) with per-line
  ``# tpulint: disable=RULE(reason)`` pragmas and a checked-in
  baseline; the tier-1 gate in tests/test_analysis.py fails on any
  unsuppressed finding, so every future PR inherits the discipline.
- **lock witness** (``lockwitness.py``): the named-lock wrapper the
  threaded fleet planes (PipelinedIngest, ShardedResidentServer,
  FanIn, SyncServer, DeviceSupervisor, the batch device locks) build
  their locks through.  Enabled under tests it records the runtime
  lock-acquisition graph, asserts it acyclic and conformant to the
  declared partial order in ``lockorder.py``, and dumps the witnessed
  graph as an artifact.

Everything here is pure stdlib (no jax import) so the linter runs in
milliseconds anywhere, including pre-commit hooks.
"""
# lazy exports: `python -m loro_tpu.analysis.lint` must not import the
# submodule at package-import time (runpy double-import warning), and
# lock adopters importing lockwitness must not pull the lint engine in
_EXPORTS = {
    "Finding": "core", "LintResult": "core", "Rule": "core",
    "all_rules": "core", "get_rule": "core",
    "lint_paths": "lint", "lint_source": "lint",
    "LockWitness": "lockwitness", "named_lock": "lockwitness",
    "named_rlock": "lockwitness", "witness": "lockwitness",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
