"""The tpulint rule catalogue: the repo's survival rules as AST checks.

Every rule encodes one post-mortem or load-bearing invariant that used
to live only as prose (CLAUDE.md / docs/RESILIENCE.md).  Scopes are
path-shaped on purpose: a rule fires exactly where its invariant
applies, and the blessed-module lists below ARE the documentation of
where the device layer is allowed to live.  docs/ANALYSIS.md carries
the full catalogue with the story behind each rule.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, ImportMap, ModuleSource, Rule, dotted, register
from .lockorder import STATIC_ATTR_LOCKS, allowed

# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------

# the device layer: the only modules allowed to touch jax launch/fetch
# entry points directly.  Everything else must route through
# resilience.DeviceSupervisor (fleet's _sup_launch/_sup_fetch) so the
# drain budget, retry/backoff and typed DeviceFailure degradation hold
# on every path.
DEVICE_BLESSED = (
    "loro_tpu/ops/",
    "loro_tpu/parallel/fleet.py",
    "loro_tpu/parallel/mesh.py",
    "loro_tpu/resilience/",
)

# jax entry points that launch device work, allocate on device, or
# initialize the backend — the calls the supervisor exists to route.
# (jax.tree_util etc. are host-side and deliberately not listed.)
DEVICE_ENTRY_ATTRS = (
    "jit", "device_put", "device_get", "devices", "local_devices",
    "pallas_call", "pmap", "shard_map",
)


def _in(path: str, *prefixes: str) -> bool:
    return any(path.startswith(p) for p in prefixes)


def _pkg_only(path: str) -> bool:
    return path.startswith("loro_tpu/")


def _pkg_and_bench(path: str) -> bool:
    return path.startswith("loro_tpu/") or path == "bench.py"


# ---------------------------------------------------------------------------
# LT-DEV — device calls outside the supervisor routing / blessed modules
# ---------------------------------------------------------------------------


@register(Rule(
    id="LT-DEV",
    name="unsupervised device call",
    summary="jax launch/fetch entry points outside DeviceSupervisor "
            "routing or the blessed kernel modules",
    post_mortem="every Fleet/resident device call routes through "
                "resilience.DeviceSupervisor (drain budget, retry, typed "
                "DeviceFailure) — a stray launch bypasses the tunnel-"
                "safety rules and the degradation path (docs/RESILIENCE.md)",
    scope=lambda p: _pkg_only(p) and not _in(p, *DEVICE_BLESSED),
))
def check_device(mod: ModuleSource) -> Iterable[Finding]:
    imap = ImportMap(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        full = imap.resolve(node.func)
        if full is None or not full.startswith("jax"):
            continue
        if full.startswith("jax.numpy."):
            yield Finding(
                "LT-DEV", mod.path, node.lineno, node.col_offset + 1,
                f"{full.replace('jax.numpy', 'jnp')}() allocates/dispatches "
                "on device outside the blessed kernel modules — route the "
                "launch through resilience.DeviceSupervisor or move it into "
                "the device layer", source_line=mod.line(node.lineno),
            )
        elif full.split(".")[-1] in DEVICE_ENTRY_ATTRS:
            yield Finding(
                "LT-DEV", mod.path, node.lineno, node.col_offset + 1,
                f"{full}() is a device launch/backend entry point — only "
                "the blessed kernel modules call it directly; everything "
                "else goes through resilience.DeviceSupervisor "
                "(launch/guard/fetch)", source_line=mod.line(node.lineno),
            )


# ---------------------------------------------------------------------------
# LT-PAD — device-shape construction bypassing pad_bucket
# ---------------------------------------------------------------------------

_SHAPE_CTORS = ("zeros", "ones", "full", "empty")


def _has_raw_dynamic_dim(node: ast.AST) -> bool:
    """True when the (shape) expression contains a len(...) call or a
    ``.shape[...]`` subscript that is NOT wrapped in pad_bucket(...).
    Variables are invisible to this check on purpose — the lint flags
    the inline smoking gun, not every possible data flow."""
    # ancestor-aware walk: flag len()/.shape[...] nodes with no
    # pad_bucket call between them and the root
    stack = [(node, False)]
    while stack:
        cur, padded = stack.pop()
        if isinstance(cur, ast.Call):
            f = dotted(cur.func)
            if f == "pad_bucket" or (f or "").endswith(".pad_bucket"):
                padded = True
            elif not padded and isinstance(cur.func, ast.Name) \
                    and cur.func.id == "len":
                return True
        if not padded and isinstance(cur, ast.Subscript):
            if isinstance(cur.value, ast.Attribute) \
                    and cur.value.attr == "shape":
                return True
        for child in ast.iter_child_nodes(cur):
            stack.append((child, padded))
    return False


@register(Rule(
    id="LT-PAD",
    name="unbucketed device shape",
    summary="device-array construction (jnp.*, or np.* inline in a "
            "device_put) in fleet/serving paths from a raw len()/.shape[] "
            "size instead of pad_bucket",
    post_mortem="every distinct padded shape is a fresh jit compile — "
                "unbucketed DEVICE shapes explode the jit cache (the "
                "CLAUDE.md invariant; obs tracks cardinality as "
                "fleet.padded_shapes).  Host staging buffers are exempt: "
                "the invariant bites at the device boundary, where the "
                "existing paths all pad_bucket before device_put",
    scope=lambda p: _in(p, "loro_tpu/parallel/", "loro_tpu/ops/"),
))
def check_pad(mod: ModuleSource) -> Iterable[Finding]:
    imap = ImportMap(mod.tree)

    def ctor_path(call: ast.Call) -> str:
        full = imap.resolve(call.func) or ""
        return full if full.split(".")[-1] in _SHAPE_CTORS else ""

    def flag(call: ast.Call, full: str, where: str):
        return Finding(
            "LT-PAD", mod.path, call.lineno, call.col_offset + 1,
            f"{full.split('.')[-1]}() {where} shapes from a raw dynamic "
            "size (len()/.shape[]) — bucket it through pad_bucket() or "
            "the jit cache grows one entry per distinct size",
            source_line=mod.line(call.lineno),
        )

    inline_device = set()  # np-ctor calls inside a device_put argument
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                (imap.resolve(node.func) or "").endswith("device_put"):
            for arg in node.args[:1]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call) and ctor_path(sub):
                        inline_device.add(id(sub))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        full = ctor_path(node)
        if not full:
            continue
        if full.startswith("jax.numpy."):
            if _has_raw_dynamic_dim(node.args[0]):
                yield flag(node, full, "allocates on device and")
        elif id(node) in inline_device and _has_raw_dynamic_dim(node.args[0]):
            yield flag(node, full, "feeds device_put and")


# ---------------------------------------------------------------------------
# LT-HASH — builtin hash()/unseeded randomness in placement/wire paths
# ---------------------------------------------------------------------------

_RANDOM_FNS = (
    "random", "getrandbits", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "uniform", "random.seed",
)


@register(Rule(
    id="LT-HASH",
    name="non-deterministic hash/randomness",
    summary="builtin hash() or module-level random.* in placement, "
            "journaling or wire paths that require keyed blake2b / "
            "seeded RNGs",
    post_mortem="builtin hash() is salted per process (PYTHONHASHSEED): "
                "rendezvous placement, WAL framing or wire layouts keyed "
                "on it silently disagree across processes — placement uses "
                "keyed blake2b for exactly this (parallel/placement.py)",
    scope=lambda p: _in(
        p, "loro_tpu/parallel/placement.py", "loro_tpu/parallel/sharded.py",
        "loro_tpu/persist/", "loro_tpu/codec/", "loro_tpu/storage/",
        "loro_tpu/sync/", "loro_tpu/oplog/",
    ),
))
def check_hash(mod: ModuleSource) -> Iterable[Finding]:
    imap = ImportMap(mod.tree)
    # hash() inside __hash__ implementations is the language protocol,
    # not a placement decision
    hash_ok_ranges: List[range] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "__hash__":
            hash_ok_ranges.append(range(node.lineno, (node.end_lineno or
                                                      node.lineno) + 1))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id == "hash":
            if any(node.lineno in r for r in hash_ok_ranges):
                continue
            yield Finding(
                "LT-HASH", mod.path, node.lineno, node.col_offset + 1,
                "builtin hash() is process-salted — use keyed blake2b "
                "(parallel/placement.py idiom) for anything that must "
                "agree across runs/processes",
                source_line=mod.line(node.lineno),
            )
            continue
        full = imap.resolve(node.func) or ""
        if full.startswith("random.") and full != "random.Random" \
                and full.split(".")[-1] in _RANDOM_FNS:
            yield Finding(
                "LT-HASH", mod.path, node.lineno, node.col_offset + 1,
                f"{full}() draws from the process-global unseeded RNG — "
                "placement/journal/wire paths need deterministic bytes "
                "(keyed blake2b or an explicit random.Random(seed))",
                source_line=mod.line(node.lineno),
            )


# ---------------------------------------------------------------------------
# LT-TIME — wall clock in logic the fake-clock tests must control
# ---------------------------------------------------------------------------


@register(Rule(
    id="LT-TIME",
    name="uninjected wall clock",
    summary="time.time() in epoch/retry/TTL logic that must use the "
            "injected clock the fake-clock tests rely on",
    post_mortem="tier-1 never wall-sleeps: supervisor retry/backoff and "
                "TTL expiry run under injected clocks (DeviceSupervisor"
                "(clock=, sleep=)) — a raw time.time() site is untestable "
                "without real sleeps and drifts vs the fake clock",
    scope=_pkg_only,
))
def check_time(mod: ModuleSource) -> Iterable[Finding]:
    imap = ImportMap(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if (imap.resolve(node.func) or "") == "time.time":
            yield Finding(
                "LT-TIME", mod.path, node.lineno, node.col_offset + 1,
                "time.time() called directly — take an injectable "
                "clock (clock=time.time parameter, the DeviceSupervisor "
                "idiom) so fake-clock tests control it",
                source_line=mod.line(node.lineno),
            )


# ---------------------------------------------------------------------------
# LT-EXC — broad catches that swallow, and untyped error classes
# ---------------------------------------------------------------------------

_BUILTIN_EXC_BASES = {
    "Exception", "BaseException", "ValueError", "TypeError", "RuntimeError",
    "KeyError", "IndexError", "OSError", "IOError", "ArithmeticError",
}
_ERRORISH = ("Error", "Failure", "Rejected", "Exceeded", "Closed")


def _handler_swallows(h: ast.ExceptHandler) -> bool:
    """True when the handler body contains no raise: the error is
    swallowed rather than re-raised typed."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return False
    return True


def _catches_broad(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True  # bare except:
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        if isinstance(t, ast.Name) and t.id == "Exception":
            return True
    return False


@register(Rule(
    id="LT-EXC",
    name="untyped exception discipline",
    summary="except Exception that swallows (no raise in the handler) "
            "where the typed hierarchy in errors.py applies; error "
            "classes not rooted in LoroError",
    post_mortem="typed errors are the degradation contract: "
                "DeviceFailure -> host fallback, CodecDecodeError -> "
                "poison isolation, PushRejected -> per-ticket failure.  A "
                "silent broad catch eats the signal those paths key on",
    scope=_pkg_and_bench,
))
def check_exc(mod: ModuleSource) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler):
            if _catches_broad(node) and _handler_swallows(node):
                what = "bare except:" if node.type is None \
                    else "except Exception"
                yield Finding(
                    "LT-EXC", mod.path, node.lineno, node.col_offset + 1,
                    f"{what} swallows the error (no raise in the handler) "
                    "— catch the typed errors.py class that applies, or "
                    "pragma the genuine catch-all with its reason",
                    source_line=mod.line(node.lineno),
                )
        elif isinstance(node, ast.ClassDef) and mod.path != "loro_tpu/errors.py":
            if not node.name.endswith(_ERRORISH) or not node.bases:
                continue
            base_names = [dotted(b) or "" for b in node.bases]
            exceptionish = any(
                b.split(".")[-1] in _BUILTIN_EXC_BASES for b in base_names
            )
            typed = any(
                b.split(".")[-1] not in _BUILTIN_EXC_BASES and b
                for b in base_names
            )
            if exceptionish and not typed:
                yield Finding(
                    "LT-EXC", mod.path, node.lineno, node.col_offset + 1,
                    f"error class {node.name} subclasses only builtin "
                    "exceptions — root it in the errors.py hierarchy "
                    "(LoroError) so typed catches and the degradation "
                    "contract see it", source_line=mod.line(node.lineno),
                )


# ---------------------------------------------------------------------------
# LT-TUNNEL — the tunnel-wedge post-mortems as lint rules
# ---------------------------------------------------------------------------


@register(Rule(
    id="LT-TUNNEL",
    name="tunnel-safety violation",
    summary="block_until_ready-as-sync, signaling processes that may "
            "hold in-flight device work, or >1 pallas unroll",
    post_mortem="jax.block_until_ready does NOT synchronize under the "
                "axon tunnel (timings lie; fetch a scalar instead); "
                "SIGTERM/SIGKILL at in-flight device work wedged the "
                "tunnel for whole sessions (rounds 2/2b post-mortems); an "
                "8x-unrolled pallas kernel hung remote_compile — Mosaic "
                "supports unroll=1 or full loops only",
    scope=_pkg_and_bench,
))
def check_tunnel(mod: ModuleSource) -> Iterable[Finding]:
    imap = ImportMap(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        full = imap.resolve(node.func) or ""
        tail = full.split(".")[-1]
        d = dotted(node.func) or ""
        if tail == "block_until_ready" or d.endswith(".block_until_ready"):
            yield Finding(
                "LT-TUNNEL", mod.path, node.lineno, node.col_offset + 1,
                "block_until_ready is not a sync under the axon tunnel "
                "(per-launch timings come back ~0ms) — fetch a scalar-"
                "reduced result with np.asarray instead",
                source_line=mod.line(node.lineno),
            )
            continue
        if full == "os.kill":
            sig = node.args[1] if len(node.args) > 1 else None
            if isinstance(sig, ast.Constant) and sig.value == 0:
                continue  # signal 0 = existence probe, sends nothing
            yield Finding(
                "LT-TUNNEL", mod.path, node.lineno, node.col_offset + 1,
                "os.kill at a process that may hold in-flight device work "
                "can wedge the tunnel for the session — size runs to "
                "finish; never signal mid-compile/mid-transfer",
                source_line=mod.line(node.lineno),
            )
            continue
        if isinstance(node.func, ast.Attribute) and (
            node.func.attr == "send_signal"
            or (node.func.attr in ("terminate", "kill") and not node.args
                and not node.keywords)
        ):
            yield Finding(
                "LT-TUNNEL", mod.path, node.lineno, node.col_offset + 1,
                f".{node.func.attr}() on a child that may hold in-flight "
                "device work can wedge the tunnel — probe ladders are "
                "NEVER signaled (resilience/probe.py)",
                source_line=mod.line(node.lineno),
            )
            continue
        for kw in node.keywords:
            if kw.arg == "unroll" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value not in (1, None, False):
                yield Finding(
                    "LT-TUNNEL", mod.path, node.lineno, node.col_offset + 1,
                    f"unroll={kw.value.value!r}: Mosaic supports unroll=1 "
                    "or full loops only — an unrolled pallas program hung "
                    "remote_compile and wedged the tunnel (round-2b)",
                    source_line=mod.line(node.lineno),
                )


# ---------------------------------------------------------------------------
# LT-LOCK — static companion of the runtime lock witness
# ---------------------------------------------------------------------------


@register(Rule(
    id="LT-LOCK",
    name="declared-lock-order inversion",
    summary="a with-acquisition of a known named lock while a lock the "
            "declared order places BELOW it is already held",
    post_mortem="the fleet's thread planes (pipeline stage/commit, "
                "sharded fan-out/collector, fan-in, supervisors) share a "
                "declared partial lock order (analysis/lockorder.py); an "
                "inverted static acquisition is a latent deadlock the "
                "runtime witness would only catch when the schedule hits it",
    scope=lambda p: _in(p, "loro_tpu/parallel/", "loro_tpu/sync/",
                        "loro_tpu/resilience/"),
))
def check_lock(mod: ModuleSource) -> Iterable[Finding]:
    def lock_name(expr: ast.AST):
        d = dotted(expr)
        if d is None:
            return None
        return STATIC_ATTR_LOCKS.get(d.split(".")[-1])

    def walk(node: ast.AST, held: List[str]):
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                nm = lock_name(item.context_expr)
                if nm is None:
                    continue
                for h in held + acquired:
                    if h != nm and not allowed(h, nm):
                        yield Finding(
                            "LT-LOCK", mod.path, item.context_expr.lineno,
                            item.context_expr.col_offset + 1,
                            f"acquires {nm!r} while holding {h!r} — the "
                            "declared order (analysis/lockorder.py) puts "
                            f"{nm!r} outside {h!r}; invert the nesting or "
                            "amend the declaration with its justification",
                            source_line=mod.line(item.context_expr.lineno),
                        )
                acquired.append(nm)
            for child in node.body:
                yield from walk(child, held + acquired)
            return
        # function boundaries reset held-set (a called function's own
        # with-blocks are analyzed in its own frame)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in node.body:
                yield from walk(child, [])
            return
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held)

    yield from walk(mod.tree, [])
