"""DocState: the materialized document state.

reference: crates/loro-internal/src/state.rs (DocState, apply_diff,
get_value/get_deep_value).  Routes causally-ordered ops into per-
container states, tracks container parenthood for event paths and deep
values, and assembles DocDiff events (parent-first, reference
state.rs:621).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

from .core.change import Change, MapSet, MovableSet, Op, SeqInsert
from .core.ids import ContainerID, ContainerType, ID, PeerID
from .core.version import Frontiers, VersionVector
from .event import Diff
from .models.base import ContainerState
from .models.counter_state import CounterState
from .models.list_state import ListState
from .models.map_state import MapState
from .models.movable_list_state import MovableListState
from .models.text_state import TextState
from .models.tree_state import TreeState
from .models.unknown_state import UnknownState

_STATE_BY_TYPE = {
    ContainerType.Map: MapState,
    ContainerType.List: ListState,
    ContainerType.Text: TextState,
    ContainerType.Tree: TreeState,
    ContainerType.MovableList: MovableListState,
    ContainerType.Counter: CounterState,
    ContainerType.Unknown: UnknownState,
}


class StateTable(dict):
    """Container states with lazy per-container hydration (reference:
    container_store.rs — states decode from their kv entries on first
    access).  Keys are always present (iteration/`in` never hydrates);
    values decode on first read.  `hydrated` counts decodes — tests
    assert laziness with it."""

    def __init__(self) -> None:
        super().__init__()
        self._thunks: Dict[ContainerID, Any] = {}
        self.hydrated = 0

    def put_cold(self, cid: ContainerID, thunk) -> None:
        super().__setitem__(cid, None)
        self._thunks[cid] = thunk

    def _hydrate(self, cid: ContainerID):
        from .errors import DecodeError

        th = self._thunks[cid]
        try:
            st = th()
        except DecodeError:
            raise  # keep the thunk: the error repeats, data never drops
        except Exception as e:
            raise DecodeError(f"malformed container state for {cid}: {e}") from e
        self._thunks.pop(cid, None)
        self.hydrated += 1
        st.materialized = True  # snapshot-backed states carry content
        super().__setitem__(cid, st)
        return st

    def __getitem__(self, cid):
        v = super().__getitem__(cid)
        if v is None and cid in self._thunks:
            v = self._hydrate(cid)
        return v

    def get(self, cid, default=None):
        if cid not in self:
            return default
        return self[cid]

    def __setitem__(self, cid, st) -> None:
        self._thunks.pop(cid, None)
        super().__setitem__(cid, st)

    def values(self):
        return [self[c] for c in self]

    def items(self):
        return [(c, self[c]) for c in self]

    def pop(self, cid, *a):
        self._thunks.pop(cid, None)
        return super().pop(cid, *a)

    # dict C fast paths would leak the None placeholders: route the
    # remaining mutation/copy surface through hydration
    def copy(self):
        return {c: self[c] for c in self}

    def setdefault(self, cid, default=None):
        if cid in self:
            return self[cid]
        self[cid] = default
        return default

    def update(self, other=(), **kw):
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v


class DocState:
    def __init__(self) -> None:
        self.states: Dict[ContainerID, ContainerState] = {}
        # child cid -> (parent cid, key-or-elem-id) for paths/deep values
        self.parents: Dict[ContainerID, Tuple[ContainerID, Union[str, ID, None]]] = {}
        self.vv = VersionVector()
        self.frontiers = Frontiers()

    # ------------------------------------------------------------------
    def get_or_create(self, cid: ContainerID) -> ContainerState:
        st = self.states.get(cid)
        if st is None:
            st = _STATE_BY_TYPE[cid.ctype](cid)
            self.states[cid] = st
        return st

    def get(self, cid: ContainerID) -> Optional[ContainerState]:
        return self.states.get(cid)

    # ------------------------------------------------------------------
    def apply_changes(
        self, changes: List[Change], record: bool = True
    ) -> Dict[ContainerID, List[Diff]]:
        """Apply causally-ordered changes.  Returns per-container diff
        lists when `record` (compose with compose_many for events)."""
        diffs: Dict[ContainerID, List[Diff]] = {}
        for ch in changes:
            for op in ch.ops:
                lamport = ch.lamport + (op.counter - ch.ctr_start)
                self._register_children(op, ch.peer)
                st = self.get_or_create(op.container)
                st.materialized = True
                d = st.apply_op(op, ch.peer, lamport, record=record)
                if record and d is not None:
                    diffs.setdefault(op.container, []).append(d)
            self.vv.extend_to_include(ch.id_span())
        return diffs

    def _register_children(self, op: Op, peer: PeerID) -> None:
        c = op.content
        if isinstance(c, MapSet):
            if isinstance(c.value, ContainerID):
                self.parents.setdefault(c.value, (op.container, c.key))
        elif isinstance(c, SeqInsert):
            if isinstance(c.content, (tuple, list)):
                for j, v in enumerate(c.content):
                    if isinstance(v, ContainerID):
                        self.parents.setdefault(v, (op.container, ID(peer, op.counter + j)))
        elif isinstance(c, MovableSet):
            if isinstance(c.value, ContainerID):
                self.parents.setdefault(c.value, (op.container, c.elem))
        # tree node meta containers register lazily via path_of

    # ------------------------------------------------------------------
    def path_of(self, cid: ContainerID) -> Tuple[Union[str, int], ...]:
        """Event path from root (keys for maps, indexes for sequences).
        reference: subscription.rs path resolution via arena parents."""
        from .core.ids import parse_mergeable_root_name

        parts: List[Union[str, int]] = []
        cur = cid
        seen = 0
        while not cur.is_root or parse_mergeable_root_name(cur.name or "") is not None:
            if cur.is_root:
                # mergeable child root: the path runs through its
                # parent map at the encoded key
                parent_cid, key = parse_mergeable_root_name(cur.name)
                parts.append(key)
                cur = parent_cid
                seen += 1
                if seen > 1000:
                    break
                continue
            link = self.parents.get(cur)
            if link is None:
                # maybe a tree-node meta map: cid == (peer,counter,Map) of a node
                owner = self._find_tree_owner(cur)
                if owner is None:
                    parts.append(repr(cur))
                    break
                tree_cid, node = owner
                parts.append(str(node))
                cur = tree_cid
                continue
            parent, key = link
            if isinstance(key, str):
                parts.append(key)
            elif isinstance(key, ID):
                st = self.states.get(parent)
                idx = None
                if isinstance(st, (ListState,)):
                    idx = st.seq.visible_index_of(key)
                elif isinstance(st, MovableListState):
                    entry = st.elems.get(key)
                    if entry is not None and not entry.deleted:
                        idx = st.seq.visible_index_of(entry.slot)
                parts.append(idx if idx is not None else -1)
            cur = parent
            seen += 1
            if seen > 1000:  # corrupt-parent guard
                break
        if cur.is_root:
            parts.append(cur.name)  # type: ignore[arg-type]
        return tuple(reversed(parts))

    def _find_tree_owner(self, cid: ContainerID) -> Optional[Tuple[ContainerID, Any]]:
        if cid.ctype != ContainerType.Map or cid.is_root:
            return None
        from .models.tree_state import TreeState as _TS

        for tcid, st in self.states.items():
            if isinstance(st, _TS):
                from .core.ids import TreeID

                node = TreeID(cid.peer, cid.counter)  # type: ignore[arg-type]
                if node in st.nodes:
                    return tcid, node
        return None

    def is_alive(self, cid: ContainerID) -> bool:
        """Reachability from a root: each hop's parent must still hold
        this child (map entry not overwritten/deleted, sequence element
        visible, tree node not trashed); reference: DocState
        dead-containers cache semantics (state.rs)."""
        from .core.ids import parse_mergeable_root_name

        cur = cid
        for _ in range(1000):
            if cur.is_root:
                pm = parse_mergeable_root_name(cur.name or "")
                if pm is None:
                    return True
                parent_cid, key = pm  # mergeable child root: key in parent map
                pst = self.states.get(parent_cid)
                if pst is None or pst.get_value().get(key) is None:
                    return False
                cur = parent_cid
                continue
            link = self.parents.get(cur)
            if link is None:
                owner = self._find_tree_owner(cur)
                if owner is None:
                    return cur in self.states  # unknown linkage: best effort
                tcid, node = owner
                tst = self.states.get(tcid)
                if tst is None or not tst.contains(node):
                    return False
                cur = tcid
                continue
            parent_cid, key = link
            pst = self.states.get(parent_cid)
            if pst is None:
                return False
            if isinstance(key, str) and hasattr(pst, "get_entry"):
                e = pst.get_entry(key)  # O(1) map lookup
                if e is None or e.value != cur:
                    return False
            elif isinstance(key, ID) and hasattr(pst, "elems"):
                # movable list: key is the element id; the element must
                # be live and its winning (set-rebindable) value == cur
                entry = pst.elems.get(key)
                if entry is None or entry.deleted or entry.value != cur:
                    return False
            elif isinstance(key, ID) and hasattr(pst, "seq"):
                e = pst.seq.by_id.get((key.peer, key.counter))
                if e is None or e.deleted or e.content != cur:
                    return False
            else:
                v = pst.get_value()
                if isinstance(v, dict):
                    if not (isinstance(key, str) and v.get(key) == cur):
                        return False
                elif isinstance(v, list) and cur not in v:
                    return False
            cur = parent_cid
        return False

    def depth_of(self, cid: ContainerID) -> int:
        d = 0
        cur = cid
        while not cur.is_root:
            link = self.parents.get(cur)
            if link is None:
                owner = self._find_tree_owner(cur)
                if owner is None:
                    return d
                cur = owner[0]
                d += 1
                continue
            cur = link[0]
            d += 1
            if d > 1000:
                break
        return d

    # ------------------------------------------------------------------
    def get_value(self) -> Dict[str, Any]:
        """Shallow doc value: root containers only (internal mergeable-
        child roots resolve through their parent maps, not here)."""
        from .core.ids import is_internal_root_name

        out: Dict[str, Any] = {}
        for cid, st in self.states.items():
            if cid.is_root and not is_internal_root_name(cid.name) and st.materialized:
                out[cid.name] = st.get_value()  # type: ignore[index]
        return out

    def get_deep_value(self) -> Dict[str, Any]:
        from .core.ids import is_internal_root_name

        out: Dict[str, Any] = {}
        for cid, st in sorted(self.states.items(), key=lambda kv: kv[0]._key()):
            if cid.is_root and not is_internal_root_name(cid.name) and st.materialized:
                out[cid.name] = self._deep(st)  # type: ignore[index]
        return out

    def _deep(self, st: ContainerState) -> Any:
        v = st.get_value()
        if isinstance(st, TreeState):
            return self._deep_tree(st)
        return self._resolve(v)

    def _resolve(self, v: Any) -> Any:
        if isinstance(v, ContainerID):
            child = self.states.get(v)
            return self._deep(child) if child is not None else None
        if isinstance(v, list):
            return [self._resolve(x) for x in v]
        if isinstance(v, dict):
            return {k: self._resolve(x) for k, x in v.items()}
        return v

    def _deep_tree(self, st: TreeState) -> List[dict]:
        def node_json(t) -> dict:
            meta_st = self.states.get(st.meta_cid(t))
            return {
                "id": str(t),
                "meta": self._deep(meta_st) if meta_st else {},
                "children": [node_json(c) for c in st.children_of(t)],
            }

        return [node_json(t) for t in st.roots()]

    def fork(self) -> "DocState":
        """Deep copy via op replay is handled at doc level; DocState itself
        is not directly copyable (treap nodes are intrusive)."""
        raise NotImplementedError


def compose_many(diffs: List[Diff]) -> Diff:
    """Balanced fold so composing n single-op diffs costs O(n log n)
    (the reference gets the same via its B-tree DeltaRope)."""
    assert diffs
    items = list(diffs)
    while len(items) > 1:
        nxt = []
        for i in range(0, len(items) - 1, 2):
            nxt.append(items[i].compose(items[i + 1]))  # type: ignore[union-attr]
        if len(items) % 2:
            nxt.append(items[-1])
        items = nxt
    return items[0]
