"""Lossless JSON op interchange format.

reference: crates/loro-internal/src/encoding/json_schema.rs
(JsonSchema{schema_version, start_version, changes}).  This is the
human-readable codec; the binary columnar codec (codec/binary.py) is the
wire-efficient one.  Both carry the same change model.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..core.change import (
    Change,
    CounterIncr,
    MapSet,
    MovableMove,
    MovableSet,
    Op,
    SeqDelete,
    SeqInsert,
    Side,
    StyleAnchor,
    TreeMove,
    UnknownContent,
)
from ..core.ids import ContainerID, ID, IdSpan, TreeID
from ..core.value import from_json, to_json
from ..errors import LoroError
from ..core.version import Frontiers, VersionVector

SCHEMA_VERSION = 1


def _id_str(id: Optional[ID]) -> Optional[str]:
    return None if id is None else str(id)


def _id_parse(s: Optional[str]) -> Optional[ID]:
    return None if s is None else ID.parse(s)


def op_to_json(op: Op) -> Dict[str, Any]:
    c = op.content
    d: Dict[str, Any] = {"container": str(op.container), "counter": op.counter}
    if isinstance(c, MapSet):
        d["type"] = "map_set"
        d["key"] = c.key
        if c.deleted:
            d["deleted"] = True
        else:
            d["value"] = to_json(c.value)
    elif isinstance(c, SeqInsert):
        d["type"] = "insert"
        from ..oplog.oplog import _RunCont

        d["parent"] = "run-cont" if isinstance(c.parent, _RunCont) else _id_str(c.parent)
        d["side"] = int(c.side)
        if isinstance(c.content, StyleAnchor):
            d["anchor"] = {
                "key": c.content.key,
                "value": to_json(c.content.value),
                "start": c.content.is_start,
                "info": c.content.info,
            }
        elif isinstance(c.content, str):
            d["text"] = c.content
        else:
            d["values"] = [to_json(v) for v in c.content]
    elif isinstance(c, SeqDelete):
        d["type"] = "delete"
        d["spans"] = [[s.peer, s.start, s.end] for s in c.spans]
    elif isinstance(c, TreeMove):
        d["type"] = "tree"
        d["target"] = str(c.target)
        d["parent"] = str(c.parent) if c.parent is not None else None
        d["position"] = c.position.hex() if c.position is not None else None
        if c.is_create:
            d["create"] = True
        if c.is_delete:
            d["del"] = True
    elif isinstance(c, CounterIncr):
        d["type"] = "counter"
        d["delta"] = c.delta
    elif isinstance(c, MovableSet):
        d["type"] = "mset"
        d["elem"] = str(c.elem)
        d["value"] = to_json(c.value)
    elif isinstance(c, MovableMove):
        d["type"] = "mmove"
        d["elem"] = str(c.elem)
        d["parent"] = _id_str(c.parent)
        d["side"] = int(c.side)
    elif isinstance(c, UnknownContent):
        d["type"] = "unknown"
        d["kind"] = c.kind
        d["data"] = c.data.hex()
    else:  # pragma: no cover
        raise TypeError(f"unknown op content {type(c)}")
    return d


def op_from_json(d: Dict[str, Any]) -> Op:
    cid = ContainerID.parse(d["container"])
    t = d["type"]
    if t == "map_set":
        if d.get("deleted"):
            content = MapSet(d["key"], None, True)
        else:
            content = MapSet(d["key"], from_json(d["value"]))
    elif t == "insert":
        if d["parent"] == "run-cont":
            from ..oplog.oplog import _RUN_CONT

            parent: Any = _RUN_CONT
        else:
            parent = _id_parse(d["parent"])
        if "anchor" in d:
            a = d["anchor"]
            body: Any = StyleAnchor(a["key"], from_json(a["value"]), a["start"], a.get("info", 0))
        elif "text" in d:
            body = d["text"]
        else:
            body = tuple(from_json(v) for v in d["values"])
        content = SeqInsert(parent, Side(d["side"]), body)
    elif t == "delete":
        content = SeqDelete(tuple(IdSpan(p, s, e) for p, s, e in d["spans"]))
    elif t == "tree":
        content = TreeMove(
            TreeID.parse(d["target"]),
            TreeID.parse(d["parent"]) if d["parent"] is not None else None,
            bytes.fromhex(d["position"]) if d["position"] is not None else None,
            d.get("create", False),
            d.get("del", False),
        )
    elif t == "counter":
        content = CounterIncr(d["delta"])
    elif t == "mset":
        content = MovableSet(ID.parse(d["elem"]), from_json(d["value"]))
    elif t == "mmove":
        content = MovableMove(ID.parse(d["elem"]), _id_parse(d["parent"]), Side(d["side"]))
    elif t == "unknown":
        content = UnknownContent(d["kind"], bytes.fromhex(d["data"]))
    else:
        raise ValueError(f"unknown op type {t!r}")
    return Op(d["counter"], cid, content)


def change_to_json(ch: Change) -> Dict[str, Any]:
    return {
        "id": str(ch.id),
        "lamport": ch.lamport,
        "deps": ch.deps.to_json(),
        "timestamp": ch.timestamp,
        "msg": ch.message,
        "ops": [op_to_json(op) for op in ch.ops],
    }


def change_from_json(d: Dict[str, Any]) -> Change:
    return Change(
        id=ID.parse(d["id"]),
        lamport=d["lamport"],
        deps=Frontiers.from_json(d["deps"]),
        ops=[op_from_json(o) for o in d["ops"]],
        timestamp=d.get("timestamp", 0),
        message=d.get("msg"),
    )


def export_json_updates(
    changes: List[Change], start_vv: VersionVector, end_vv: VersionVector
) -> Dict[str, Any]:
    return {
        "schema_version": SCHEMA_VERSION,
        "start_version": start_vv.to_json(),
        "end_version": end_vv.to_json(),
        "changes": [change_to_json(c) for c in changes],
    }


REDACTED_CHAR = "�"


class RedactError(LoroError, ValueError):
    """reference: json_schema.rs RedactError (InvalidSchema /
    UnknownOperationType).  Rooted in LoroError (the typed-error
    discipline) while keeping the historical ValueError base for
    pre-existing ``except ValueError`` callers."""


def _op_json_len(d: Dict[str, Any]) -> int:
    """Counter span of a JSON op (mirror of Op.atom_len)."""
    if d["type"] == "insert":
        if "text" in d:
            return max(1, len(d["text"]))
        if "values" in d:
            return max(1, len(d["values"]))
    return 1


def _redact_value(v: Any) -> Any:
    """Nulls a JSON value unless it is a child-container ref (child
    creation must survive redaction — reference json_schema.rs
    redact_value)."""
    if isinstance(v, dict) and set(v.keys()) == {"__cid__"}:
        return v
    return None


def redact_json_updates(doc_json: Dict[str, Any], rng) -> Dict[str, Any]:
    """Redact sensitive content of ops inside `rng` (a VersionRange) in
    place, preserving all CRDT structure so redacted and non-redacted
    docs keep converging (reference: loro::json::redact,
    json_schema.rs:1750-1880):

    - text inserts: covered chars become U+FFFD (lengths preserved)
    - list / movable-list insert values and movable set values: Null
      (child-container refs kept)
    - map insert values: Null (keys kept); deletes untouched
    - text mark (anchor) values: Null (keys kept)
    - counter increments: 0
    - tree / move / delete ops: unchanged
    - unknown future ops: RedactError (their counter span is opaque)
    """
    ranges = dict(rng.items())
    i32_max = (1 << 31) - 1
    errors: List[RedactError] = []
    for change in doc_json.get("changes", []):
        try:
            cid = ID.parse(change["id"])
        except (KeyError, ValueError) as e:
            raise RedactError(f"invalid change id: {e}") from None
        if cid.peer not in ranges:
            continue
        s, e = ranges[cid.peer]
        for op in change["ops"]:
            ctr = op.get("counter")
            if not isinstance(ctr, int) or ctr < 0 or ctr > i32_max:
                raise RedactError(f"op counter out of range: {ctr!r}")
            length = _op_json_len(op)
            if ctr + length > i32_max:
                raise RedactError("op counter overflow")
            if ctr >= e:
                break
            t = op["type"]
            if t == "unknown":
                # fail-closed: an unknown (future-format) op's counter
                # span is opaque, so any such op starting before the
                # range end may hold covered content
                errors.append(RedactError("cannot redact unknown op type"))
                continue
            lo = max(s - ctr, 0)
            hi = min(e - ctr, length)
            if hi <= lo:
                continue
            if t == "insert":
                if "text" in op:
                    chars = list(op["text"])
                    for i in range(lo, hi):
                        chars[i] = REDACTED_CHAR
                    op["text"] = "".join(chars)
                elif "values" in op:
                    vals = op["values"]
                    for i in range(lo, hi):
                        vals[i] = _redact_value(vals[i])
                elif "anchor" in op:
                    op["anchor"]["value"] = None
            elif t == "map_set":
                if not op.get("deleted"):
                    op["value"] = _redact_value(op["value"])
            elif t == "mset":
                op["value"] = _redact_value(op["value"])
            elif t == "counter":
                op["delta"] = 0
            elif t in ("delete", "tree", "mmove"):
                pass  # structure ops carry no redactable content
            else:
                errors.append(RedactError(f"unrecognized op type {t!r}"))
    if errors:
        raise errors[-1]
    return doc_json


def import_json_updates(doc_json: Dict[str, Any]) -> List[Change]:
    if doc_json.get("schema_version", 1) > SCHEMA_VERSION:
        raise ValueError(f"unsupported schema version {doc_json.get('schema_version')}")
    return [change_from_json(c) for c in doc_json["changes"]]


def dumps(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False).encode()


def loads(b: bytes) -> Dict[str, Any]:
    return json.loads(b.decode())
