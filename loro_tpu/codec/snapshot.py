"""Snapshot codecs: fast snapshot + shallow snapshot + state-only.

reference: crates/loro-internal/src/encoding/fast_snapshot.rs (layout
[oplog][state][shallow-root-state]; import installs bytes directly, no
replay) and encoding/shallow_snapshot.rs (history trimmed before chosen
frontiers, frozen root state kept).

Container states serialize to compact tables: sequences dump their
element table in traversal order (rebuild is pure insert-after, no
Fugue logic), maps/trees/counters dump their entry/move tables.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.change import Side, StyleAnchor
from ..core.ids import ContainerID, ContainerType, ID, TreeID
from ..models.counter_state import CounterState
from ..models.list_state import ListState
from ..models.map_state import MapEntry, MapState
from ..models.movable_list_state import ElemEntry, MovableListState
from ..models.seq_crdt import FugueSeq, SeqElem
from ..models.text_state import TextState
from ..models.tree_state import TreeState
from ..models.unknown_state import UnknownState
from .binary import Reader, Writer, _Dicts, _read_cid, _read_value, _write_cid, _write_value

S_MAP, S_SEQ, S_MOVABLE, S_TREE, S_COUNTER, S_UNKNOWN = range(6)

# bump on any incompatible state-table layout change (v2: per-element
# deleted_by records; v3: movable-list slot/set histories; v4:
# per-container byte-length table for lazy hydration)
STATE_FORMAT = 4

# element content tags for sequence states
E_CHAR, E_VALUE, E_ANCHOR, E_ELEMREF = range(4)


# ---------------------------------------------------------------------------
# per-container state encoding
# ---------------------------------------------------------------------------


def _write_seq(w: Writer, d: _Dicts, seq: FugueSeq) -> None:
    """Element table in traversal order.  parent refs use traversal
    indexes (parents always exist in the table)."""
    elems = list(seq.all_elems())
    index: Dict[Tuple[int, int], int] = {(e.peer, e.counter): i for i, e in enumerate(elems)}
    w.varint(len(elems))
    for e in elems:
        w.varint(d.peer(e.peer))
        w.zigzag(e.counter)
        w.varint(e.lamport)
        if e.fparent is None:
            w.varint(0)
        else:
            w.varint(index[(e.fparent.peer, e.fparent.counter)] + 1)
        # bit2: invisible though not deleted (movable-list stale slots)
        flags = int(e.fside) | (2 if e.deleted else 0) | (4 if e.vis_w == 0 else 0)
        w.u8(flags)
        # deletion records (version-diff visibility evaluation)
        w.varint(len(e.deleted_by))
        for did in e.deleted_by:
            w.varint(d.peer(did.peer))
            w.zigzag(did.counter)
        c = e.content
        if isinstance(c, StyleAnchor):
            w.u8(E_ANCHOR)
            w.varint(d.key(c.key))
            _write_value(w, d, c.value)
            w.u8(1 if c.is_start else 0)
            w.varint(c.info)
        elif isinstance(c, str):
            w.u8(E_CHAR)
            w.str_(c)
        elif isinstance(c, ID):
            w.u8(E_ELEMREF)
            w.varint(d.peer(c.peer))
            w.zigzag(c.counter)
        else:
            w.u8(E_VALUE)
            _write_value(w, d, c)


def _read_seq(r: Reader, peers: List[int], keys: List[str], cids: List[ContainerID]) -> FugueSeq:
    seq = FugueSeq()
    n = r.varint()
    elems: List[SeqElem] = []
    prefs: List[int] = []
    prev: Optional[SeqElem] = None
    for _ in range(n):
        peer = peers[r.varint()]
        counter = r.zigzag()
        lamport = r.varint()
        pref = r.varint()
        flags = r.u8()
        deleted_by = [ID(peers[r.varint()], r.zigzag()) for _ in range(r.varint())]
        tag = r.u8()
        if tag == E_ANCHOR:
            key = keys[r.varint()]
            value = _read_value(r, cids)
            is_start = bool(r.u8())
            info = r.varint()
            content: Any = StyleAnchor(key, value, is_start, info)
        elif tag == E_CHAR:
            content = r.str_()
        elif tag == E_ELEMREF:
            content = ID(peers[r.varint()], r.zigzag())
        else:
            content = _read_value(r, cids)
        # fparent linked in a second pass — a parent can appear *later*
        # in traversal order (L-children precede their parent)
        e = SeqElem(peer, counter, content, None, Side(flags & 1), lamport)
        e.deleted_by = deleted_by
        for x in deleted_by:
            seq.deleter_index.setdefault((x.peer, x.counter), []).append(e)
        if flags & 2:
            e.deleted = True
        invisible = bool(flags & 6) or e.is_anchor
        e.init_treap(0 if invisible else e.base_width())
        seq.treap.insert_after(prev, e)
        seq.by_id[(peer, counter)] = e
        elems.append(e)
        prefs.append(pref)
        prev = e
    for e, pref in zip(elems, prefs):
        e.fparent = elems[pref - 1] if pref else None
    # rebuild children lists (sorted by sibling key)
    for e in elems:
        if e.fparent is None:
            seq.root_children.append(e)
        elif e.fside == Side.Right:
            e.fparent.r_children.append(e)
        else:
            e.fparent.l_children.append(e)
    seq.root_children.sort(key=lambda x: x.sib_key)
    for e in elems:
        if e.l_children:
            e.l_children.sort(key=lambda x: x.sib_key)
        if e.r_children:
            e.r_children.sort(key=lambda x: x.sib_key)
    return seq


def encode_container_state(w: Writer, d: _Dicts, st) -> None:
    if isinstance(st, MapState):
        w.u8(S_MAP)
        w.varint(len(st.entries))
        for k, e in st.entries.items():
            w.varint(d.key(k))
            w.varint(e.lamport)
            w.varint(d.peer(e.peer))
            w.zigzag(e.counter)
            w.u8(1 if e.deleted else 0)
            if not e.deleted:
                _write_value(w, d, e.value)
    elif isinstance(st, (TextState, ListState)):
        w.u8(S_SEQ)
        _write_seq(w, d, st.seq)
    elif isinstance(st, MovableListState):
        w.u8(S_MOVABLE)
        _write_seq(w, d, st.seq)
        w.varint(len(st.elems))
        for eid, entry in st.elems.items():
            w.varint(d.peer(eid.peer))
            w.zigzag(eid.counter)
            _write_value(w, d, entry.value)
            w.varint(entry.value_key[0])
            w.varint(d.peer(entry.value_key[1]))
            w.varint(entry.pos_key[0])
            w.varint(d.peer(entry.pos_key[1]))
            w.varint(d.peer(entry.slot.peer))
            w.zigzag(entry.slot.counter)
            w.u8(1 if entry.deleted else 0)
            w.varint(len(entry.slots))
            for sid in entry.slots:
                w.varint(d.peer(sid.peer))
                w.zigzag(sid.counter)
            w.varint(len(entry.sets))
            for lam, sp, oid, val in entry.sets:
                w.varint(lam)
                w.varint(d.peer(sp))
                w.varint(d.peer(oid.peer))
                w.zigzag(oid.counter)
                _write_value(w, d, val)
    elif isinstance(st, TreeState):
        w.u8(S_TREE)
        w.varint(len(st.moves))
        for (lam, peer, ctr), mv in st.moves:
            w.varint(lam)
            w.varint(d.peer(peer))
            w.zigzag(ctr)
            w.varint(d.peer(mv.target.peer))
            w.zigzag(mv.target.counter)
            flags = (
                (1 if mv.is_create else 0)
                | (2 if mv.is_delete else 0)
                | (4 if mv.parent is not None else 0)
                | (8 if mv.position is not None else 0)
            )
            w.u8(flags)
            if mv.parent is not None:
                w.varint(d.peer(mv.parent.peer))
                w.zigzag(mv.parent.counter)
            if mv.position is not None:
                w.bytes_(mv.position)
    elif isinstance(st, CounterState):
        w.u8(S_COUNTER)
        w.f64(st.value)
    elif isinstance(st, UnknownState):
        w.u8(S_UNKNOWN)
        w.varint(0)
    else:  # pragma: no cover
        raise TypeError(f"cannot snapshot state {type(st)}")


def decode_container_state(
    r: Reader, cid: ContainerID, peers: List[int], keys: List[str], cids: List[ContainerID]
):
    from ..core.change import TreeMove

    tag = r.u8()
    if tag == S_MAP:
        st = MapState(cid)
        for _ in range(r.varint()):
            k = keys[r.varint()]
            lam = r.varint()
            peer = peers[r.varint()]
            ctr = r.zigzag()
            deleted = bool(r.u8())
            value = None if deleted else _read_value(r, cids)
            st.entries[k] = MapEntry(value, lam, peer, ctr, deleted)
        return st
    if tag == S_SEQ:
        st = TextState(cid) if cid.ctype == ContainerType.Text else ListState(cid)
        st.seq = _read_seq(r, peers, keys, cids)
        if isinstance(st, TextState):
            st.n_anchors = sum(1 for e in st.seq.all_elems() if e.is_anchor)
        return st
    if tag == S_MOVABLE:
        st = MovableListState(cid)
        st.seq = _read_seq(r, peers, keys, cids)
        for _ in range(r.varint()):
            eid = ID(peers[r.varint()], r.zigzag())
            value = _read_value(r, cids)
            vk = (r.varint(), peers[r.varint()])
            pk = (r.varint(), peers[r.varint()])
            slot = ID(peers[r.varint()], r.zigzag())
            entry = ElemEntry(value, vk, pk, slot)
            entry.deleted = bool(r.u8())
            entry.slots = [ID(peers[r.varint()], r.zigzag()) for _ in range(r.varint())]
            entry.sets = [
                (r.varint(), peers[r.varint()], ID(peers[r.varint()], r.zigzag()), _read_value(r, cids))
                for _ in range(r.varint())
            ]
            st.elems[eid] = entry
        return st
    if tag == S_TREE:
        st = TreeState(cid)
        for _ in range(r.varint()):
            lam = r.varint()
            peer = peers[r.varint()]
            ctr = r.zigzag()
            target = TreeID(peers[r.varint()], r.zigzag())
            flags = r.u8()
            parent = TreeID(peers[r.varint()], r.zigzag()) if flags & 4 else None
            position = r.bytes_() if flags & 8 else None
            st.moves.append(
                ((lam, peer, ctr), TreeMove(target, parent, position, bool(flags & 1), bool(flags & 2)))
            )
        st._replay_all()
        return st
    if tag == S_COUNTER:
        st = CounterState(cid)
        st.value = r.f64()
        return st
    if tag == S_UNKNOWN:
        r.varint()
        return UnknownState(cid)
    raise ValueError(f"bad state tag {tag}")


# ---------------------------------------------------------------------------
# doc-level snapshot
# ---------------------------------------------------------------------------


def encode_doc_state(doc_state, parents: Dict) -> bytes:
    """Serialize a whole DocState (tables emitted after scratch so value
    cid refs register first — same trap as binary.encode_changes)."""
    d = _Dicts()
    scratch = Writer()
    # read-created ghost states (materialized=False) must not ship: the
    # importer would hydrate them as real empty roots and diverge from
    # replicas that never read them
    items = sorted(
        (kv for kv in doc_state.states.items() if kv[1].materialized),
        key=lambda kv: kv[0]._key(),
    )
    for cid, st in items:
        d.cid(cid)
    seg_lens = []
    for cid, st in items:
        before = len(scratch.buf)
        encode_container_state(scratch, d, st)
        seg_lens.append(len(scratch.buf) - before)
    # parent links (for event paths after fast import)
    pw = Writer()
    links = [(c, p, k) for c, (p, k) in parents.items()]
    pw.varint(len(links))
    for c, p, k in links:
        pw.varint(d.cid(c))
        pw.varint(d.cid(p))
        if isinstance(k, str):
            pw.u8(0)
            pw.varint(d.key(k))
        elif isinstance(k, ID):
            pw.u8(1)
            pw.varint(d.peer(k.peer))
            pw.zigzag(k.counter)
        else:
            pw.u8(2)
    for c in d.cids:
        if not c.is_root:
            d.peer(c.peer)  # type: ignore[arg-type]

    w = Writer()
    w.u8(STATE_FORMAT)
    w.varint(len(d.peers))
    for p in d.peers:
        w.u64le(p)
    w.varint(len(d.keys))
    for k in d.keys:
        w.str_(k)
    w.varint(len(d.cids))
    for c in d.cids:
        _write_cid(w, d, c)
    w.varint(len(items))
    for cid, _ in items:
        w.varint(d.cid(cid))
    # per-container byte lengths: lets the decoder hydrate containers
    # lazily (reference: container_store.rs per-container kv entries)
    for n in seg_lens:
        w.varint(n)
    w.buf += scratch.buf
    w.buf += pw.buf
    return bytes(w.buf)


def decode_doc_state(buf: bytes):
    """Returns (states, parents).  `states` is a StateTable whose
    container payloads decode on first access — importing a snapshot
    with many containers touches none of them until read (reference:
    container_store.rs lazy per-container entries).  Deferred decode
    failures surface as typed DecodeError at the read site (same
    contract as the change store's lazy blocks)."""
    from ..state import StateTable

    r = Reader(buf)
    fmt = r.u8()
    if fmt != STATE_FORMAT:
        raise ValueError(f"unsupported snapshot state format {fmt} (want {STATE_FORMAT})")
    peers = [r.u64le() for _ in range(r.varint())]
    keys = [r.str_() for _ in range(r.varint())]
    cids = [_read_cid(r, peers) for _ in range(r.varint())]
    order = [cids[r.varint()] for _ in range(r.varint())]
    seg_lens = [r.varint() for _ in range(len(order))]
    states = StateTable()
    for cid, ln in zip(order, seg_lens):
        if r.i + ln > len(buf):
            raise ValueError("truncated container state segment")
        seg = buf[r.i : r.i + ln]
        r.i += ln

        def thunk(seg=seg, cid=cid):
            rr = Reader(seg)
            return decode_container_state(rr, cid, peers, keys, cids)

        states.put_cold(cid, thunk)
    parents = {}
    for _ in range(r.varint()):
        c = cids[r.varint()]
        p = cids[r.varint()]
        t = r.u8()
        if t == 0:
            k: Any = keys[r.varint()]
        elif t == 1:
            k = ID(peers[r.varint()], r.zigzag())
        else:
            k = None
        parents[c] = (p, k)
    return states, parents
