"""Binary columnar wire codec.

reference: crates/loro-internal/src/oplog/change_store/block_encode.rs +
encoding/ (LEB128 headers, peer table, delta-encoded counters/lamports,
delta-of-delta timestamps, columnar op table).  Same layout ideas,
different format (we are not wire-compatible with the reference —
SURVEY.md §7 treats wire compat as a test oracle only, and our op model
ships Fugue (parent, side) placements).

Layout (after the doc-level LTPU envelope):
  [peer table]   varint n, then n u64 LE peers (dictionary; ids below
                 are peer *indices*)
  [key table]    varint n, n length-prefixed utf8 strings (map keys +
                 style keys)
  [cid table]    varint n, n encoded ContainerIDs
  [change meta]  varint n_changes, then columnar arrays:
                   peer_idx (varint each)
                   counter (zigzag delta per peer stream)
                   lamport (zigzag delta vs counter delta)
                   timestamp (zigzag delta)
                   n_deps + deps (peer_idx, zigzag counter)
                   message (tag + utf8)
                   n_ops
  [ops]          per change, per op: container_idx varint, kind byte,
                 kind-specific payload (varints/values)
Values use a compact tagged encoding (VNULL..VCID below).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List

from ..core.change import (
    Change,
    CounterIncr,
    MapSet,
    MovableMove,
    MovableSet,
    Op,
    SeqDelete,
    SeqInsert,
    Side,
    StyleAnchor,
    TreeMove,
    UnknownContent,
)
from ..core.ids import ContainerID, ContainerType, ID, IdSpan, TreeID
from ..core.version import Frontiers

# op kind tags
K_MAP_SET = 0
K_MAP_DEL = 1
K_INSERT_TEXT = 2
K_INSERT_VALUES = 3
K_INSERT_ANCHOR = 4
K_DELETE = 5
K_TREE = 6
K_COUNTER = 7
K_MSET = 8
K_MMOVE = 9
K_UNKNOWN = 10

# value tags
VNULL, VTRUE, VFALSE, VINT, VF64, VSTR, VBYTES, VLIST, VMAP, VCID = range(10)

RUN_CONT_TAG = 2  # parent encoding: 0=None, 1=id, 2=run-continuation


class Writer:
    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def u8(self, v: int) -> None:
        self.buf.append(v & 0xFF)

    def varint(self, v: int) -> None:
        """LEB128 unsigned."""
        assert v >= 0
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.buf.append(b | 0x80)
            else:
                self.buf.append(b)
                return

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1)

    def u64le(self, v: int) -> None:
        self.buf += struct.pack("<Q", v)

    def u32le(self, v: int) -> None:
        self.buf += struct.pack("<I", v)

    def f64(self, v: float) -> None:
        self.buf += struct.pack("<d", v)

    def bytes_(self, b: bytes) -> None:
        self.varint(len(b))
        self.buf += b

    def str_(self, s: str) -> None:
        self.bytes_(s.encode())


class Reader:
    __slots__ = ("buf", "i")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.i = 0

    def u8(self) -> int:
        v = self.buf[self.i]
        self.i += 1
        return v

    def varint(self) -> int:
        v = 0
        shift = 0
        while True:
            b = self.buf[self.i]
            self.i += 1
            v |= (b & 0x7F) << shift
            if not (b & 0x80):
                return v
            shift += 7
            if shift > 70:
                raise ValueError("varint overflow")

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) if not (v & 1) else -((v + 1) >> 1)

    def u64le(self) -> int:
        v = struct.unpack_from("<Q", self.buf, self.i)[0]
        self.i += 8
        return v

    def u32le(self) -> int:
        v = struct.unpack_from("<I", self.buf, self.i)[0]
        self.i += 4
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.buf, self.i)[0]
        self.i += 8
        return v

    def bytes_(self) -> bytes:
        n = self.varint()
        if self.i + n > len(self.buf):
            raise ValueError("truncated bytes")
        b = self.buf[self.i : self.i + n]
        self.i += n
        return bytes(b)

    def str_(self) -> str:
        return self.bytes_().decode()

    def eof(self) -> bool:
        return self.i >= len(self.buf)


class _Dicts:
    """Encoding dictionaries (peer / key / container tables)."""

    def __init__(self) -> None:
        self.peers: List[int] = []
        self._peer_idx: Dict[int, int] = {}
        self.keys: List[str] = []
        self._key_idx: Dict[str, int] = {}
        self.cids: List[ContainerID] = []
        self._cid_idx: Dict[ContainerID, int] = {}

    def peer(self, p: int) -> int:
        i = self._peer_idx.get(p)
        if i is None:
            i = len(self.peers)
            self.peers.append(p)
            self._peer_idx[p] = i
        return i

    def key(self, k: str) -> int:
        i = self._key_idx.get(k)
        if i is None:
            i = len(self.keys)
            self.keys.append(k)
            self._key_idx[k] = i
        return i

    def cid(self, c: ContainerID) -> int:
        i = self._cid_idx.get(c)
        if i is None:
            i = len(self.cids)
            self.cids.append(c)
            self._cid_idx[c] = i
        return i


def _write_value(w: Writer, d: _Dicts, v: Any) -> None:
    if v is None:
        w.u8(VNULL)
    elif v is True:
        w.u8(VTRUE)
    elif v is False:
        w.u8(VFALSE)
    elif isinstance(v, int):
        w.u8(VINT)
        w.zigzag(v)
    elif isinstance(v, float):
        w.u8(VF64)
        w.f64(v)
    elif isinstance(v, str):
        w.u8(VSTR)
        w.str_(v)
    elif isinstance(v, bytes):
        w.u8(VBYTES)
        w.bytes_(v)
    elif isinstance(v, (list, tuple)):
        w.u8(VLIST)
        w.varint(len(v))
        for x in v:
            _write_value(w, d, x)
    elif isinstance(v, dict):
        w.u8(VMAP)
        w.varint(len(v))
        for k in sorted(v):
            w.str_(k)
            _write_value(w, d, v[k])
    elif isinstance(v, ContainerID):
        w.u8(VCID)
        w.varint(d.cid(v))
    else:
        raise TypeError(f"cannot encode value {type(v)}")


def _read_value(r: Reader, cids: List[ContainerID]) -> Any:
    t = r.u8()
    if t == VNULL:
        return None
    if t == VTRUE:
        return True
    if t == VFALSE:
        return False
    if t == VINT:
        return r.zigzag()
    if t == VF64:
        return r.f64()
    if t == VSTR:
        return r.str_()
    if t == VBYTES:
        return r.bytes_()
    if t == VLIST:
        return [_read_value(r, cids) for _ in range(r.varint())]
    if t == VMAP:
        return {r.str_(): _read_value(r, cids) for _ in range(r.varint())}
    if t == VCID:
        return cids[r.varint()]
    raise ValueError(f"bad value tag {t}")


def _write_cid(w: Writer, d: _Dicts, c: ContainerID) -> None:
    if c.is_root:
        w.u8(int(c.ctype) | 0x80)
        w.str_(c.name)  # type: ignore[arg-type]
    else:
        w.u8(int(c.ctype))
        w.varint(d.peer(c.peer))  # type: ignore[arg-type]
        w.zigzag(c.counter)  # type: ignore[arg-type]


def _read_cid(r: Reader, peers: List[int]) -> ContainerID:
    b = r.u8()
    ctype = ContainerType(b & 0x7F)
    if b & 0x80:
        return ContainerID.root(r.str_(), ctype)
    pi = r.varint()
    if pi >= len(peers):
        raise ValueError(f"cid peer index {pi} out of table ({len(peers)} peers)")
    return ContainerID.normal(peers[pi], r.zigzag(), ctype)


def encode_changes(changes: List[Change]) -> bytes:
    d = _Dicts()
    # pass 1: dictionaries (stable order)
    for ch in changes:
        d.peer(ch.peer)
        for dep in ch.deps:
            d.peer(dep.peer)
        for op in ch.ops:
            d.cid(op.container)
            c = op.content
            if isinstance(c, MapSet):
                d.key(c.key)
            elif isinstance(c, SeqInsert):
                if isinstance(c.parent, ID):
                    d.peer(c.parent.peer)
                if isinstance(c.content, StyleAnchor):
                    d.key(c.content.key)
            elif isinstance(c, SeqDelete):
                for s in c.spans:
                    d.peer(s.peer)
            elif isinstance(c, TreeMove):
                d.peer(c.target.peer)
                if c.parent is not None:
                    d.peer(c.parent.peer)
            elif isinstance(c, (MovableSet, MovableMove)):
                d.peer(c.elem.peer)
                if isinstance(c, MovableMove) and isinstance(c.parent, ID):
                    d.peer(c.parent.peer)
    # values may reference cids — collect by dry-encoding values last;
    # VCID entries are registered during the value write below, so write
    # ops to a scratch buffer first, then emit tables, then the scratch.
    ops_w = Writer()
    for ch in changes:
        for op in ch.ops:
            _write_op(ops_w, d, op)

    # container ids can reference peers that appear in no change meta
    # (e.g. a partial update editing a container created long ago) —
    # register them BEFORE the peer table is emitted, or the cid table
    # below would append peers past the already-written table
    for c in d.cids:
        if not c.is_root:
            d.peer(c.peer)  # type: ignore[arg-type]

    w = Writer()
    w.varint(len(d.peers))
    for p in d.peers:
        w.u64le(p)
    w.varint(len(d.keys))
    for k in d.keys:
        w.str_(k)
    w.varint(len(d.cids))
    for c in d.cids:
        _write_cid(w, d, c)
    # change meta (columnar-ish: one field at a time per change)
    w.varint(len(changes))
    prev_ts = 0
    for ch in changes:
        w.varint(d.peer(ch.peer))
        w.zigzag(ch.ctr_start)
        w.zigzag(ch.lamport)
        w.zigzag(ch.timestamp - prev_ts)
        prev_ts = ch.timestamp
        w.varint(len(ch.deps))
        for dep in ch.deps:
            w.varint(d.peer(dep.peer))
            w.zigzag(dep.counter)
        if ch.message is None:
            w.u8(0)
        else:
            w.u8(1)
            w.str_(ch.message)
        w.varint(len(ch.ops))
    w.buf += ops_w.buf
    return bytes(w.buf)


def _write_op(w: Writer, d: _Dicts, op: Op) -> None:
    c = op.content
    w.varint(d.cid(op.container))
    if isinstance(c, MapSet):
        if c.deleted:
            w.u8(K_MAP_DEL)
            w.varint(d.key(c.key))
        else:
            w.u8(K_MAP_SET)
            w.varint(d.key(c.key))
            _write_value(w, d, c.value)
    elif isinstance(c, SeqInsert):
        if isinstance(c.content, StyleAnchor):
            w.u8(K_INSERT_ANCHOR)
            self_anchor = c.content
            _write_parent(w, d, c.parent)
            w.u8(int(c.side))
            w.varint(d.key(self_anchor.key))
            _write_value(w, d, self_anchor.value)
            w.u8(1 if self_anchor.is_start else 0)
            w.varint(self_anchor.info)
        elif isinstance(c.content, str):
            w.u8(K_INSERT_TEXT)
            _write_parent(w, d, c.parent)
            w.u8(int(c.side))
            w.str_(c.content)
        else:
            w.u8(K_INSERT_VALUES)
            _write_parent(w, d, c.parent)
            w.u8(int(c.side))
            w.varint(len(c.content))
            for v in c.content:
                _write_value(w, d, v)
    elif isinstance(c, SeqDelete):
        w.u8(K_DELETE)
        w.varint(len(c.spans))
        for s in c.spans:
            w.varint(d.peer(s.peer))
            w.zigzag(s.start)
            w.varint(s.end - s.start)
    elif isinstance(c, TreeMove):
        w.u8(K_TREE)
        w.varint(d.peer(c.target.peer))
        w.zigzag(c.target.counter)
        flags = (1 if c.is_create else 0) | (2 if c.is_delete else 0) | (4 if c.parent is not None else 0) | (
            8 if c.position is not None else 0
        )
        w.u8(flags)
        if c.parent is not None:
            w.varint(d.peer(c.parent.peer))
            w.zigzag(c.parent.counter)
        if c.position is not None:
            w.bytes_(c.position)
    elif isinstance(c, CounterIncr):
        w.u8(K_COUNTER)
        w.f64(c.delta)
    elif isinstance(c, MovableSet):
        w.u8(K_MSET)
        w.varint(d.peer(c.elem.peer))
        w.zigzag(c.elem.counter)
        _write_value(w, d, c.value)
    elif isinstance(c, MovableMove):
        w.u8(K_MMOVE)
        w.varint(d.peer(c.elem.peer))
        w.zigzag(c.elem.counter)
        _write_parent(w, d, c.parent)
        w.u8(int(c.side))
    elif isinstance(c, UnknownContent):
        w.u8(K_UNKNOWN)
        w.varint(c.kind)
        w.bytes_(c.data)
    else:  # pragma: no cover
        raise TypeError(f"cannot encode op content {type(c)}")


def _write_parent(w: Writer, d: _Dicts, parent) -> None:
    from ..oplog.oplog import _RunCont

    if parent is None:
        w.u8(0)
    elif isinstance(parent, _RunCont):
        w.u8(RUN_CONT_TAG)
    else:
        w.u8(1)
        w.varint(d.peer(parent.peer))
        w.zigzag(parent.counter)


def _read_parent(r: Reader, peers: List[int]):
    from ..oplog.oplog import _RUN_CONT

    t = r.u8()
    if t == 0:
        return None
    if t == RUN_CONT_TAG:
        return _RUN_CONT
    return ID(peers[r.varint()], r.zigzag())


def read_tables(buf: bytes):
    """Parse just the payload prelude dictionaries.  Returns
    (peers, keys, cids, reader-positioned-after-tables) — the single
    place that knows the header layout besides encode_changes.
    Truncated/corrupt preludes raise a typed CodecDecodeError (a
    ValueError subclass, so every per-payload ``except ValueError``
    fallback path catches it)."""
    from ..errors import CodecDecodeError

    r = Reader(buf)
    try:
        peers = [r.u64le() for _ in range(r.varint())]
        keys = [r.str_() for _ in range(r.varint())]
        cids = [_read_cid(r, peers) for _ in range(r.varint())]
    except CodecDecodeError:
        raise
    except (IndexError, struct.error, UnicodeDecodeError, ValueError,
            OverflowError) as e:
        raise CodecDecodeError(
            f"malformed payload tables ({type(e).__name__}: {e})"
        ) from e
    return peers, keys, cids, r


def decode_changes(buf: bytes) -> List[Change]:
    """Decode a bare (envelope-stripped) updates payload.  Truncated or
    bit-flipped input raises a typed CodecDecodeError (a ValueError and
    DecodeError subclass) — never an untyped IndexError/struct.error
    escaping from the Reader."""
    from ..errors import CodecDecodeError

    try:
        return _decode_changes_inner(buf)
    except CodecDecodeError:
        raise
    except (IndexError, struct.error, UnicodeDecodeError, ValueError,
            KeyError, OverflowError) as e:
        raise CodecDecodeError(
            f"malformed updates payload ({type(e).__name__}: {e})"
        ) from e


def _decode_changes_inner(buf: bytes) -> List[Change]:
    peers, keys, cids, r = read_tables(buf)
    n_changes = r.varint()
    metas = []
    prev_ts = 0
    for _ in range(n_changes):
        peer = peers[r.varint()]
        ctr = r.zigzag()
        lamport = r.zigzag()
        ts = prev_ts + r.zigzag()
        prev_ts = ts
        deps = Frontiers(ID(peers[r.varint()], r.zigzag()) for _ in range(r.varint()))
        msg = r.str_() if r.u8() else None
        n_ops = r.varint()
        metas.append((peer, ctr, lamport, ts, deps, msg, n_ops))
    out: List[Change] = []
    for peer, ctr, lamport, ts, deps, msg, n_ops in metas:
        ops: List[Op] = []
        counter = ctr
        for _ in range(n_ops):
            op = _read_op(r, peers, keys, cids, counter)
            ops.append(op)
            counter = op.ctr_end
        out.append(Change(ID(peer, ctr), lamport, deps, ops, ts, msg))
    return out


def _read_op(r: Reader, peers, keys, cids, counter: int) -> Op:
    cid = cids[r.varint()]
    kind = r.u8()
    if kind == K_MAP_SET:
        content: Any = MapSet(keys[r.varint()], _read_value(r, cids))
    elif kind == K_MAP_DEL:
        content = MapSet(keys[r.varint()], None, True)
    elif kind == K_INSERT_TEXT:
        parent = _read_parent(r, peers)
        side = Side(r.u8())
        content = SeqInsert(parent, side, r.str_())
    elif kind == K_INSERT_VALUES:
        parent = _read_parent(r, peers)
        side = Side(r.u8())
        content = SeqInsert(parent, side, tuple(_read_value(r, cids) for _ in range(r.varint())))
    elif kind == K_INSERT_ANCHOR:
        parent = _read_parent(r, peers)
        side = Side(r.u8())
        key = keys[r.varint()]
        value = _read_value(r, cids)
        is_start = bool(r.u8())
        info = r.varint()
        content = SeqInsert(parent, side, StyleAnchor(key, value, is_start, info))
    elif kind == K_DELETE:
        spans = []
        for _ in range(r.varint()):
            p = peers[r.varint()]
            s = r.zigzag()
            ln = r.varint()
            spans.append(IdSpan(p, s, s + ln))
        content = SeqDelete(tuple(spans))
    elif kind == K_TREE:
        target = TreeID(peers[r.varint()], r.zigzag())
        flags = r.u8()
        parent_t = TreeID(peers[r.varint()], r.zigzag()) if flags & 4 else None
        position = r.bytes_() if flags & 8 else None
        content = TreeMove(target, parent_t, position, bool(flags & 1), bool(flags & 2))
    elif kind == K_COUNTER:
        content = CounterIncr(r.f64())
    elif kind == K_MSET:
        content = MovableSet(ID(peers[r.varint()], r.zigzag()), _read_value(r, cids))
    elif kind == K_MMOVE:
        elem = ID(peers[r.varint()], r.zigzag())
        parent = _read_parent(r, peers)
        content = MovableMove(elem, parent, Side(r.u8()))
    elif kind == K_UNKNOWN:
        content = UnknownContent(r.varint(), r.bytes_())
    else:
        raise ValueError(f"bad op kind {kind}")
    return Op(counter, cid, content)
