"""loro-tpu: a TPU-native CRDT framework with the capabilities of Loro.

Collaborative JSON containers (Fugue rich text, List, MovableList,
LWW-Map, MovableTree, Counter) with causal-DAG history, version vectors,
time travel, snapshots and a columnar wire format.  The merge engine is
reformulated as JAX/XLA kernels over columnar op arrays and vmapped
across documents (loro_tpu.parallel.fleet) so a collaboration backend
reconciles an entire document fleet in one XLA launch.
"""

from .core.ids import ContainerID, ContainerType, ID, IdSpan, TreeID
from .core.version import Frontiers, VersionRange, VersionVector
from .core.change import Change, Op, Side
from .doc import (
    DecodeError,
    EncodeMode,
    ExportMode,
    ImportStatus,
    LoroDoc,
    LoroError,
)
from .event import (
    ContainerDiff,
    CounterDiff,
    Delete,
    Delta,
    DocDiff,
    EventTriggerKind,
    Insert,
    MapDiff,
    Retain,
    TreeDiff,
    TreeDiffAction,
    TreeDiffItem,
)
from .models.handlers import (
    CounterHandler,
    Handler,
    ListHandler,
    MapHandler,
    MovableListHandler,
    TextHandler,
    TreeHandler,
)
from . import obs
from . import persist
from . import resilience
from . import sync
from .awareness import Awareness, EphemeralStore
from .codec.json_schema import RedactError, redact_json_updates
from .cursor import AbsolutePosition, Cursor, CursorSide, get_cursor, get_cursor_pos
from .undo import UndoManager

__version__ = "0.1.0"

__all__ = [
    "LoroDoc",
    "LoroError",
    "DecodeError",
    "RedactError",
    "redact_json_updates",
    "ExportMode",
    "EncodeMode",
    "ImportStatus",
    "ContainerID",
    "ContainerType",
    "ID",
    "IdSpan",
    "TreeID",
    "Frontiers",
    "VersionVector",
    "VersionRange",
    "Change",
    "Op",
    "Side",
    "Delta",
    "Retain",
    "Insert",
    "Delete",
    "MapDiff",
    "TreeDiff",
    "TreeDiffAction",
    "TreeDiffItem",
    "CounterDiff",
    "DocDiff",
    "ContainerDiff",
    "EventTriggerKind",
    "TextHandler",
    "ListHandler",
    "MapHandler",
    "MovableListHandler",
    "TreeHandler",
    "CounterHandler",
    "Handler",
    "UndoManager",
    "Cursor",
    "CursorSide",
    "AbsolutePosition",
    "get_cursor",
    "get_cursor_pos",
    "Awareness",
    "EphemeralStore",
    "obs",
    "persist",
    "resilience",
]
