"""Runtime configuration (reference: crates/loro-internal/src/configure.rs)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict


@dataclass
class Configure:
    record_timestamp: bool = False
    merge_interval_s: int = 1000  # change RLE-merge window (reference default 1000s)
    editable_detached_mode: bool = False
    hide_empty_root_containers: bool = False
    # style expand behavior per key: "after" (default), "before", "both", "none"
    text_style_config: Dict[str, str] = field(default_factory=dict)
    # expand behavior for keys absent from text_style_config
    # (reference: LoroDoc::config_default_text_style)
    default_text_style: str = "after"
    # tree sibling positions: fractional indexes on create/move
    # (reference: Tree::enable/disable_fractional_index)
    fractional_index_enabled: bool = True
    fractional_index_jitter: int = 0
