"""OpLog: append-only causal op history.

reference: crates/loro-internal/src/oplog.rs + oplog/pending_changes.rs +
oplog/change_store.rs.  Host-side store: per-peer sorted change lists
(the columnar block encoding lives in loro_tpu/codec/; the device-facing
SoA extraction lives in loro_tpu/ops/columnar.py).
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.change import Change, Op, SeqInsert
from ..core.ids import ID, Counter, Lamport, PeerID
from ..core.version import Frontiers, VersionRange, VersionVector
from .dag import AppDag


@dataclass
class PendingChanges:
    """Changes whose deps aren't satisfied yet, keyed by a missing dep id.
    reference: oplog/pending_changes.rs."""

    by_missing: Dict[ID, List[Change]] = field(default_factory=dict)

    def park(self, missing: ID, change: Change) -> None:
        self.by_missing.setdefault(missing, []).append(change)

    def take_unlocked(self, vv: VersionVector) -> List[Change]:
        """Pop every parked change whose trigger dep is now satisfied."""
        out: List[Change] = []
        for key in [k for k in self.by_missing if vv.includes(k)]:
            out.extend(self.by_missing.pop(key))
        return out

    def pending_range(self) -> VersionRange:
        vr = VersionRange()
        for lst in self.by_missing.values():
            for ch in lst:
                vr.extend_to_include(ch.id_span())
        return vr

    def __len__(self) -> int:
        return sum(len(v) for v in self.by_missing.values())


@dataclass
class ImportPlan:
    """Outcome of OpLog.plan_import: inserts in causal order + the
    pending store as it would look after commit."""

    inserts: List[Change]
    pending: Dict[ID, List[Change]]


class OpLog:
    """Append-only causal history: changes + DAG + pending queue."""

    def __init__(self) -> None:
        self.dag = AppDag()
        self.changes: Dict[PeerID, List[Change]] = {}
        self._starts: Dict[PeerID, List[Counter]] = {}
        self.pending = PendingChanges()
        self.next_lamport: Lamport = 0
        # the owning doc's Configure (None for bare oplogs in tests);
        # governs the local-commit RLE-merge window (reference:
        # configure.rs merge_interval)
        self.config = None
        # block-chunked cold history (attached on fast-snapshot import;
        # reference: change_store.rs lazy blocks).  Peers hydrate into
        # self.changes on first op access; dag/vv come from block metas.
        self.cold = None  # Optional[BlockStore]
        self._cold_peers: set = set()
        # peers whose in-memory history diverges from the cold blocks
        # (snapshot export re-encodes these; clean peers reuse raw)
        self._dirty_peers: set = set()

    # -- cold store (lazy blocks) --------------------------------------
    def attach_cold_store(self, store) -> None:
        """Adopt a decoded BlockStore as this (empty) oplog's history:
        register dag spans from block metas WITHOUT decoding any op
        payload.  reference: fast_snapshot.rs installs oplog bytes
        directly; change blocks parse lazily."""
        from ..errors import DecodeError

        assert not self.changes and self.cold is None, "attach requires empty oplog"
        metas = sorted(store.iter_metas(), key=lambda m: (m[3], m[0], m[1]))
        # dep-closure check before touching the dag: every dep must be
        # covered by the blocks themselves or the shallow floor (the
        # replaced import_changes path parked dep-missing changes; a
        # snapshot with dangling deps is malformed, not pending)
        full_vv = self.dag.shallow_since_vv.copy()
        for peer, cs, ce, _lam, _deps in metas:
            if ce > full_vv.get(peer):
                full_vv.set_end(peer, ce)
        for peer, cs, ce, _lam, deps in metas:
            for d in deps:
                if d.counter >= full_vv.get(d.peer):
                    raise DecodeError(
                        f"snapshot change (peer={peer}, ctr={cs}) depends on "
                        f"{d} which no block covers"
                    )
        for peer in store.peers():
            bl = store.blocks[peer]
            first = bl[0].ctr_start
            floor = self.dag.shallow_since_vv.get(peer)
            if first != floor:
                raise DecodeError(
                    f"peer {peer} history starts at {first}, expected {floor}"
                )
            # no intra-peer gaps (BlockStore.decode also checks; this
            # covers hand-built stores so the dag never gets a hole)
            for a, b in zip(bl, bl[1:]):
                if a.ctr_end != b.ctr_start:
                    raise DecodeError(f"peer {peer} history has a gap at {a.ctr_end}")
        for peer, cs, ce, lam, deps in metas:
            self.dag.add_node(peer, cs, ce, lam, tuple(deps))
            lam_end = lam + (ce - cs)
            if lam_end > self.next_lamport:
                self.next_lamport = lam_end
        self.cold = store
        self._cold_peers = set(store.peers())

    def _hydrate_peer(self, peer: PeerID) -> None:
        if peer not in self._cold_peers:
            return
        self._cold_peers.discard(peer)
        decoded = self.cold.changes_for_peer(peer)
        hot = self.changes.get(peer, [])
        assert not hot, "hot changes appeared before hydration"
        self.changes[peer] = decoded
        self._starts[peer] = [ch.ctr_start for ch in decoded]

    def _hydrate_all(self) -> None:
        for peer in list(self._cold_peers):
            self._hydrate_peer(peer)

    def _history_peers(self):
        return set(self.changes) | self._cold_peers

    # -- queries ------------------------------------------------------
    @property
    def vv(self) -> VersionVector:
        return self.dag.vv

    @property
    def frontiers(self) -> Frontiers:
        return self.dag.frontiers

    def is_empty(self) -> bool:
        return not self.changes and not self._cold_peers and len(self.pending) == 0

    def change_at(self, id: ID) -> Optional[Change]:
        self._hydrate_peer(id.peer)
        starts = self._starts.get(id.peer)
        if not starts:
            return None
        i = bisect.bisect_right(starts, id.counter) - 1
        if i < 0:
            return None
        ch = self.changes[id.peer][i]
        return ch if ch.ctr_start <= id.counter < ch.ctr_end else None

    def total_ops(self) -> int:
        return self.vv.total_ops()

    def total_changes(self) -> int:
        hot = sum(len(v) for v in self.changes.values())
        cold = sum(
            len(b.metas)
            for p in self._cold_peers
            for b in self.cold.blocks.get(p, [])
        )
        return hot + cold

    # -- local commit -------------------------------------------------
    def next_counter(self, peer: PeerID) -> Counter:
        return self.vv.get(peer)

    def import_local_change(self, change: Change) -> None:
        """Single mutation point for local commits
        (reference: oplog.rs:191-220 insert_new_change).  Consecutive
        small commits RLE-merge into one stored Change when they form a
        linear extension within the merge interval."""
        assert change.ctr_start == self.vv.get(change.peer), "non-contiguous local change"
        for d in change.deps:
            assert self.dag.contains(d), f"local change dep missing: {d}"
        interval = self.config.merge_interval_s if self.config is not None else 1000
        self._hydrate_peer(change.peer)
        self._dirty_peers.add(change.peer)
        lst = self.changes.get(change.peer)
        if lst and lst[-1].can_merge_right(change, interval):
            lst[-1].ops.extend(change.ops)
            self._register_span(change)
            return
        self._insert_change(change)

    def plan_backfill(self, changes: Iterable[Change]) -> Optional[Dict[PeerID, List[Change]]]:
        """Shallow-history upgrade, planning half (pure — no mutation):
        when the incoming batch fully covers every peer's trimmed range
        [0, floor_p) with structurally-valid changes, return the spliced
        plan; else None (reference semantics:
        should_import_snapshot_before_shallow — a full snapshot arriving
        after a shallow one un-shallows the doc).  All-or-nothing."""
        floor = self.dag.shallow_since_vv
        if not len(floor):
            return None
        # collect pre-floor slices per peer
        pieces: Dict[PeerID, Dict[Counter, Change]] = {}
        for ch in changes:
            fp = floor.get(ch.peer)
            if fp <= 0 or ch.ctr_start >= fp:
                continue
            piece = _slice_change_end(ch, fp) if ch.ctr_end > fp else ch
            pieces.setdefault(ch.peer, {})[piece.ctr_start] = piece
        # coverage: every floor peer's [0, floor_p) must tile exactly
        plan: Dict[PeerID, List[Change]] = {}
        for p, fp in floor.items():
            if fp <= 0:
                continue
            have = sorted(pieces.get(p, {}).values(), key=lambda c: c.ctr_start)
            at = 0
            for ch in have:
                if ch.ctr_start != at:
                    return None
                at = ch.ctr_end
            if at != fp:
                return None
            plan[p] = have
        # structural validation — these changes bypass plan_import (the
        # floor vv marks their span as known), so vet them here: deps
        # inside the covered history, lamports monotone per peer and
        # >= every dep's lamport end, and consistent with the existing
        # floor nodes.  A violation means a malformed blob: no upgrade.
        full_vv = self.vv.copy()

        def lamport_end_of(d: ID) -> Optional[int]:
            node = self.dag.node_at(d)
            if node is not None:
                return node.lamport_of(d.counter) + 1
            lst = plan.get(d.peer)
            if lst is None:
                return None
            for c in lst:
                if c.ctr_start <= d.counter < c.ctr_end:
                    return c.lamport + (d.counter - c.ctr_start) + 1
            return None

        for p, lst in plan.items():
            prev_end = 0
            for ch in lst:
                if ch.lamport < prev_end:
                    return None
                prev_end = ch.lamport_end
                for d in ch.deps:
                    if not full_vv.includes(d):
                        return None
                    dl = lamport_end_of(d)
                    if dl is None or ch.lamport < dl:
                        return None
            # the first retained (post-floor) node must sit at/after the
            # backfilled lamport range
            floor_node = self.dag.node_at(ID(p, floor.get(p)))
            if floor_node is not None and floor_node.lamport < prev_end:
                return None
        return plan

    def commit_backfill(self, plan: Dict[PeerID, List[Change]]) -> None:
        """Commit a plan_backfill result: splice the pre-floor changes
        below the per-peer lists, rebuild dag nodes, drop the shallow
        root.  Call only after the rest of the import batch has been
        validated (leave-untouched-on-failure contract)."""
        for p, lst in plan.items():
            self._hydrate_peer(p)
            self._dirty_peers.add(p)
            cur = self.changes.get(p, [])
            self.changes[p] = lst + cur
            self._starts[p] = [c.ctr_start for c in self.changes[p]]
            for ch in lst:
                if ch.lamport_end > self.next_lamport:
                    self.next_lamport = ch.lamport_end
        self.dag.backfill_and_unshallow(
            {p: [(c.ctr_start, c.ctr_end, c.lamport, tuple(c.deps)) for c in lst] for p, lst in plan.items()}
        )

    def _register_span(self, ch: Change) -> None:
        """DAG/lamport bookkeeping shared by fresh inserts and RLE-merges."""
        self.dag.add_node(ch.peer, ch.ctr_start, ch.ctr_end, ch.lamport, tuple(ch.deps))
        if ch.lamport_end > self.next_lamport:
            self.next_lamport = ch.lamport_end

    # -- remote import ------------------------------------------------
    def plan_import(self, changes: Iterable[Change]) -> "ImportPlan":
        """Pure planning pass: decide which changes would insert (in
        causal order, trimmed), which would park, and what the pending
        store would become — WITHOUT mutating anything.  The doc layer
        validates the planned inserts against known element ids before
        committing (a corrupt payload whose deps lie must fail typed,
        leaving oplog AND state untouched — reference: import rollback,
        oplog.rs)."""
        vv = self.vv.copy()
        pending = PendingChanges(
            by_missing={k: list(v) for k, v in self.pending.by_missing.items()}
        )
        queue: List[Change] = list(changes)
        inserts: List[Change] = []
        progress = True
        while progress:
            progress = False
            next_queue: List[Change] = []
            # causal linearization attempt: sort by (lamport, peer, ctr)
            queue.sort(key=lambda c: (c.lamport, c.peer, c.ctr_start))
            for ch in queue:
                known_end = vv.get(ch.peer)
                if ch.ctr_end <= known_end:
                    continue  # fully known — dedup (trim_the_known_part)
                if ch.ctr_start > known_end:
                    # a gap within the same peer: park on the previous op
                    pending.park(ID(ch.peer, ch.ctr_start - 1), ch)
                    continue
                if ch.ctr_start < known_end:
                    ch = self._trim_known_prefix(ch, known_end)
                missing = next((d for d in ch.deps if not vv.includes(d)), None)
                if missing is not None:
                    pending.park(missing, ch)
                    continue
                inserts.append(ch)
                vv.set_end(ch.peer, max(vv.get(ch.peer), ch.ctr_end))
                progress = True
                # unlock parked changes whose trigger is now satisfied
                next_queue.extend(pending.take_unlocked(vv))
            queue = next_queue
        return ImportPlan(inserts=inserts, pending=pending.by_missing)

    def commit_import(self, plan: "ImportPlan") -> Tuple[List[Change], VersionRange]:
        for ch in plan.inserts:
            self._insert_change(ch)
        self.pending.by_missing = plan.pending
        return plan.inserts, self.pending.pending_range()

    def import_changes(self, changes: Iterable[Change]) -> Tuple[List[Change], VersionRange]:
        """Import remote changes: dedup known spans, park dep-missing ones,
        apply the rest in causal order.  Returns (applied changes in causal
        order, still-pending version range).
        reference: oplog.rs apply_decoded_changes_to_oplog + pending loop."""
        return self.commit_import(self.plan_import(changes))

    def _trim_known_prefix(self, ch: Change, known_end: Counter) -> Change:
        return trim_known_prefix(ch, known_end)

    def _insert_change(self, ch: Change) -> None:
        self._hydrate_peer(ch.peer)
        self._dirty_peers.add(ch.peer)
        self.changes.setdefault(ch.peer, []).append(ch)
        self._starts.setdefault(ch.peer, []).append(ch.ctr_start)
        self._register_span(ch)

    # -- export -------------------------------------------------------
    def changes_since(self, vv: VersionVector) -> List[Change]:
        """All changes (sliced) not included in `vv`, in causal order.
        reference: ChangeStore.export_blocks_from."""
        out: List[Change] = []
        for peer in list(self._history_peers()):
            start = vv.get(peer)
            if start >= self.vv.get(peer):
                continue  # fully known: no need to hydrate
            self._hydrate_peer(peer)
            lst = self.changes.get(peer, [])
            if not lst:
                continue
            i = bisect.bisect_right(self._starts[peer], start) - 1
            i = max(i, 0)
            for ch in lst[i:]:
                if ch.ctr_end <= start:
                    continue
                out.append(ch if ch.ctr_start >= start else self._trim_known_prefix_view(ch, start))
        out.sort(key=lambda c: (c.lamport, c.peer, c.ctr_start))
        return out

    def _trim_known_prefix_view(self, ch: Change, start: Counter) -> Change:
        return self._trim_known_prefix(ch, start)

    def changes_between(self, from_vv: VersionVector, to_vv: VersionVector) -> List[Change]:
        """Changes (sliced) with counters in [from_vv, to_vv) per peer, in
        causal order.  `to_vv` must be causally closed (a valid version)."""
        out: List[Change] = []
        for peer in list(self._history_peers()):
            lo = from_vv.get(peer)
            hi = to_vv.get(peer)
            if hi <= lo:
                continue  # cold peers outside the range stay cold
            self._hydrate_peer(peer)
            lst = self.changes.get(peer, [])
            if not lst:
                continue
            i = bisect.bisect_right(self._starts[peer], lo) - 1
            i = max(i, 0)
            for ch in lst[i:]:
                if ch.ctr_end <= lo:
                    continue
                if ch.ctr_start >= hi:
                    break
                if ch.ctr_start < lo:
                    ch = self._trim_known_prefix(ch, lo)
                if ch.ctr_end > hi:
                    ch = _slice_change_end(ch, hi)
                out.append(ch)
        out.sort(key=lambda c: (c.lamport, c.peer, c.ctr_start))
        return out

    def changes_in_causal_order(self) -> List[Change]:
        self._hydrate_all()
        out = [ch for lst in self.changes.values() for ch in lst]
        out.sort(key=lambda c: (c.lamport, c.peer, c.ctr_start))
        return out

    def iter_ops_causal(self, since: Optional[VersionVector] = None):
        """Yield (change, op) pairs in a causal linear extension."""
        chs = self.changes_in_causal_order() if since is None else self.changes_since(since)
        for ch in chs:
            for op in ch.ops:
                yield ch, op

    def export_block_store(self):
        """Sealed blocks covering the full history.  Peers untouched
        since cold-attach reuse their raw compressed blocks (no decode,
        no re-encode); dirty/hot peers seal fresh blocks."""
        from .change_store import BlockStore, blocks_from_changes

        st = BlockStore()
        for peer in self._history_peers():
            if (
                self.cold is not None
                and peer in self.cold.blocks
                and peer not in self._dirty_peers
            ):
                st.blocks[peer] = self.cold.blocks[peer]
            else:
                self._hydrate_peer(peer)
                chs = self.changes.get(peer, [])
                if chs:
                    st.blocks[peer] = blocks_from_changes(chs)
        return st

    def compact(self) -> None:
        """Seal all hot history into compressed blocks and free the
        decoded Change objects (reference: compact_change_store).  The
        next access hydrates from the blocks."""
        store = self.export_block_store()
        # drop decoded caches inside reused blocks so memory actually
        # shrinks (they were populated for dirty/hot peers)
        for bl in store.blocks.values():
            for b in bl:
                b._changes = None
        self.cold = store
        self.cold.decoded_blocks = 0
        self._cold_peers = set(store.peers())
        self._dirty_peers = set()
        self.changes = {}
        self._starts = {}

    def diagnose_size(self) -> Dict[str, int]:
        """reference: oplog.rs:675 diagnose_size."""
        self._hydrate_all()
        return {
            "changes": self.total_changes(),
            "ops": sum(len(c.ops) for lst in self.changes.values() for c in lst),
            "atoms": self.total_ops(),
            "dag_nodes": self.dag.total_changes(),
            "pending": len(self.pending),
        }


def trim_known_prefix(ch: Change, known_end: Counter) -> Change:
    """The one known-prefix trim rule: drop ops at/below ``known_end``,
    slice the straddling run, and rewrite id/lamport/deps to the trim
    point.  Shared by remote import (``plan_import``), ranged export
    (``changes_since``/``changes_between``) and the sync read plane
    (``ops/export_batch.py``) — the byte-identity of batched device
    pulls rests on all three trimming identically."""
    ops: List[Op] = []
    for op in ch.ops:
        if op.ctr_end <= known_end:
            continue
        if op.counter < known_end:
            assert isinstance(op.content, SeqInsert)
            op = _slice_run(op, known_end)
        ops.append(op)
    off = known_end - ch.ctr_start
    return Change(
        id=ID(ch.peer, known_end),
        lamport=ch.lamport + off,
        deps=Frontiers([ID(ch.peer, known_end - 1)]),
        ops=ops,
        timestamp=ch.timestamp,
        message=ch.message,
    )


def _slice_change_end(ch: Change, end: Counter) -> Change:
    """Restrict a change to counters < end (for ranged export/checkout)."""
    ops: List[Op] = []
    for op in ch.ops:
        if op.counter >= end:
            break
        if op.ctr_end > end:
            c = op.content
            assert isinstance(c, SeqInsert)
            keep = end - op.counter
            op = Op(op.counter, op.container, SeqInsert(c.parent, c.side, c.content[:keep]))
        ops.append(op)
    return Change(ch.id, ch.lamport, ch.deps, ops, ch.timestamp, ch.message)


def _slice_run(op: Op, new_start: Counter) -> Op:
    """Slice a SeqInsert run so it starts at `new_start`.  The sliced run's
    first element's parent is the previous element of the original run."""
    c: SeqInsert = op.content  # type: ignore[assignment]
    from ..core.change import Side

    off = new_start - op.counter
    # NOTE: run element ids are (peer, op.counter + j); we don't know peer
    # here, so the caller-facing invariant is that slicing happens at the
    # Change level where peer is known.  We re-derive parent at apply time:
    # element j>0's parent is always (peer, counter-1) implicitly, so the
    # sliced op keeps parent=None and a flag via counter offset.
    return Op(new_start, op.container, SeqInsert(_RUN_CONT, Side.Right, c.content[off:]))


class _RunCont:
    """Sentinel parent meaning "previous counter of the same peer"
    (restores the implicit right-spine parent after run slicing)."""

    def __repr__(self) -> str:
        return "<run-cont>"


_RUN_CONT = _RunCont()
