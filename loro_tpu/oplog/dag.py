"""AppDag: the causal DAG over change spans.

reference: crates/loro-internal/src/{dag.rs,oplog/loro_dag.rs}.

Key simplification vs the reference: because each peer's ops are
causally totally ordered, a causally-closed op set is exactly a
VersionVector, so the common ancestor of two versions is the pointwise
meet of their VVs (the reference reaches the same result via a
lamport-ordered heap walk, dag.rs:318-517, because it avoids
materializing VVs; we cache VVs per node instead — small host data).
"""
from __future__ import annotations

import bisect
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.ids import ID, Counter, Lamport, PeerID
from ..core.version import Frontiers, VersionVector


class DiffMode(enum.IntEnum):
    """Fast-path ladder for the merge engine (reference diff_calc.rs:72-103).

    Checkout: arbitrary version jump (may retreat).
    Import:   merge of concurrent history (forward only, from LCA).
    Linear:   imported ops are a causal linear extension of the current
              version — no concurrency, direct state apply.
    """

    Checkout = 0
    Import = 1
    ImportGreaterUpdates = 2
    Linear = 3


@dataclass
class DagNode:
    """One change span in the DAG (reference AppDagNode, loro_dag.rs:99)."""

    peer: PeerID
    ctr_start: Counter
    ctr_end: Counter
    lamport: Lamport
    deps: Tuple[ID, ...]
    _vv: Optional[VersionVector] = field(default=None, repr=False)  # closure cache

    @property
    def id(self) -> ID:
        return ID(self.peer, self.ctr_start)

    @property
    def last_id(self) -> ID:
        return ID(self.peer, self.ctr_end - 1)

    @property
    def lamport_end(self) -> Lamport:
        return self.lamport + (self.ctr_end - self.ctr_start)

    def lamport_of(self, counter: Counter) -> Lamport:
        return self.lamport + (counter - self.ctr_start)


class AppDag:
    """Per-peer sorted lists of DagNodes + frontier/VV tracking."""

    def __init__(self) -> None:
        self._nodes: Dict[PeerID, List[DagNode]] = {}
        self._starts: Dict[PeerID, List[Counter]] = {}  # parallel ctr_start arrays
        self.vv = VersionVector()
        self.frontiers = Frontiers()
        # shallow-history root (set when importing a shallow snapshot):
        # ops before this version are not present in the log.
        self.shallow_since_vv = VersionVector()
        self.shallow_since_frontiers = Frontiers()

    # -- lookup -------------------------------------------------------
    def node_at(self, id: ID) -> Optional[DagNode]:
        starts = self._starts.get(id.peer)
        if not starts:
            return None
        i = bisect.bisect_right(starts, id.counter) - 1
        if i < 0:
            return None
        n = self._nodes[id.peer][i]
        return n if n.ctr_start <= id.counter < n.ctr_end else None

    def lamport_of(self, id: ID) -> Lamport:
        n = self.node_at(id)
        if n is None:
            raise KeyError(f"id not in dag: {id}")
        return n.lamport_of(id.counter)

    def contains(self, id: ID) -> bool:
        return self.vv.includes(id)

    # -- mutation -----------------------------------------------------
    def add_node(
        self, peer: PeerID, ctr_start: Counter, ctr_end: Counter, lamport: Lamport, deps: Tuple[ID, ...]
    ) -> None:
        """Append a change span.  Caller guarantees deps are satisfied and
        counters are contiguous per peer (OpLog enforces)."""
        lst = self._nodes.setdefault(peer, [])
        starts = self._starts.setdefault(peer, [])
        # RLE-merge with previous node when it's a simple linear extension
        if (
            lst
            and lst[-1].ctr_end == ctr_start
            and lst[-1].lamport_end == lamport
            and len(deps) == 1
            and deps[0] == lst[-1].last_id
        ):
            lst[-1].ctr_end = ctr_end
            lst[-1]._vv = None
        else:
            lst.append(DagNode(peer, ctr_start, ctr_end, lamport, tuple(deps)))
            starts.append(ctr_start)
        # update version + frontiers
        self.vv.set_end(peer, max(self.vv.get(peer), ctr_end))
        new_heads = [i for i in self.frontiers if not (i in deps)]
        new_heads.append(ID(peer, ctr_end - 1))
        self.frontiers = Frontiers(new_heads)

    def backfill_and_unshallow(
        self,
        spans_by_peer: Dict[PeerID, List[Tuple[Counter, Counter, Lamport, Tuple[ID, ...]]]],
    ) -> None:
        """Shallow-history upgrade (OpLog.backfill_below_floor commits
        through here): splice pre-floor spans below the existing
        per-peer node lists, drop the shallow root, and invalidate every
        memoized closure — cached node VVs were computed with the old
        floor folded in and would over-approximate real causality."""
        for p, spans in spans_by_peer.items():
            new = [DagNode(p, cs, ce, lam, deps) for cs, ce, lam, deps in spans]
            cur = self._nodes.get(p, [])
            self._nodes[p] = new + cur
            self._starts[p] = [n.ctr_start for n in self._nodes[p]]
        self.shallow_since_vv = VersionVector()
        self.shallow_since_frontiers = Frontiers()
        for lst in self._nodes.values():
            for n in lst:
                n._vv = None
        cache = getattr(self, "_f2vv_cache", None)
        if cache:
            cache.clear()

    def update_frontiers_on_new_change(self, change_last_id: ID, deps: Frontiers) -> None:
        heads = [i for i in self.frontiers if i not in set(deps)]
        heads.append(change_last_id)
        self.frontiers = Frontiers(heads)

    # -- closures -----------------------------------------------------
    def node_vv(self, node: DagNode) -> VersionVector:
        """Causal closure of node's *full span* as a VV (cached).
        Iterative DFS to avoid Python recursion limits on long chains."""
        if node._vv is not None:
            return node._vv
        stack = [node]
        while stack:
            n = stack[-1]
            if n._vv is not None:
                stack.pop()
                continue
            pending = []
            for d in n.deps:
                dn = self.node_at(d)
                if dn is None:
                    # dep below the shallow root: treat its closure as the
                    # shallow root vv (already folded into shallow_since_vv)
                    continue
                if dn._vv is None:
                    pending.append(dn)
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            vv = VersionVector()
            vv.merge(self.shallow_since_vv)
            for d in n.deps:
                dn = self.node_at(d)
                if dn is None:
                    continue
                dvv = dn._vv.copy()
                # dep points at a counter inside dn's span: clamp
                dvv.set_end(dn.peer, d.counter + 1)
                # note: clamping below dn's own closure start is safe only
                # because within a peer counters are causally ordered and
                # dn._vv already includes full closures of dn's deps.
                vv.merge(dvv)
                vv.set_end(d.peer, max(vv.get(d.peer), d.counter + 1))
            vv.set_end(n.peer, max(vv.get(n.peer), n.ctr_end))
            n._vv = vv
        return node._vv

    def id_vv(self, id: ID) -> VersionVector:
        """Closure of a single id (inclusive)."""
        n = self.node_at(id)
        if n is None:
            raise KeyError(f"id not in dag: {id}")
        vv = self.node_vv(n).copy()
        vv.set_end(id.peer, id.counter + 1)
        return vv

    def frontiers_to_vv(self, f: Frontiers) -> VersionVector:
        """reference: loro_dag.rs:1192.  Memoized: the dag is
        append-only, so a frontier's closure never changes once all its
        heads exist."""
        if f == self.shallow_since_frontiers and not f.is_empty():
            return self.shallow_since_vv.copy()
        cache = getattr(self, "_f2vv_cache", None)
        if cache is None:
            cache = self._f2vv_cache = {}
        hit = cache.get(f)
        if hit is not None:
            return hit.copy()
        vv = VersionVector()
        vv.merge(self.shallow_since_vv)
        for id in f:
            vv.merge(self.id_vv(id))
        if len(cache) >= 64:
            cache.pop(next(iter(cache)))
        cache[f] = vv.copy()
        return vv

    def set_shallow_root(self, vv: VersionVector, f: Frontiers) -> None:
        """Install the shallow replay floor.  Clears the frontier-
        closure memo — cached closures were computed against the old
        floor."""
        self.shallow_since_vv = vv.copy()
        self.shallow_since_frontiers = f
        self.vv = vv.copy()
        self.frontiers = f
        cache = getattr(self, "_f2vv_cache", None)
        if cache:
            cache.clear()

    def vv_to_frontiers(self, vv: VersionVector) -> Frontiers:
        """reference: loro_dag.rs:1269.  Heads = last id per peer that is
        not dominated by another head's closure.

        Dominance probes the CACHED node closures directly (no per-pair
        VV copies): a mid-span id's cross-peer closure equals its
        node's — RLE merge only absorbs dep-on-self extensions, so a
        merged node's deps all hang off its first change."""
        if len(self.shallow_since_vv) and vv <= self.shallow_since_vv:
            # at/below the replay floor: the floor's own frontiers are
            # the only representable heads (per-peer last ids would
            # reference ids outside the dag)
            return self.shallow_since_frontiers
        cands: List[ID] = []
        for p, c in vv.items():
            if c > 0:
                cands.append(ID(p, c - 1))
        if len(cands) <= 1:
            return Frontiers(cands)
        nodes = [self.node_at(i) for i in cands]
        heads = []
        for i, id in enumerate(cands):
            dominated = False
            for j, other in enumerate(cands):
                if i == j:
                    continue
                n = nodes[j]
                if n is None:
                    # other is at/below the shallow root: its closure is
                    # within shallow_since_vv, which every candidate vv
                    # already includes — cannot dominate a live head
                    continue
                closure = self.node_vv(n)
                cover = closure.get(id.peer)
                if id.counter < cover:
                    dominated = True
                    break
            if not dominated:
                heads.append(id)
        return Frontiers(heads)

    # -- ancestry -----------------------------------------------------
    def find_common_ancestor(
        self, a: Frontiers, b: Frontiers
    ) -> Tuple[Frontiers, VersionVector, DiffMode]:
        """Common-ancestor version of two frontiers + the fast-path mode.
        reference: dag.rs:318-517 (heap walk); here: VV meet."""
        va = self.frontiers_to_vv(a)
        vb = self.frontiers_to_vv(b)
        meet = va.meet(vb)
        if va <= vb:
            # a is an ancestor of b: forward-only linear extension
            return a, meet, DiffMode.Linear
        if vb <= va:
            return b, meet, DiffMode.Checkout  # b behind a: retreat needed
        return self.vv_to_frontiers(meet), meet, DiffMode.Import

    # -- iteration ----------------------------------------------------
    def iter_causal_nodes(self) -> List[DagNode]:
        """All nodes in a causal linear extension ((lamport, peer, ctr))."""
        all_nodes = [n for lst in self._nodes.values() for n in lst]
        all_nodes.sort(key=lambda n: (n.lamport, n.peer, n.ctr_start))
        return all_nodes

    def total_changes(self) -> int:
        return sum(len(v) for v in self._nodes.values())
