"""Block-chunked lazy change store.

reference: crates/loro-internal/src/oplog/change_store.rs:41-65 (change
blocks target ~4KB, keyed (peer, counter), lazily parsed) and
crates/kv-store (SSTable-style blocks, LZ4 + checksum per block).

TPU-first re-design: history is cold data for the merge engine — the
device path consumes columnar extracts, not Change objects — so the
store's job is (a) cheap snapshot export (reuse already-compressed
blocks without re-encoding), (b) cheap import (attach block headers +
dag metadata without decoding op payloads), and (c) per-peer lazy
hydration when replay/diff actually needs ops.

Layout (BlockStore.encode):
  varint n_blocks
  per block:
    u64le peer, zigzag ctr_start, zigzag ctr_end
    varint n_changes
    change meta (relative to block): per change
      zigzag ctr_start delta, varint atom_len, varint lamport delta?
      -> see _encode_block_meta: explicit (ctr_start, ctr_end, lamport,
         deps) so the dag attaches without touching the payload
    u32le crc32 of compressed payload
    varint len + bytes: zlib(encode_changes(block changes))

Compression is zlib (the stdlib's LZ77; reference uses LZ4 — same
role, no extra dependency) with a per-block crc32 (reference:
xxhash32).
"""
from __future__ import annotations

import zlib
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.change import Change
from ..core.ids import ID
from ..core.version import Frontiers

# target uncompressed payload bytes per block (reference: 4KB;
# change_store.rs:41-44 — 128B in its tests)
BLOCK_TARGET = 4096


class Block:
    """One sealed change block: compressed payload + enough metadata
    (spans, lamports, deps) to register in the AppDag without decode."""

    __slots__ = (
        "peer",
        "ctr_start",
        "ctr_end",
        "metas",
        "raw",
        "crc",
        "_changes",
    )

    def __init__(
        self,
        peer: int,
        ctr_start: int,
        ctr_end: int,
        metas: List[Tuple[int, int, int, Tuple[ID, ...]]],
        raw: bytes,
        crc: int,
        changes: Optional[List[Change]] = None,
    ):
        self.peer = peer
        self.ctr_start = ctr_start
        self.ctr_end = ctr_end
        # per change: (ctr_start, ctr_end, lamport, deps)
        self.metas = metas
        self.raw = raw
        self.crc = crc
        self._changes = changes

    def changes(self) -> List[Change]:
        """Decode (and cache) this block's Change list.  Raises a typed
        DecodeError on corruption — lazy blocks surface decode failures
        at first access, not import time (same trade the reference's
        lazy on-disk blocks make); the per-block crc + meta cross-check
        below bound the blast radius to this block."""
        if self._changes is None:
            from ..codec.binary import decode_changes
            from ..errors import DecodeError

            if zlib.crc32(self.raw) != self.crc:
                raise DecodeError(
                    f"change block (peer={self.peer}, ctr={self.ctr_start}) "
                    "checksum mismatch"
                )
            try:
                decoded = decode_changes(zlib.decompress(self.raw))
            except DecodeError:
                raise
            except Exception as e:
                raise DecodeError(f"malformed change block: {e}") from e
            # decoded payload must agree with the metas the dag was
            # built from at attach time
            got = [(c.ctr_start, c.ctr_end, c.lamport) for c in decoded]
            want = [(cs, ce, lam) for (cs, ce, lam, _d) in self.metas]
            if got != want:
                raise DecodeError(
                    f"change block (peer={self.peer}) payload disagrees "
                    "with its header metas"
                )
            self._changes = decoded
        return self._changes

    @property
    def is_decoded(self) -> bool:
        return self._changes is not None


def _seal(changes: List[Change]) -> Block:
    from ..codec.binary import encode_changes

    payload = encode_changes(changes)
    raw = zlib.compress(payload, 6)
    metas = [
        (ch.ctr_start, ch.ctr_end, ch.lamport, tuple(ch.deps)) for ch in changes
    ]
    return Block(
        peer=changes[0].peer,
        ctr_start=changes[0].ctr_start,
        ctr_end=changes[-1].ctr_end,
        metas=metas,
        raw=raw,
        crc=zlib.crc32(raw),
        changes=list(changes),
    )


def blocks_from_changes(changes: Iterable[Change]) -> List[Block]:
    """Seal a peer-contiguous change list into ~BLOCK_TARGET blocks."""
    out: List[Block] = []
    cur: List[Change] = []
    cur_bytes = 0
    for ch in changes:
        # rough per-change size estimate: atoms dominate (1-4 bytes per
        # atom in the columnar codec); avoid encoding twice just to size
        est = 16 + ch.atom_len() * 2 + len(ch.deps) * 10
        if cur and cur_bytes + est > BLOCK_TARGET:
            out.append(_seal(cur))
            cur, cur_bytes = [], 0
        cur.append(ch)
        cur_bytes += est
    if cur:
        out.append(_seal(cur))
    return out


class BlockStore:
    """Per-peer sealed blocks, decoded lazily per peer.

    `decoded_blocks` counts payload decodes — tests assert laziness
    with it.
    """

    def __init__(self) -> None:
        self.blocks: Dict[int, List[Block]] = {}
        self.decoded_blocks = 0

    # -- build --------------------------------------------------------
    @staticmethod
    def from_changes_by_peer(changes_by_peer: Dict[int, List[Change]]) -> "BlockStore":
        st = BlockStore()
        for peer, chs in changes_by_peer.items():
            if chs:
                st.blocks[peer] = blocks_from_changes(chs)
        return st

    # -- queries ------------------------------------------------------
    def peers(self) -> List[int]:
        return list(self.blocks.keys())

    def cold_end(self, peer: int) -> int:
        bl = self.blocks.get(peer)
        return bl[-1].ctr_end if bl else 0

    def iter_metas(self):
        """(peer, ctr_start, ctr_end, lamport, deps) for every change,
        without decoding payloads."""
        for peer, bl in self.blocks.items():
            for b in bl:
                for (cs, ce, lam, deps) in b.metas:
                    yield peer, cs, ce, lam, deps

    def changes_for_peer(self, peer: int) -> List[Change]:
        out: List[Change] = []
        for b in self.blocks.get(peer, []):
            if not b.is_decoded:
                self.decoded_blocks += 1
            out.extend(b.changes())
        return out

    def total_changes(self) -> int:
        return sum(len(b.metas) for bl in self.blocks.values() for b in bl)

    # -- wire ---------------------------------------------------------
    def encode(self) -> bytes:
        from ..codec.binary import Writer

        w = Writer()
        all_blocks = [b for bl in self.blocks.values() for b in bl]
        w.varint(len(all_blocks))
        for b in all_blocks:
            w.u64le(b.peer)
            w.zigzag(b.ctr_start)
            w.zigzag(b.ctr_end)
            w.varint(len(b.metas))
            prev_end = b.ctr_start
            for (cs, ce, lam, deps) in b.metas:
                assert cs == prev_end, "non-contiguous changes in block"
                w.varint(ce - cs)
                w.varint(lam)
                w.varint(len(deps))
                for d in deps:
                    w.u64le(d.peer)
                    w.zigzag(d.counter)
                prev_end = ce
            w.u32le(b.crc)
            w.bytes_(b.raw)
        return bytes(w.buf)

    @staticmethod
    def decode(buf: bytes) -> "BlockStore":
        from ..codec.binary import Reader

        r = Reader(buf)
        st = BlockStore()
        n_blocks = r.varint()
        if n_blocks > 1 << 26:
            raise ValueError(f"implausible block count {n_blocks}")
        for _ in range(n_blocks):
            peer = r.u64le()
            cs0 = r.zigzag()
            ce0 = r.zigzag()
            n_changes = r.varint()
            if n_changes > 1 << 22:
                raise ValueError(f"implausible change count {n_changes}")
            metas = []
            cur = cs0
            for _ in range(n_changes):
                alen = r.varint()
                lam = r.varint()
                deps = tuple(
                    ID(r.u64le(), r.zigzag()) for _ in range(r.varint())
                )
                metas.append((cur, cur + alen, lam, deps))
                cur += alen
            if cur != ce0:
                raise ValueError("block span does not match change metas")
            crc = r.u32le()
            raw = r.bytes_()
            st.blocks.setdefault(peer, []).append(
                Block(peer, cs0, ce0, metas, raw, crc)
            )
        for bl in st.blocks.values():
            bl.sort(key=lambda b: b.ctr_start)
            for a, b in zip(bl, bl[1:]):
                if a.ctr_end != b.ctr_start:
                    raise ValueError("non-contiguous blocks for peer")
        return st
