"""Segmented append-only write-ahead log of resident ingest rounds.

The ResidentServer round journal is the CRDT oplog of the fleet path,
compactly encoded — but it lived only in RAM, so a process crash (the
normal case per the TPU-pool lottery in docs/RESILIENCE.md) lost every
round since birth.  This WAL is the durable form: one record per
APPLIED round, crc32-framed in the codec/binary.py Writer/Reader
envelope family, segment files rotated at every checkpoint so segments
at/under the checkpoint epoch can be deleted wholesale.

Reference shape: loro's L1 ChangeStore journals block-encoded changes
over a KV store (SURVEY §L1); the write-optimized-delta + periodic-
merge split follows the differential-store literature (arxiv
1109.6885) — the WAL is the delta store, checkpoints are the merged
read-optimized store.

Directory layout (under ``<durable_dir>/wal/``)::

    seg-00000001.log
    seg-00000002.log      <- rotated at a checkpoint
    ...

Segment file = 5-byte header ``"LTWL" u8:version`` then frames::

    u32le payload_len | u32le crc32(payload) | payload

Frame payload = ``u8 rtype`` + body (codec/binary Writer primitives):

- ``R_META``  — ``u8 meta_ver, str family, varint n_docs, u8 flags
  (bit0 auto_grow, bit1 host_fallback, bit2 group-commit fsync mode),
  varint n_caps, (str, varint)*``
  Construction caps: cold recovery (no valid checkpoint) rebuilds the
  server from this record.  Written as the FIRST record of EVERY
  segment so pruning old segments never loses it.
- ``R_ROUND`` — ``varint epoch, cid_opt, varint n_docs,
  (u8 present [, bytes_ update])*``.  Updates are the journal's frozen
  wire bytes (encode_changes output or the client payload as-is).
- ``R_CKPT``  — ``varint epoch, str filename``: marker that a
  checkpoint blob landed (inspect shows the ladder inline).

``cid_opt``: ``u8 0`` = None; ``u8 1, u8 ctype, str name`` = root;
``u8 2, u8 ctype, u64le peer, zigzag counter`` = normal.

Torn-tail policy (the crash contract): a bad frame — short header,
length past EOF, crc mismatch, malformed payload — in the NEWEST
segment is a torn tail: scanning stops there, and opening for append
truncates the file back to the last good frame (counted in
``persist.wal_torn_tail_truncations_total``).  The same damage in an
OLDER segment cannot be a torn write (later segments exist, so the
file was complete once) and raises a typed ``CodecDecodeError``.

Fault sites (resilience/faultinject.py): ``wal_write`` fires
``check()`` before each append (raise/delay); ``wal_torn_tail`` runs
the frame bytes through ``mangle()`` on their way to disk, so a
truncate fault writes a genuinely torn frame for reopen tests.
"""
from __future__ import annotations

import os
import struct
import time as _time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from ..codec.binary import Reader, Writer
from ..core.ids import ContainerID, ContainerType
from ..errors import CodecDecodeError, PersistError
from ..obs import flight
from ..obs import metrics as obs
from ..resilience import faultinject

faultinject.register_site(
    "wal_write", "persist.wal append: raise/delay before the frame "
    "reaches disk (durability-path failures)")
faultinject.register_site(
    "wal_torn_tail", "persist.wal append: mangle the frame bytes on "
    "their way to disk (a genuinely torn write for the reopen-"
    "tolerance tests)")

SEG_MAGIC = b"LTWL"
SEG_VERSION = 1
META_VERSION = 1

R_META = 0
R_ROUND = 1
R_CKPT = 2
R_PRUNE = 3  # round-bearing segments were deleted below this epoch

_FRAME_HDR = 8  # u32le len + u32le crc
_MAX_FRAME = 1 << 31  # sanity bound on a declared payload length

# byte-scale buckets for the append-size histogram (the default obs
# buckets are seconds-scale)
_BYTE_BUCKETS = (64, 256, 1024, 4096, 16384, 65536, 262144,
                 1 << 20, 4 << 20, 16 << 20)


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so file creations/renames/unlinks inside it
    survive power loss (file-content fsync alone does not commit the
    directory entry).  Best-effort on platforms without O_DIRECTORY
    semantics."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# standalone ContainerID codec (the binary.py cid codec needs the
# payload-level peer dictionary; WAL records are self-contained)
# ---------------------------------------------------------------------------


def write_caps(w: Writer, caps: Dict[str, int]) -> None:
    """Construction-caps table (sorted ``str key, varint value``) —
    THE one encoder: WAL meta and the v3 server checkpoint both ride
    it, so the layouts cannot drift."""
    w.varint(len(caps))
    for k in sorted(caps):
        w.str_(k)
        w.varint(int(caps[k]))


def read_caps(r: Reader) -> Dict[str, int]:
    return {r.str_(): r.varint() for _ in range(r.varint())}


def write_cid_opt(w: Writer, cid: Optional[ContainerID]) -> None:
    if cid is None:
        w.u8(0)
    elif cid.is_root:
        w.u8(1)
        w.u8(int(cid.ctype))
        w.str_(cid.name)
    else:
        w.u8(2)
        w.u8(int(cid.ctype))
        w.u64le(cid.peer)
        w.zigzag(cid.counter)


def read_cid_opt(r: Reader) -> Optional[ContainerID]:
    tag = r.u8()
    if tag == 0:
        return None
    ctype = ContainerType(r.u8())
    if tag == 1:
        return ContainerID.root(r.str_(), ctype)
    if tag == 2:
        return ContainerID.normal(r.u64le(), r.zigzag(), ctype)
    raise ValueError(f"bad cid tag {tag}")


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


@dataclass
class WalMeta:
    """Construction parameters of the owning server — enough for cold
    recovery to rebuild it without any checkpoint.  ``fsync_mode``
    records the durability mode the log was CREATED with ("per_round"
    or "group" — docs/PERSISTENCE.md "group commit"); it is
    informational (inspect shows it) and excluded from the reopen
    mismatch check, so a directory can be reopened under either mode."""

    family: str
    n_docs: int
    caps: Dict[str, int] = field(default_factory=dict)
    auto_grow: bool = True
    host_fallback: bool = True
    fsync_mode: str = "per_round"
    # deep (history-complete) mirror anchor — sharded fleets set it so
    # a cold recovery rebuilds a migration-capable server; like
    # fsync_mode it is informational for the reopen mismatch check
    deep_anchor: bool = False

    def compatible(self, other: "WalMeta") -> bool:
        """Same server shape (the refusal check ignores fsync_mode)."""
        return (
            self.family == other.family
            and self.n_docs == other.n_docs
            and self.caps == other.caps
            and self.auto_grow == other.auto_grow
            and self.host_fallback == other.host_fallback
        )

    def encode(self) -> bytes:
        w = Writer()
        w.u8(R_META)
        w.u8(META_VERSION)
        w.str_(self.family)
        w.varint(self.n_docs)
        w.u8(
            (1 if self.auto_grow else 0)
            | (2 if self.host_fallback else 0)
            | (4 if self.fsync_mode == "group" else 0)
            | (8 if self.deep_anchor else 0)
        )
        write_caps(w, self.caps)
        return bytes(w.buf)

    @classmethod
    def decode(cls, r: Reader) -> "WalMeta":
        ver = r.u8()
        if ver > META_VERSION:
            raise CodecDecodeError(f"WAL meta v{ver} newer than supported")
        family = r.str_()
        n_docs = r.varint()
        flags = r.u8()
        caps = read_caps(r)
        return cls(
            family, n_docs, caps, bool(flags & 1), bool(flags & 2),
            "group" if flags & 4 else "per_round", bool(flags & 8),
        )


@dataclass
class WalRecord:
    """One decoded frame (``rtype`` selects which fields are set).
    ``trace``/``stamp_us`` are the request-tracing stamps round records
    optionally carry (docs/OBSERVABILITY.md "Request tracing"): the
    trace id of the request that committed the round and the leader's
    wall clock at journal time in microseconds — what a follower's
    apply loop turns into measured replication-lag attribution."""

    rtype: int
    epoch: int = 0
    cid: Optional[ContainerID] = None
    updates: Optional[List[Optional[bytes]]] = None
    meta: Optional[WalMeta] = None
    ckpt_name: str = ""
    trace: Optional[str] = None
    stamp_us: int = 0


def _encode_round(epoch: int, cid, updates, trace: Optional[str] = None,
                  stamp_us: int = 0) -> bytes:
    w = Writer()
    w.u8(R_ROUND)
    w.varint(epoch)
    write_cid_opt(w, cid)
    w.varint(len(updates))
    for u in updates:
        if u is None:
            w.u8(0)
        else:
            w.u8(1)
            w.bytes_(bytes(u))
    # trailing trace stamps: flags byte + optional fields.  Readers
    # that predate them stop after the updates (frame length delimits
    # the payload), and the decoder below checks eof() first — both
    # directions stay compatible without a record-version bump.
    if trace is not None or stamp_us:
        flags = (1 if trace is not None else 0) | (2 if stamp_us else 0)
        w.u8(flags)
        if trace is not None:
            w.str_(trace)
        if stamp_us:
            w.u64le(stamp_us)
    return bytes(w.buf)


def _decode_payload(payload: bytes) -> WalRecord:
    try:
        r = Reader(payload)
        rtype = r.u8()
        if rtype == R_META:
            return WalRecord(R_META, meta=WalMeta.decode(r))
        if rtype == R_ROUND:
            epoch = r.varint()
            cid = read_cid_opt(r)
            ups: List[Optional[bytes]] = []
            for _ in range(r.varint()):
                ups.append(r.bytes_() if r.u8() else None)
            trace: Optional[str] = None
            stamp_us = 0
            if not r.eof():
                flags = r.u8()
                if flags & 1:
                    trace = r.str_()
                if flags & 2:
                    stamp_us = r.u64le()
            return WalRecord(R_ROUND, epoch=epoch, cid=cid, updates=ups,
                             trace=trace, stamp_us=stamp_us)
        if rtype == R_CKPT:
            return WalRecord(R_CKPT, epoch=r.varint(), ckpt_name=r.str_())
        if rtype == R_PRUNE:
            return WalRecord(R_PRUNE, epoch=r.varint())
        raise ValueError(f"unknown WAL record type {rtype}")
    except CodecDecodeError:
        raise
    except (IndexError, ValueError, UnicodeDecodeError, struct.error) as e:
        raise CodecDecodeError(f"malformed WAL record: {e}") from None


# ---------------------------------------------------------------------------
# segment scanning
# ---------------------------------------------------------------------------


@dataclass
class SegmentInfo:
    """Scan result for one segment file (inspect + recovery both use
    it)."""

    path: str
    index: int
    size: int = 0
    good_bytes: int = 0       # offset just past the last valid frame
    n_records: int = 0
    min_epoch: Optional[int] = None
    max_epoch: Optional[int] = None
    torn: bool = False        # bad frame found at good_bytes
    error: str = ""


def _seg_index(name: str) -> int:
    return int(name[len("seg-"):-len(".log")])


def _seg_name(index: int) -> str:
    return f"seg-{index:08d}.log"


def _scan_segment(path: str, index: int, collect=None) -> SegmentInfo:
    """Walk one segment's frames; stop at the first bad frame (torn).
    ``collect(offset, record)`` is called per valid record when given.
    A bad segment HEADER is never a torn tail — it raises typed."""
    info = SegmentInfo(path=path, index=index)
    with open(path, "rb") as f:
        data = f.read()
    info.size = len(data)
    if len(data) < 5 or data[:4] != SEG_MAGIC:
        raise CodecDecodeError(f"{os.path.basename(path)}: not a WAL segment")
    if data[4] > SEG_VERSION:
        raise CodecDecodeError(
            f"{os.path.basename(path)}: WAL segment v{data[4]} too new"
        )
    off = 5
    while off < len(data):
        if off + _FRAME_HDR > len(data):
            info.torn, info.error = True, "short frame header"
            break
        ln, crc = struct.unpack_from("<II", data, off)
        if ln > _MAX_FRAME or off + _FRAME_HDR + ln > len(data):
            info.torn, info.error = True, "frame length past EOF"
            break
        payload = data[off + _FRAME_HDR: off + _FRAME_HDR + ln]
        if zlib.crc32(payload) != crc:
            info.torn, info.error = True, "frame crc mismatch"
            break
        try:
            rec = _decode_payload(payload)
        except CodecDecodeError as e:
            info.torn, info.error = True, str(e)
            break
        if rec.rtype == R_ROUND:
            info.min_epoch = rec.epoch if info.min_epoch is None else info.min_epoch
            info.max_epoch = rec.epoch
        if collect is not None:
            collect(off, rec)
        info.n_records += 1
        off += _FRAME_HDR + ln
    info.good_bytes = off  # torn: offset of the bad frame (= truncate point)
    return info


# ---------------------------------------------------------------------------
# the log
# ---------------------------------------------------------------------------


class WriteAheadLog:
    """Append-only segmented log under ``<dir>`` (one server per
    directory).  Opening an existing directory scans every segment:
    torn tails on the newest segment are truncated away (counted),
    corruption in older segments raises typed ``CodecDecodeError``.

    ``fsync`` selects the durability mode: ``True`` fsyncs every frame
    before the append returns (per-round commit), ``"group"`` defers
    the fsync to an explicit ``sync()`` — the group-commit flush point
    (docs/PERSISTENCE.md): appends stay buffered-to-OS until the owner
    syncs a whole window, amortizing the fsync across rounds; a crash
    loses at most the unsynced tail (the torn-tail reopen contract
    already covers partially-flushed frames).  ``False`` never fsyncs
    (tests only).
    """

    def __init__(self, dir: str, fsync=True):
        self.dir = dir
        if fsync is True:
            self.fsync_mode = "per_round"
        elif fsync is False:
            self.fsync_mode = "off"
        elif fsync in ("per_round", "group", "off"):
            self.fsync_mode = fsync
        else:
            raise PersistError(f"unknown WAL fsync mode {fsync!r}")
        # segment-creation/rotation fsyncs stay on in group mode (rare,
        # and a lost directory entry would orphan the whole segment)
        self.fsync = self.fsync_mode != "off"
        self._unsynced = 0  # appends since the last fsync (group mode)
        os.makedirs(dir, exist_ok=True)
        self._f = None  # active segment file handle
        self._active: Optional[SegmentInfo] = None
        # replication hooks (loro_tpu/replication/, docs/REPLICATION.md):
        # ``fence`` fires before EVERY append — a deposed leader raises
        # typed FencedLeader there, before any bytes reach the segment;
        # ``retention_floor`` pins prune_below at the registered
        # followers' acked epochs; ``publish_visibility`` mirrors the
        # fsync watermark to ``.visible`` so cross-process followers can
        # honor the durable-tail protocol without this object.
        self.fence = None
        self.retention_floor = None
        self.publish_visibility = False
        # fsync watermark on the ACTIVE segment: bytes at/under it are
        # known durable (the ship-visibility bound).  Sealed segments
        # are fully visible — rotation fsyncs them closed.
        self._synced_bytes = 0
        self.meta: Optional[WalMeta] = None
        # newest R_PRUNE floor: rounds at/under it were DELETED from
        # the log, so a from-birth cold replay is no longer possible
        self.pruned_below = 0
        self._segments: List[SegmentInfo] = self._scan_all()
        self._open_active()

    # -- open / scan ---------------------------------------------------
    def _scan_all(self) -> List[SegmentInfo]:
        names = sorted(
            n for n in os.listdir(self.dir)
            if n.startswith("seg-") and n.endswith(".log")
        )
        # drop headerless TRAILING segments first (crash between
        # segment creation and the header write): the survivor then
        # becomes the tail, and a torn frame on IT is a legitimate
        # torn tail, not mid-log corruption
        while names and os.path.getsize(os.path.join(self.dir, names[-1])) < 5:
            os.unlink(os.path.join(self.dir, names.pop()))
            obs.counter(
                "persist.wal_torn_tail_truncations_total",
                "torn WAL tails truncated on reopen",
            ).inc()
        infos: List[SegmentInfo] = []
        for i, name in enumerate(names):
            is_last = i == len(names) - 1
            path = os.path.join(self.dir, name)

            def keep_meta(off, rec):
                if rec.rtype == R_META and self.meta is None:
                    self.meta = rec.meta
                elif rec.rtype == R_PRUNE:
                    self.pruned_below = max(self.pruned_below, rec.epoch)

            info = _scan_segment(path, _seg_index(name), keep_meta)
            if info.torn and not is_last:
                raise CodecDecodeError(
                    f"{name}: corrupt frame in a non-tail WAL segment "
                    f"({info.error}) — not a torn tail (later segments exist)"
                )
            infos.append(info)
        return infos

    def _open_active(self) -> None:
        if not self._segments:
            self._start_segment(1)
            return
        last = self._segments[-1]
        if last.torn:
            # torn tail: truncate back to the last good frame so the
            # next append starts on a clean boundary
            with open(last.path, "r+b") as f:
                f.truncate(last.good_bytes)
            last.size = last.good_bytes
            last.torn = False
            obs.counter(
                "persist.wal_torn_tail_truncations_total",
                "torn WAL tails truncated on reopen",
            ).inc()
        self._f = open(last.path, "ab")
        self._active = last
        # everything that survived the reopen scan is on disk already
        self._synced_bytes = last.good_bytes

    def _start_segment(self, index: int) -> None:
        path = os.path.join(self.dir, _seg_name(index))
        self._f = open(path, "wb")
        self._f.write(SEG_MAGIC + bytes([SEG_VERSION]))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
            fsync_dir(self.dir)  # commit the new directory entry too
        info = SegmentInfo(path=path, index=index, size=5, good_bytes=5)
        self._segments.append(info)
        self._active = info
        self._synced_bytes = 5
        obs.counter("persist.wal_segments_total").inc()
        # every segment is self-describing: re-write the meta record
        # (and the prune floor, when history was ever dropped) so
        # pruning any prefix of segments never loses what cold
        # recovery needs to rebuild — or to refuse honestly
        if self.meta is not None:
            self._append(self.meta.encode(), rtype="meta")
        if self.pruned_below:
            w = Writer()
            w.u8(R_PRUNE)
            w.varint(self.pruned_below)
            self._append(bytes(w.buf), rtype="prune")
        # control records never ride the group-commit window: the old
        # segment (holding the previous meta copy) may be pruned right
        # after this rotation, so the fresh copy must hit disk first
        self.sync()

    # -- appends -------------------------------------------------------
    def _append(self, payload: bytes, rtype: str) -> None:
        if self._f is None:
            raise PersistError("WAL is closed")
        if self.fence is not None:
            # leader fencing (docs/REPLICATION.md): a promoted follower
            # holds a newer leader token, so this append must fail-stop
            # typed BEFORE any bytes land — never a partial record
            self.fence()
        faultinject.check("wal_write", rtype=rtype)
        frame = (
            struct.pack("<II", len(payload), zlib.crc32(payload)) + payload
        )
        # a truncate/bitflip fault here writes a genuinely damaged
        # frame — the reopen path must cope with it (torn-tail tests)
        frame = faultinject.mangle("wal_torn_tail", frame)
        self._f.write(frame)
        self._f.flush()
        if self.fsync_mode == "per_round":
            self._fsync_active()
        elif self.fsync_mode == "group":
            self._unsynced += 1
        obs.histogram(
            "persist.wal_append_bytes", "WAL frame payload sizes",
            buckets=_BYTE_BUCKETS,
        ).observe(len(payload))
        obs.counter("persist.wal_records_total").inc(rtype=rtype)
        a = self._active
        a.size = a.good_bytes = a.good_bytes + _FRAME_HDR + len(payload)
        a.n_records += 1
        if self.fsync_mode == "per_round":
            # the frame was fsync'd above: the whole segment is visible
            self._synced_bytes = a.good_bytes
            self._publish_visibility()
        elif self.fsync_mode == "off":
            # tests: no fsync anywhere — durability is disclaimed, so
            # visibility = appended bytes.  Publish the marker too:
            # an in-process follower (visible_extent) and a
            # cross-process one (.visible) must see the SAME tail for
            # the same log, whichever process they run in
            self._synced_bytes = a.good_bytes
            self._publish_visibility()

    def _fsync_active(self) -> None:
        """fsync the active segment handle (timed + counted: the
        bench A/B and the count-based perf guard compare fsyncs/round
        across commit modes)."""
        t0 = _time.perf_counter()
        with obs.histogram(
            "persist.wal_fsync_seconds", "WAL fsync wall time"
        ).time():
            os.fsync(self._f.fileno())
        obs.counter(
            "persist.wal_fsyncs_total", "WAL data fsyncs issued"
        ).inc(mode=self.fsync_mode)
        flight.record(
            "wal.fsync", mode=self.fsync_mode,
            ms=round((_time.perf_counter() - t0) * 1e3, 3),
        )

    def sync(self) -> int:
        """Group-commit flush point: fsync the active segment if any
        appends are pending; returns how many appends the fsync covered
        (0 = nothing pending).  No-op in per-round mode (every append
        already synced) and off mode."""
        if self.fsync_mode != "group" or not self._unsynced:
            return 0
        if self._f is None:
            raise PersistError("WAL is closed")
        n, self._unsynced = self._unsynced, 0
        self._fsync_active()
        self._synced_bytes = self._active.good_bytes
        self._publish_visibility()
        obs.histogram(
            "persist.wal_group_commit_rounds", "appends per group fsync",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
        ).observe(n)
        return n

    def write_meta(self, meta: WalMeta) -> None:
        """Record construction caps (once per log; re-emitted at every
        rotation).  A log that already carries a DIFFERENT meta belongs
        to another server — cold recovery would rebuild the wrong shape
        from it, so the mismatch is refused, never silently inherited.
        (``fsync_mode`` is excluded: reopening under a different
        durability mode is legitimate — see WalMeta.compatible.)"""
        if self.meta is not None:
            if not self.meta.compatible(meta):
                raise PersistError(
                    f"{self.dir}: WAL meta mismatch — log was created for "
                    f"{self.meta.family}/{self.meta.n_docs} docs, this "
                    f"server is {meta.family}/{meta.n_docs}; use a fresh "
                    "directory (or recover_server for the original)"
                )
            return
        self.meta = meta
        self._append(meta.encode(), rtype="meta")
        # control records never ride the group-commit window: a meta
        # lost from the OS buffer would make the directory scan as
        # empty and let open_server silently build a fresh server over
        # it (the rotation/prune paths sync their copies the same way)
        self.sync()

    def append_round(self, epoch: int, cid, updates,
                     trace: Optional[str] = None,
                     stamp_us: int = 0) -> None:
        """Journal one applied round (``updates``: per-doc frozen wire
        bytes, None = no update for that doc).  ``trace``/``stamp_us``
        optionally stamp the record with the committing request's trace
        id and the leader wall clock (replication-lag attribution —
        docs/OBSERVABILITY.md)."""
        self._append(
            _encode_round(epoch, cid, updates, trace, stamp_us),
            rtype="round",
        )
        a = self._active
        a.min_epoch = epoch if a.min_epoch is None else a.min_epoch
        a.max_epoch = epoch

    def append_ckpt_marker(self, epoch: int, name: str) -> None:
        w = Writer()
        w.u8(R_CKPT)
        w.varint(epoch)
        w.str_(name)
        self._append(bytes(w.buf), rtype="ckpt")

    # -- rotation / pruning -------------------------------------------
    def rotate(self) -> None:
        """Close the active segment and start the next one (called at
        every checkpoint, so older segments become prunable units).
        Pending group-commit appends are fsynced first — a rotated-away
        segment can never be synced again, and silently dropping its
        tail would lose journaled rounds the owner believes durable."""
        self.sync()
        if self._f is not None:
            self._f.close()
        self._start_segment(self._active.index + 1 if self._active else 1)

    def prune_below(self, epoch: int) -> int:
        """Delete non-active segments whose every round is at/under
        ``epoch`` (covered by a checkpoint).  Returns segments
        removed.  When a ROUND-bearing segment goes, an ``R_PRUNE``
        marker lands in the active segment first: cold recovery must
        be able to tell "no rounds ever" from "rounds were deleted"
        (silently replaying a truncated history would fabricate
        state).  With a ``retention_floor`` installed (replication:
        registered followers' acked epochs), the prune point is
        clamped to it — a lagging follower pins the segments it still
        needs (docs/REPLICATION.md "retention")."""
        floor = None
        if self.retention_floor is not None:
            floor = self.retention_floor()
            if floor is not None and floor < epoch:
                obs.gauge(
                    "repl.retention_pinned_floor",
                    "WAL prune epoch pinned by follower acks",
                ).set(floor)
                epoch = floor
        # With a live follower pin, pruning must only ever remove a
        # contiguous PREFIX of the stream, and marker-only segments
        # (max_epoch None: ckpt/prune markers, or freshly rotated and
        # empty) go only when a round-bearing segment that is itself
        # under the clamped floor follows them — an acked epoch maps to
        # round positions, never to marker positions, so a floating
        # marker-only segment may still be ahead of the follower's
        # shipped copy.  Pruning one would punch a hole in the shipped
        # stream and orphan the follower typed (StaleFollower) even
        # though it was fresh and pinned — the epoch-0 auto-checkpoint
        # right after a follower attaches hits exactly this (chaos
        # seed 4, docs/RESILIENCE.md "Chaos plane").
        pinned = floor is not None
        doomed: List[SegmentInfo] = []
        pending: List[SegmentInfo] = []
        for info in self._segments:
            if info is self._active:
                break
            if info.max_epoch is None:
                if pinned:
                    pending.append(info)
                else:
                    doomed.append(info)
            elif info.max_epoch <= epoch:
                doomed.extend(pending)
                pending = []
                doomed.append(info)
            else:
                break
        if any(info.max_epoch is not None for info in doomed):
            floor = max(info.max_epoch for info in doomed
                        if info.max_epoch is not None)
            w = Writer()
            w.u8(R_PRUNE)
            w.varint(floor)
            self._append(bytes(w.buf), rtype="prune")
            # the marker must be durable BEFORE the segments vanish: a
            # crash in between must read "rounds were deleted", never
            # silently replay a truncated history (group mode defers
            # data fsyncs — control records don't get to)
            self.sync()
            self.pruned_below = max(self.pruned_below, floor)
        removed = 0
        keep: List[SegmentInfo] = []
        for info in self._segments:
            if info in doomed:
                os.unlink(info.path)
                removed += 1
            else:
                keep.append(info)
        self._segments = keep
        if removed:
            obs.counter("persist.wal_segments_pruned_total").inc(removed)
        return removed

    # -- reads ---------------------------------------------------------
    def records(self) -> Iterator[WalRecord]:
        """Replay every record across segments in order.  The active
        handle is flushed first so a same-process reader sees its own
        appends."""
        if self._f is not None:
            self._f.flush()
        for info in list(self._segments):
            recs: List[WalRecord] = []
            _scan_segment(info.path, info.index, lambda off, r: recs.append(r))
            for rec in recs:
                yield rec

    def rounds_after(self, epoch: int, doc: Optional[int] = None
                     ) -> List[Tuple[int, Optional[ContainerID], List[Optional[bytes]]]]:
        """Round records with epoch > ``epoch``; ``doc=`` narrows to
        rounds carrying an update for that doc index — the
        one-doc-scoped bounded replay the tiered cold tier uses
        (parallel/residency.py revives a cold doc from its backing
        checkpoint rung plus exactly these rounds)."""
        return [
            (r.epoch, r.cid, r.updates)
            for r in self.records()
            if r.rtype == R_ROUND and r.epoch > epoch
            and (doc is None
                 or (doc < len(r.updates) and r.updates[doc] is not None))
        ]

    def segments(self) -> List[SegmentInfo]:
        return list(self._segments)

    # -- ship visibility (loro_tpu/replication/) -----------------------
    def visible_extent(self) -> List[Tuple[int, str, int]]:
        """``(index, path, visible_bytes)`` per segment — the bytes a
        WAL shipper may stream to a follower.  Sealed segments are
        fully visible (rotation fsyncs them closed); the ACTIVE segment
        is visible only up to the fsync watermark, so a follower can
        never apply a round the leader has not made durable (the
        group-commit tail protocol, docs/REPLICATION.md)."""
        out: List[Tuple[int, str, int]] = []
        for info in self._segments:
            vis = self._synced_bytes if info is self._active else info.good_bytes
            out.append((info.index, info.path, vis))
        return out

    def _publish_visibility(self) -> None:
        """Mirror the fsync watermark to ``<dir>/.visible`` (atomic
        replace, deliberately un-fsynced: it only ever UNDERSTATES what
        is durable, which is the safe direction) so a follower in
        another process can honor the tail protocol.  Off by default —
        ``replication.enable()`` turns it on; non-replicated servers
        never pay the extra write."""
        if not self.publish_visibility or self._active is None:
            return
        import json

        path = os.path.join(self.dir, ".visible")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"seg": self._active.index,
                           "off": self._synced_bytes}, f)
            os.replace(tmp, path)
        except OSError:
            pass  # advisory only; the in-process extent stays exact

    def close(self) -> None:
        if self._f is not None:
            self.sync()  # group mode: never strand a buffered tail
            self._f.close()
            self._f = None


class DurableLog:
    """The per-server durable directory: ``wal/`` (this module) +
    ``ckpt/`` (checkpoints.CheckpointManager), coordinated so a
    checkpoint atomically (a) lands the blob on the ladder, (b) marks
    the WAL, (c) rotates the segment and (d) prunes segments fully
    covered by the checkpoint."""

    def __init__(self, dir: str, fsync=True, keep_recent: int = 3):
        from .checkpoints import CheckpointManager

        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.wal = WriteAheadLog(os.path.join(dir, "wal"), fsync=fsync)
        self.checkpoints = CheckpointManager(
            os.path.join(dir, "ckpt"), keep_recent=keep_recent
        )

    @property
    def meta(self) -> Optional[WalMeta]:
        return self.wal.meta

    @property
    def fsync_mode(self) -> str:
        return self.wal.fsync_mode

    def sync(self) -> int:
        """Group-commit flush point (see WriteAheadLog.sync)."""
        return self.wal.sync()

    def ensure_meta(self, meta: WalMeta) -> None:
        self.wal.write_meta(meta)

    def in_use(self) -> bool:
        """True when the directory already holds durable state — round
        records OR checkpoint rungs.  Both matter: a checkpoint prunes
        every round-bearing segment, so a rounds-only check would let
        a fresh server silently reuse (and strand) a live directory."""
        return any(
            s.max_epoch is not None for s in self.wal.segments()
        ) or bool(self.checkpoints.list())

    def append_round(self, epoch: int, cid, updates,
                     trace: Optional[str] = None,
                     stamp_us: int = 0) -> None:
        self.wal.append_round(epoch, cid, updates, trace, stamp_us)

    def record_checkpoint(self, epoch: int, blob: bytes) -> str:
        name = self.checkpoints.save(epoch, blob)
        self.wal.append_ckpt_marker(epoch, name)
        self.wal.rotate()
        # prune only below the OLDEST retained rung: a corrupt newest
        # rung falls DOWN the ladder, and the fallback must still find
        # the rounds between that older rung and now in the WAL
        rungs = self.checkpoints.list()
        if rungs:
            self.wal.prune_below(min(c.epoch for c in rungs))
        return name

    def close(self) -> None:
        self.wal.close()
