"""One-screen dump of a durable directory.

Usage::

    python -m loro_tpu.persist.inspect <durable_dir>

Shows the WAL meta, every segment (records, epoch range, crc/torn
status), every checkpoint rung (epoch, size, crc status) and the
recovery preview (which rung would restore, how many rounds replay).
A sharded fleet directory (``sharding.json`` manifest +
``shard-NN/`` sub-dirs, docs/SHARDING.md) prints one screen per
shard plus the fleet-wide minimum durable watermark.  Read-only:
never truncates a torn tail, never prunes.
"""
from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

from ..errors import DecodeError
from .checkpoints import CheckpointManager
from .wal import R_ROUND, SegmentInfo, _scan_segment, _seg_index


def _human(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n}B"


def _emap_to_global(bps, e: int) -> int:
    """Manifest epoch-map interpolation: shard-local epoch → fleet
    global, through the REAL `_EpochMap` (parallel/placement.py is
    jax-free on purpose so this tool can import it)."""
    from ..parallel.placement import _EpochMap

    return _EpochMap.decode(bps).to_global(e)


def _inspect_sharded(durable_dir: str, manifest: dict, out) -> int:
    """Multi-shard report: one screen per shard + the fleet-wide min
    durable watermark — the min over shards of each shard's durable
    floor (newest journaled round, or newest valid checkpoint rung
    when the WAL was legitimately pruned by it), translated to the
    GLOBAL clock through the manifest's epoch maps (shard clocks tick
    faster than fleet rounds — delete rounds, poison isolation).  A
    shard with neither rounds nor rungs while its siblings have some
    pins the floor to 0: lockstep clocks journal every fleet round to
    every shard, so a bare directory next to full ones means that
    shard lost its durable state."""
    p = lambda s="": print(s, file=out)  # noqa: E731
    n_shards = int(manifest.get("shards", 0))
    placement = manifest.get("shard_of", [])
    emaps = manifest.get("emaps") or [[[0, 0]]] * n_shards
    p(f"sharded fleet: {durable_dir}")
    p(f"manifest: family={manifest.get('family')} "
      f"n_docs={manifest.get('n_docs')} shards={n_shards} "
      f"global_epoch={manifest.get('global_epoch')}")
    rc = 0
    marks: List[tuple] = []  # (shard, shard-local floor or None)
    for s in range(n_shards):
        sub = os.path.join(durable_dir, f"shard-{s:02d}")
        docs = [g for g, sh in enumerate(placement) if sh == s]
        p()
        p(f"--- shard-{s:02d} ({len(docs)} doc(s): "
          f"{','.join(map(str, docs[:8]))}"
          f"{',...' if len(docs) > 8 else ''}) ---")
        if not os.path.isdir(sub):
            p("  MISSING (manifest names it, directory absent)")
            rc = 1
            marks.append((s, None))
            continue
        stats: dict = {}
        rc = max(rc, inspect_dir(sub, out=out, _stats=stats))
        floors = [e for e in (stats.get("newest_round_epoch"),
                              stats.get("newest_ckpt_epoch"))
                  if e is not None]
        marks.append((s, max(floors) if floors else None))
    p()
    known = [(s, e) for s, e in marks if e is not None]
    if not known:
        p("fleet-wide min durable watermark: (nothing journaled yet)")
    elif len(known) < len(marks):
        bare = ", ".join(f"shard-{s:02d}" for s, e in marks if e is None)
        p(f"fleet-wide min durable watermark: global epoch 0 — {bare} "
          "holds NO rounds and NO rungs while siblings do "
          "(lost/missing durable state?)")
        rc = 1
    else:
        s_min, g_min, e_min = min(
            ((s, _emap_to_global(emaps[s] if s < len(emaps) else [[0, 0]],
                                 e), e)
             for s, e in known),
            key=lambda x: x[1],
        )
        p(f"fleet-wide min durable watermark: global epoch {g_min} "
          f"(shard-{s_min:02d} local e{e_min})")
    return rc


def inspect_dir(durable_dir: str, out=None, _stats: Optional[dict] = None) -> int:
    """Print the report; returns a process exit code (0 clean, 1 if
    any segment is torn/corrupt or any rung fails its crc).  A
    sharded fleet dir recurses into its shards.  ``_stats`` (internal)
    receives facts the sharded summary needs from the single scan —
    currently ``newest_round_epoch`` — so the fleet report never
    re-reads segments."""
    out = out or sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    manifest_path = os.path.join(durable_dir, "sharding.json")
    if os.path.isfile(manifest_path):
        try:
            with open(manifest_path, "r") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            p(f"sharded fleet: {durable_dir}")
            p(f"  sharding.json UNREADABLE ({e})")
            return 1
        return _inspect_sharded(durable_dir, manifest, out)
    rc = 0
    wal_dir = os.path.join(durable_dir, "wal")
    p(f"persist dir: {durable_dir}")

    # -- WAL segments (ONE read-only scan: no truncation; the per-
    # segment record lists feed every count below) ---------------------
    segs: List[SegmentInfo] = []
    seg_recs: List[list] = []
    meta = None
    if os.path.isdir(wal_dir):
        names = sorted(
            n for n in os.listdir(wal_dir)
            if n.startswith("seg-") and n.endswith(".log")
        )
        for name in names:
            path = os.path.join(wal_dir, name)
            try:
                recs = []
                info = _scan_segment(
                    path, _seg_index(name), lambda off, r: recs.append(r)
                )
                for r in recs:
                    if r.rtype == 0 and meta is None:  # R_META
                        meta = r.meta
                segs.append(info)
                seg_recs.append(recs)
            except DecodeError as e:
                p(f"  {name}: UNREADABLE ({e})")
                rc = 1
    if meta is not None:
        caps = " ".join(f"{k}={v}" for k, v in sorted(meta.caps.items()))
        p(f"meta: family={meta.family} n_docs={meta.n_docs} "
          f"auto_grow={meta.auto_grow} host_fallback={meta.host_fallback} "
          f"fsync={meta.fsync_mode}"
          + (" deep_anchor=True" if meta.deep_anchor else "")
          + (f" {caps}" if caps else ""))
    else:
        p("meta: (none)")
    p(f"wal segments: {len(segs)}")
    rounds = [r for recs in seg_recs for r in recs if r.rtype == R_ROUND]
    if _stats is not None:
        _stats["newest_round_epoch"] = max(
            (r.epoch for r in rounds), default=None
        )
    for s in segs:
        span = ("-" if s.min_epoch is None
                else f"e{s.min_epoch}..e{s.max_epoch}")
        status = "ok"
        if s.torn:
            status = f"TORN at +{s.good_bytes} ({s.error})"
            rc = 1
        p(f"  {os.path.basename(s.path)}  {_human(s.size):>8}  "
          f"{s.n_records:>4} recs  {span:>12}  {status}")
    p(f"rounds journaled: {len(rounds)}")

    # -- checkpoint ladder (no CheckpointManager before the isdir
    # check: its constructor mkdirs, and this tool is READ-ONLY) ------
    ckpt_dir = os.path.join(durable_dir, "ckpt")
    mgr = CheckpointManager(ckpt_dir) if os.path.isdir(ckpt_dir) else None
    rungs = mgr.list() if mgr is not None else []
    p(f"checkpoint ladder: {len(rungs)} rung(s)")
    newest_valid = None
    for info in rungs:
        try:
            mgr.load(info)
            status = "crc ok"
            if newest_valid is None:
                newest_valid = info
        except DecodeError as e:
            status = f"CORRUPT ({e})"
            rc = 1
        p(f"  {info.name}  {_human(info.size):>8}  epoch {info.epoch}  {status}")
    if _stats is not None:
        _stats["newest_ckpt_epoch"] = (
            newest_valid.epoch if newest_valid is not None else None
        )

    # -- tiered residency (parallel/residency.py, docs/RESIDENCY.md) ---
    res_path = os.path.join(durable_dir, "residency.json")
    if os.path.isfile(res_path):
        try:
            with open(res_path, "r") as f:
                res = json.load(f)
        except (OSError, ValueError) as e:
            p(f"residency: residency.json UNREADABLE ({e})")
            rc = 1
        else:
            hot = res.get("hot", {})
            warm = res.get("warm", [])
            cold = res.get("cold", {})
            p(f"residency: hot_slots={res.get('hot_slots')}  "
              f"hot={len(hot)} warm={len(warm)} cold={len(cold)}")
            if hot:
                pairs = ", ".join(
                    f"doc {d}→slot {s}" for d, s in sorted(
                        hot.items(), key=lambda kv: int(kv[0])
                    )[:8]
                )
                p(f"  hot: {pairs}{', ...' if len(hot) > 8 else ''}")
            rung_names = {r.name for r in rungs}
            for d, rung in sorted(cold.items(), key=lambda kv: int(kv[0])):
                ok = rung in rung_names
                p(f"  cold doc {d}: backed by {rung}"
                  + ("" if ok else "  MISSING RUNG"))
                if not ok:
                    rc = 1

    # -- replication (loro_tpu/replication/, docs/REPLICATION.md) ------
    rep_path = os.path.join(durable_dir, "replication.json")
    if os.path.isfile(rep_path):
        try:
            with open(rep_path, "r") as f:
                rep = json.load(f)
        except (OSError, ValueError) as e:
            p(f"replication: replication.json UNREADABLE ({e})")
            rc = 1
        else:
            p(f"replication: leader_token={rep.get('leader_token')} "
              f"held_by={rep.get('leader_id')!r}")
            newest = _stats.get("newest_round_epoch") if _stats else None
            if newest is None:
                newest = max((r.epoch for r in rounds), default=None)
            floors = []
            import time as _t

            now = _t.time()  # tpulint: disable=LT-TIME(read-only CLI report of wall-clock last-seen stamps; no fake-clock test drives it)
            for fid, f in sorted(rep.get("followers", {}).items()):
                acked = int(f.get("acked_epoch", 0))
                lag = (newest - acked) if newest is not None else 0
                age = now - float(f.get("last_seen", now))
                floors.append(acked)
                p(f"  follower {fid}: acked e{acked}  "
                  f"lag {max(0, lag)} round(s)  "
                  f"last seen {age:.0f}s ago")
            if floors:
                p(f"  pinned prune floor: e{min(floors)} "
                  "(WAL segments above it are retained for followers)")
            else:
                p("  no registered followers (nothing pinned)")

    # -- recovery preview ----------------------------------------------
    if newest_valid is not None:
        tail = sum(
            1 for s in segs
            if s.max_epoch is not None and s.max_epoch > newest_valid.epoch
        )
        replay = sum(1 for r in rounds if r.epoch > newest_valid.epoch)
        p(f"recovery: restore {newest_valid.name} (epoch "
          f"{newest_valid.epoch}) + replay {replay} round(s) "
          f"from {tail} segment(s)")
    elif rounds or meta is not None:
        p(f"recovery: COLD — no valid rung; replay all {len(rounds)} "
          "round(s) from the WAL meta")
    else:
        p("recovery: nothing to recover")
    return rc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        return 2
    if not os.path.isdir(argv[0]):
        print(f"not a directory: {argv[0]}", file=sys.stderr)
        return 2
    return inspect_dir(argv[0])


if __name__ == "__main__":
    sys.exit(main())
