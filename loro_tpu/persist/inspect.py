"""One-screen dump of a durable directory.

Usage::

    python -m loro_tpu.persist.inspect <durable_dir>

Shows the WAL meta, every segment (records, epoch range, crc/torn
status), every checkpoint rung (epoch, size, crc status) and the
recovery preview (which rung would restore, how many rounds replay).
Read-only: never truncates a torn tail, never prunes.
"""
from __future__ import annotations

import os
import sys
from typing import List

from ..errors import DecodeError
from .checkpoints import CheckpointManager
from .wal import R_ROUND, SegmentInfo, _scan_segment, _seg_index


def _human(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n}B"


def inspect_dir(durable_dir: str, out=None) -> int:
    """Print the report; returns a process exit code (0 clean, 1 if
    any segment is torn/corrupt or any rung fails its crc)."""
    out = out or sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    rc = 0
    wal_dir = os.path.join(durable_dir, "wal")
    p(f"persist dir: {durable_dir}")

    # -- WAL segments (ONE read-only scan: no truncation; the per-
    # segment record lists feed every count below) ---------------------
    segs: List[SegmentInfo] = []
    seg_recs: List[list] = []
    meta = None
    if os.path.isdir(wal_dir):
        names = sorted(
            n for n in os.listdir(wal_dir)
            if n.startswith("seg-") and n.endswith(".log")
        )
        for name in names:
            path = os.path.join(wal_dir, name)
            try:
                recs = []
                info = _scan_segment(
                    path, _seg_index(name), lambda off, r: recs.append(r)
                )
                for r in recs:
                    if r.rtype == 0 and meta is None:  # R_META
                        meta = r.meta
                segs.append(info)
                seg_recs.append(recs)
            except DecodeError as e:
                p(f"  {name}: UNREADABLE ({e})")
                rc = 1
    if meta is not None:
        caps = " ".join(f"{k}={v}" for k, v in sorted(meta.caps.items()))
        p(f"meta: family={meta.family} n_docs={meta.n_docs} "
          f"auto_grow={meta.auto_grow} host_fallback={meta.host_fallback} "
          f"fsync={meta.fsync_mode}"
          + (f" {caps}" if caps else ""))
    else:
        p("meta: (none)")
    p(f"wal segments: {len(segs)}")
    rounds = [r for recs in seg_recs for r in recs if r.rtype == R_ROUND]
    for s in segs:
        span = ("-" if s.min_epoch is None
                else f"e{s.min_epoch}..e{s.max_epoch}")
        status = "ok"
        if s.torn:
            status = f"TORN at +{s.good_bytes} ({s.error})"
            rc = 1
        p(f"  {os.path.basename(s.path)}  {_human(s.size):>8}  "
          f"{s.n_records:>4} recs  {span:>12}  {status}")
    p(f"rounds journaled: {len(rounds)}")

    # -- checkpoint ladder (no CheckpointManager before the isdir
    # check: its constructor mkdirs, and this tool is READ-ONLY) ------
    ckpt_dir = os.path.join(durable_dir, "ckpt")
    mgr = CheckpointManager(ckpt_dir) if os.path.isdir(ckpt_dir) else None
    rungs = mgr.list() if mgr is not None else []
    p(f"checkpoint ladder: {len(rungs)} rung(s)")
    newest_valid = None
    for info in rungs:
        try:
            mgr.load(info)
            status = "crc ok"
            if newest_valid is None:
                newest_valid = info
        except DecodeError as e:
            status = f"CORRUPT ({e})"
            rc = 1
        p(f"  {info.name}  {_human(info.size):>8}  epoch {info.epoch}  {status}")

    # -- recovery preview ----------------------------------------------
    if newest_valid is not None:
        tail = sum(
            1 for s in segs
            if s.max_epoch is not None and s.max_epoch > newest_valid.epoch
        )
        replay = sum(1 for r in rounds if r.epoch > newest_valid.epoch)
        p(f"recovery: restore {newest_valid.name} (epoch "
          f"{newest_valid.epoch}) + replay {replay} round(s) "
          f"from {tail} segment(s)")
    elif rounds or meta is not None:
        p(f"recovery: COLD — no valid rung; replay all {len(rounds)} "
          "round(s) from the WAL meta")
    else:
        p("recovery: nothing to recover")
    return rc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        return 2
    if not os.path.isdir(argv[0]):
        print(f"not a directory: {argv[0]}", file=sys.stderr)
        return 2
    return inspect_dir(argv[0])


if __name__ == "__main__":
    sys.exit(main())
