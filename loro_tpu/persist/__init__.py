"""loro_tpu.persist: durable WAL + checkpoint ladder + bounded-replay
recovery for the resident fleet path (the reproduction's L1 storage
layer; docs/PERSISTENCE.md has the full design).

Four pieces:

- ``wal``         — segmented, crc32-framed, torn-tail-tolerant
  write-ahead log of ingest rounds (+ ``DurableLog``, the per-server
  directory coordinating WAL and checkpoints);
- ``checkpoints`` — CheckpointManager: retention ladder of
  ``ResidentServer.checkpoint()`` blobs (newest K + geometrically
  thinned older rungs), typed DecodeError on corrupt rungs;
- ``anchor``      — ``MirrorAnchor`` (per-doc shallow-snapshot anchors
  so the host-mirror degradation oracle no longer needs history since
  birth) and ``recover_server``/``open_server`` (restore the newest
  valid checkpoint, replay only WAL rounds after its epoch, falling
  down the ladder past corrupt blobs);
- ``inspect``     — ``python -m loro_tpu.persist.inspect <dir>``
  one-screen dump of segments, records, checkpoint epochs and crc
  status.

Fault sites (``LORO_FAULT``/faultinject): ``wal_write``,
``wal_torn_tail``, ``ckpt_corrupt``.  Metrics: ``persist.*``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

from .anchor import MirrorAnchor, RecoveryReport, open_server, recover_server
from .checkpoints import CheckpointInfo, CheckpointManager
from .wal import DurableLog, WalMeta, WalRecord, WriteAheadLog

__all__ = [
    "CheckpointInfo",
    "CheckpointManager",
    "DurableLog",
    "MirrorAnchor",
    "RecoveryReport",
    "WalMeta",
    "WalRecord",
    "WriteAheadLog",
    "open_server",
    "recover_server",
    "recover_sharded_server",
]


def recover_sharded_server(durable_dir: str, mesh=None, fsync: bool = True):
    """Reopen a sharded fleet directory (``sharding.json`` manifest +
    per-shard WAL/ladder sub-dirs) — see
    ``parallel.sharded.recover_sharded_server`` (lazy import: the
    sharded module pulls in the jax-backed fleet)."""
    from ..parallel.sharded import recover_sharded_server as _impl

    return _impl(durable_dir, mesh=mesh, fsync=fsync)
