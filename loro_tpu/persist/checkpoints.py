"""Checkpoint ladder: a retention-managed directory of ResidentServer
checkpoint blobs.

A checkpoint is the read-optimized merge of everything the WAL
journaled up to its epoch (the differential-store split: WAL = write-
optimized delta, checkpoint = merged store).  Recovery restores the
NEWEST valid blob and replays only WAL rounds after its epoch; a
corrupt newest blob falls back DOWN the ladder (recovery cost grows by
the extra rounds to replay, but never to rounds-since-birth while any
rung is valid).

File format (``<dir>/ck-<epoch:012d>-<seq:04d>.ltck``)::

    "LTCK" | u8 version | varint epoch | u32le crc32(blob) | blob

The blob itself is the ``ResidentServer.checkpoint()`` LTKV store
(docs/ENCODING.md).  ``load`` verifies magic/version/crc and raises
typed ``DecodeError`` on any mismatch — recovery treats that as "this
rung is gone", never as untyped garbage.

Retention ladder: the newest ``keep_recent`` blobs are always kept;
older blobs are thinned to a geometric spacing (each surviving older
rung covers at least twice the epoch span of the one above it), capped
at ``keep_total``.  The ladder therefore spans a long history with
O(log) rungs — deep fallback stays possible without unbounded disk.

Fault site: ``ckpt_corrupt`` runs the framed bytes through
``faultinject.mangle`` on their way to disk, so a bitflip/truncate
fault produces a genuinely corrupt rung for fallback tests.
"""
from __future__ import annotations

import os
import re
import struct
import zlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..codec.binary import Reader, Writer
from ..errors import CodecDecodeError, DecodeError
from ..obs import metrics as obs
from ..resilience import faultinject

faultinject.register_site(
    "ckpt_corrupt", "persist.checkpoints save: mangle the framed rung "
    "blob (recovery must fall back down the ladder)")

CKPT_MAGIC = b"LTCK"
CKPT_VERSION = 1

# widths are zero-padded minimums, not caps: a long-lived server can
# pass 10^4 checkpoints (or 10^12 epochs) and the rungs must stay
# visible to list()/recovery/retention
_NAME_RE = re.compile(r"^ck-(\d{12,})-(\d{4,})\.ltck$")


@dataclass
class CheckpointInfo:
    path: str
    name: str
    epoch: int
    seq: int
    size: int


class CheckpointManager:
    """Save/list/load checkpoint blobs with ladder retention."""

    def __init__(self, dir: str, keep_recent: int = 3, keep_total: int = 8):
        self.dir = dir
        self.keep_recent = max(1, keep_recent)
        self.keep_total = max(self.keep_recent, keep_total)
        os.makedirs(dir, exist_ok=True)

    # -- listing -------------------------------------------------------
    def list(self) -> List[CheckpointInfo]:
        """All rungs, NEWEST first (epoch desc, then seq desc)."""
        out: List[CheckpointInfo] = []
        for name in os.listdir(self.dir):
            m = _NAME_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.dir, name)
            out.append(CheckpointInfo(
                path=path, name=name, epoch=int(m.group(1)),
                seq=int(m.group(2)), size=os.path.getsize(path),
            ))
        out.sort(key=lambda c: (c.epoch, c.seq), reverse=True)
        return out

    # -- save ----------------------------------------------------------
    def save(self, epoch: int, blob: bytes) -> str:
        """Frame + write one blob; apply ladder retention.  Returns the
        file name."""
        seq = max((c.seq for c in self.list()), default=0) + 1
        name = f"ck-{epoch:012d}-{seq:04d}.ltck"
        w = Writer()
        w.buf += CKPT_MAGIC
        w.u8(CKPT_VERSION)
        w.varint(epoch)
        w.u32le(zlib.crc32(blob))
        framed = bytes(w.buf) + blob
        framed = faultinject.mangle("ckpt_corrupt", framed)
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(framed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # commit the rename itself: record_checkpoint prunes the WAL
        # segments this rung covers right after, so a rename that the
        # fs journal hasn't landed would be the ONLY copy of history
        from .wal import fsync_dir

        fsync_dir(self.dir)
        obs.counter("persist.checkpoints_total").inc()
        obs.gauge(
            "persist.checkpoint_epoch", "epoch of the newest checkpoint"
        ).set(epoch)
        self.prune()
        return name

    # -- load ----------------------------------------------------------
    def load(self, info: CheckpointInfo) -> bytes:
        """Verified blob bytes; typed DecodeError on any damage."""
        with open(info.path, "rb") as f:
            data = f.read()
        if len(data) < 5 or data[:4] != CKPT_MAGIC:
            raise CodecDecodeError(f"{info.name}: not a checkpoint file")
        if data[4] > CKPT_VERSION:
            raise CodecDecodeError(f"{info.name}: checkpoint v{data[4]} too new")
        try:
            r = Reader(data)
            r.i = 5
            epoch = r.varint()
            crc = r.u32le()
            blob = data[r.i:]
        except (IndexError, ValueError, struct.error) as e:
            raise CodecDecodeError(f"{info.name}: malformed header: {e}") from None
        if epoch != info.epoch:
            raise CodecDecodeError(
                f"{info.name}: header epoch {epoch} != filename epoch {info.epoch}"
            )
        if zlib.crc32(blob) != crc:
            raise CodecDecodeError(f"{info.name}: checkpoint crc mismatch")
        return blob

    def iter_valid(self, on_skip=None):
        """Yield ``(info, blob)`` down the ladder, skipping rungs that
        fail crc/decode (each skip ticks the fallback counter and
        ``on_skip(info, error)`` when given).  The ONE ladder walk —
        recovery and load_newest both ride it so fallback semantics
        cannot drift."""
        for info in self.list():
            try:
                blob = self.load(info)
            except DecodeError as e:
                obs.counter(
                    "persist.ckpt_fallbacks_total",
                    "corrupt checkpoint rungs skipped during recovery",
                ).inc()
                if on_skip is not None:
                    on_skip(info, e)
                continue
            yield info, blob

    def load_newest(self) -> Optional[Tuple[CheckpointInfo, bytes]]:
        """Newest rung that loads clean, walking DOWN the ladder past
        corrupt blobs (each fallback counted)."""
        return next(self.iter_valid(), None)

    # -- retention -----------------------------------------------------
    def prune(self) -> int:
        """Apply the ladder: keep the newest ``keep_recent``; thin the
        rest to geometric epoch spacing; cap at ``keep_total``."""
        rungs = self.list()
        keep = rungs[: self.keep_recent]
        older = rungs[self.keep_recent:]
        if keep and older:
            newest_epoch = keep[0].epoch
            # each surviving older rung must be at least 2x the age of
            # the previously kept one (age = epoch distance from newest)
            min_age = max(1, newest_epoch - keep[-1].epoch) * 2
            for c in older:
                age = newest_epoch - c.epoch
                if age >= min_age and len(keep) < self.keep_total:
                    keep.append(c)
                    min_age = age * 2
        removed = 0
        keep_paths = {c.path for c in keep}
        for c in rungs:
            if c.path not in keep_paths:
                os.unlink(c.path)
                removed += 1
        return removed
