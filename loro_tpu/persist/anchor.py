"""Bounded-replay recovery + the shallow-base host-mirror oracle.

Two problems had the same root cause (ROADMAP open item): the
ResidentServer round journal grew for the server's life because BOTH
consumers needed history since birth — ``recover()`` replayed every
round onto a fresh device batch, and the HOST-MIRROR degradation path
seeded per-doc LoroDoc replicas from round zero (folded checkpoint
state cannot seed a replica).

The fix is an *anchor*: at every checkpoint, the journal rounds are
folded into per-doc **shallow snapshots** (``codec/snapshot.py`` state
export via ``ExportMode.StateOnly`` — state at the doc's head, history
trimmed below it, the reference's shallow-snapshot floor).  A fresh
LoroDoc imports that blob and keeps integrating newer rounds through
the normal backfill machinery, so the mirror no longer needs history
below the anchor — and the journal can be trimmed to rounds SINCE the
checkpoint (Eg-walker's principle: merge cost proportional to
concurrent work, not total history; arxiv 2409.14252).

``recover_server(durable_dir)`` is the crash path: restore the newest
checkpoint that loads clean (falling DOWN the ladder past corrupt
rungs), then replay only the WAL rounds after its epoch.  With no
valid rung at all it rebuilds from the WAL meta record and replays
from birth — strictly the old behavior, now the worst case instead of
the only case.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..codec.binary import Reader, Writer
from ..errors import CodecDecodeError, DecodeError, PersistError
from ..obs import metrics as obs
from .wal import DurableLog, read_cid_opt, write_cid_opt

ANCHOR_VERSION = 2


class MirrorAnchor:
    """Per-doc shallow snapshot anchors at one journal epoch.

    ``advance(rounds, cid)`` folds journal rounds (epoch-ascending,
    frozen wire bytes) newer than the anchor into the per-doc blobs;
    ``seed_engine()`` builds a ``hostpath.HostEngine`` whose docs start
    from the anchors instead of from birth.

    ``deep=True`` anchors fold FULL snapshots (history included)
    instead of StateOnly blobs: the seeded mirror docs can then export
    updates since birth — the capability live doc migration between
    shards needs (docs/SHARDING.md) — at the cost of history-sized
    instead of state-sized anchor blobs."""

    def __init__(self, family: str, n_docs: int, deep: bool = False):
        self.family = family
        self.n_docs = n_docs
        self.deep = deep
        self.epoch = 0
        self.cid = None
        # per-doc StateOnly blob (b"" = doc still empty at the anchor)
        self.doc_blobs: List[bytes] = [b""] * n_docs
        # per-doc first-seen container ids (the device batches scope
        # map/counter reads by the cids in that doc's ops; the state
        # blob alone cannot reproduce first-seen order)
        self.seen_cids: List[list] = [[] for _ in range(n_docs)]

    # -- mirror seeding ------------------------------------------------
    def seed_engine(self):
        """HostEngine whose docs imported the anchor blobs.  Reads are
        byte-identical to a from-birth mirror by the shallow-snapshot
        contract (state at the anchor head, later rounds backfill)."""
        from ..resilience.hostpath import HostEngine

        eng = HostEngine(self.family, self.n_docs)
        eng._cid = self.cid
        for i, blob in enumerate(self.doc_blobs):
            if blob:
                eng.docs[i].import_(blob, origin="persist-anchor")
            eng._seen_cids[i] = {c: None for c in self.seen_cids[i]}
        return eng

    def advance(self, rounds, cid=None) -> None:
        """Fold journal rounds (``(epoch, frozen_updates, cid)``) with
        epoch > self.epoch into fresh anchors.  Only TOUCHED docs (a
        non-None entry in some folded round) are imported and
        re-exported — untouched docs keep their blobs, so a checkpoint
        costs O(active docs), not O(fleet state).  Re-exporting keeps
        the anchor state-sized: fold docs never accumulate history
        across checkpoints."""
        from ..doc import ExportMode
        from ..resilience.hostpath import HostEngine

        todo = [r for r in rounds if r[0] > self.epoch]
        if cid is not None:
            self.cid = cid
        if not todo:
            return
        touched = {
            di
            for _e, ups, _c in todo if ups is not None
            for di, u in enumerate(ups) if u is not None
        }
        eng = HostEngine(self.family, self.n_docs)
        eng._cid = self.cid
        for i in touched:
            if self.doc_blobs[i]:
                eng.docs[i].import_(self.doc_blobs[i], origin="persist-anchor")
            eng._seen_cids[i] = {c: None for c in self.seen_cids[i]}
        for epoch, ups, c in todo:
            eng.apply(ups, c)
            self.epoch = epoch
        if eng._cid is not None:
            self.cid = eng._cid
        for i in touched:
            d = eng.docs[i]
            if not len(d.oplog_vv()):
                self.doc_blobs[i] = b""
            elif self.deep:
                self.doc_blobs[i] = d.export(ExportMode.Snapshot)
            else:
                self.doc_blobs[i] = d.export(ExportMode.StateOnly)
            self.seen_cids[i] = list(eng._seen_cids[i])

    # -- wire ----------------------------------------------------------
    def encode(self) -> bytes:
        w = Writer()
        # non-deep anchors stay on the v1 layout byte-for-byte; the
        # flags byte exists only in v2 (deep) blobs.  Literal layout
        # versions on purpose: a future ANCHOR_VERSION bump must not
        # silently re-tag these bytes
        w.u8(2 if self.deep else 1)
        if self.deep:
            w.u8(1)
        w.str_(self.family)
        w.varint(self.n_docs)
        w.varint(self.epoch)
        write_cid_opt(w, self.cid)
        for blob in self.doc_blobs:
            w.bytes_(blob)
        for cids in self.seen_cids:
            w.varint(len(cids))
            for c in cids:
                write_cid_opt(w, c)
        return bytes(w.buf)

    @classmethod
    def decode(cls, data: bytes) -> "MirrorAnchor":
        try:
            r = Reader(data)
            ver = r.u8()
            if ver > ANCHOR_VERSION:
                raise CodecDecodeError(f"mirror anchor v{ver} too new")
            deep = bool(r.u8() & 1) if ver >= 2 else False
            a = cls(r.str_(), r.varint(), deep=deep)
            a.epoch = r.varint()
            a.cid = read_cid_opt(r)
            a.doc_blobs = [r.bytes_() for _ in range(a.n_docs)]
            a.seen_cids = [
                [read_cid_opt(r) for _ in range(r.varint())]
                for _ in range(a.n_docs)
            ]
            return a
        except CodecDecodeError:
            raise
        except (IndexError, ValueError, UnicodeDecodeError) as e:
            raise CodecDecodeError(f"malformed mirror anchor: {e}") from None


# ---------------------------------------------------------------------------
# recovery
# ---------------------------------------------------------------------------


@dataclass
class RecoveryReport:
    """What recovery actually did (tests assert bounded replay on it;
    ``server.last_recovery`` keeps it)."""

    checkpoint_epoch: int = 0          # 0 = no valid rung, cold replay
    checkpoint_name: str = ""
    checkpoints_skipped: int = 0       # corrupt rungs fallen past
    rounds_replayed: int = 0
    recovered_epoch: int = 0
    cold: bool = False                 # rebuilt from WAL meta, no rung


def recover_server(durable_dir: str, mesh=None, fsync: bool = True):
    """Reopen a durable directory after a crash: newest valid
    checkpoint + bounded WAL replay.  Returns a live ResidentServer
    (durable journaling re-attached, ``last_recovery`` holding the
    RecoveryReport).

    Raises ``PersistError`` if the directory has no WAL at all, and
    typed ``DecodeError`` if the WAL itself is corrupt beyond the
    torn-tail contract."""
    from ..parallel.server import ResidentServer

    log = DurableLog(durable_dir, fsync=fsync)
    if log.meta is None and not log.checkpoints.list():
        log.close()
        raise PersistError(
            f"{durable_dir}: no WAL meta and no checkpoints — nothing to "
            "recover (a fresh server writes the meta at construction; "
            "this directory never held one, or died before the write)"
        )
    report = RecoveryReport()

    def _skip(info, err):
        report.checkpoints_skipped += 1

    try:
        srv = None
        for info, blob in log.checkpoints.iter_valid(on_skip=_skip):
            try:
                srv = ResidentServer.restore(blob, mesh=mesh)
            except DecodeError:
                # crc-clean rung whose server state won't decode: fall
                # further down the ladder like any other corrupt rung
                _skip(info, None)
                obs.counter(
                    "persist.ckpt_fallbacks_total",
                    "corrupt checkpoint rungs skipped during recovery",
                ).inc()
                continue
            report.checkpoint_epoch = info.epoch
            report.checkpoint_name = info.name
            break
        if srv is None:
            # cold path: every rung corrupt (or none existed) — rebuild
            # from the WAL meta record and replay from birth
            meta = log.meta
            if meta is None:
                raise DecodeError(
                    f"{durable_dir}: every checkpoint rung is corrupt and "
                    "the WAL has no meta record — unrecoverable"
                )
            if log.wal.pruned_below > 0:
                # rounds at/under this epoch were DELETED at checkpoint
                # time: a from-birth replay would silently fabricate a
                # truncated history — typed refusal, never garbage
                raise DecodeError(
                    f"{durable_dir}: every checkpoint rung is corrupt and "
                    f"the WAL was pruned below epoch "
                    f"{log.wal.pruned_below} — history incomplete, "
                    "unrecoverable"
                )
            report.cold = True
            srv = ResidentServer(
                meta.family, meta.n_docs, mesh=mesh,
                auto_grow=meta.auto_grow, host_fallback=meta.host_fallback,
                auto_checkpoint=False,
                mirror_anchor="deep" if meta.deep_anchor else True,
                **meta.caps,
            )
        # bounded replay: only rounds after the restored epoch
        tail = log.wal.rounds_after(report.checkpoint_epoch)
        srv._replay_journal_tail(tail)
        if log.meta is None and srv._caps is not None:
            # ladder-only recovery (WAL lost/empty): re-seed the meta
            # record from the v3 checkpoint's caps so a LATER cold
            # recovery of this directory stays possible
            from .wal import WalMeta

            log.ensure_meta(WalMeta(
                family=srv.family, n_docs=srv.n_docs,
                caps=dict(srv._caps), auto_grow=srv._auto_grow,
                host_fallback=srv._host_fallback,
            ))
    except BaseException:
        log.close()  # never leak the active segment handle on failure
        raise
    report.rounds_replayed = len(tail)
    report.recovered_epoch = srv.epoch
    if report.checkpoint_name:
        # tiered residency (parallel/residency.py): the restored rung
        # carries every doc's anchor blob, so it becomes the backing
        # rung for the docs that were cold at checkpoint time — their
        # blobs drop out of RAM again unless the WAL replay already
        # revived them.  No-op for plain servers.
        hook = getattr(srv.batch, "note_restored_rung", None)
        if hook is not None:
            srv.attach_durable(log)  # the hook re-reads/writes the dir
            hook(report.checkpoint_name)
    obs.counter(
        "persist.recovery_rounds_replayed_total",
        "WAL rounds replayed by recover_server",
    ).inc(len(tail))
    obs.counter("persist.recoveries_total").inc(
        outcome="cold" if report.cold else "checkpoint"
    )
    srv.attach_durable(log)
    srv.last_recovery = report
    return srv


def open_server(durable_dir: str, family: Optional[str] = None,
                n_docs: Optional[int] = None, mesh=None, fsync: bool = True,
                **kw):
    """Open-or-create: recover when the directory holds durable state
    (a WAL meta/rounds or a checkpoint ladder), else build a fresh
    durable server (``family``/``n_docs`` required then).  A WAL that
    died before its meta record — bare segment headers, no rounds, no
    rungs — counts as empty, so the directory never dead-ends.  The
    convenience entry point examples/ and the soaks use."""
    probe = DurableLog(durable_dir, fsync=fsync)
    held = probe.in_use() or probe.meta is not None
    probe.close()
    if held:
        return recover_server(durable_dir, mesh=mesh, fsync=fsync)
    if family is None or n_docs is None:
        raise PersistError(
            f"{durable_dir}: empty durable dir — pass family/n_docs to "
            "create a fresh server"
        )
    from ..parallel.server import ResidentServer

    return ResidentServer(
        family, n_docs, mesh=mesh, durable_dir=durable_dir,
        durable_fsync=fsync, **kw,
    )
