"""Execute a chaos plan against the composed stack.

``ChaosRunner.run(plan)`` walks the step list on one thread (the stack
underneath stays genuinely concurrent — fan-in workers, pipeline
executors, read-plane windows, follower shipping), journals per-step
outcomes, runs the invariant barriers, and on the first violating
barrier dumps a replayable JSON **artifact** (config + full step trace
+ violations) and stops.

**Journal.**  One JSONL line per executed step (flushed — the OS page
cache survives a SIGKILL).  Edit steps record the ACKED payload bytes
(base64), which is what makes the reference oracle *regenerable*: a
resuming process (``resume_from=``) rebuilds the oracle docs by
importing the journaled payloads in order — no dependence on the
recovering servers it is about to judge.  Topology steps (``reopen``
/ ``promote`` / ``kill``) record the surviving directory layout so a
resume fronts the right dirs.

**Hold points.**  ``hold_at=K`` executes steps ``i < K``, flushes
every plane (all accepted pushes committed + journaled), writes the
``CHAOS_READY`` marker and sleeps — the orchestrating parent
(tests/soak_chaos.py) SIGKILLs there, recovers in a fresh process with
``resume_from=K+1`` and verifies nothing acked was lost.  Executed
WITHOUT an orchestrator, a ``kill`` step downgrades to ``reopen`` on
every family (counted as ``chaos.kill_downgraded_total``) so plans
stay replayable and shrinkable in-process.
"""
from __future__ import annotations

import base64
import json
import os
import time
from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional

from ..errors import ChaosError
from ..obs import metrics as obs
from ..resilience import faultinject
from .invariants import InvariantChecker, Violation
from .plan import ChaosConfig, Step, generate_plan, steps_from_json, trace_json
from .stack import ChaosStack

ARTIFACT_VERSION = 1


@dataclass
class ChaosReport:
    """One run's outcome: the verdict, every violation, and the trace
    (the full input plan — what the artifact replays)."""

    config: ChaosConfig
    steps_run: int = 0
    checks: int = 0
    violations: List[Violation] = field(default_factory=list)
    trace: List[Step] = field(default_factory=list)
    fired: Dict[str, int] = field(default_factory=dict)
    held: bool = False

    @property
    def clean(self) -> bool:
        return not self.violations

    def to_artifact(self) -> dict:
        from ..obs import flight

        return {
            "version": ARTIFACT_VERSION,
            "config": self.config.to_json(),
            "trace": [s.to_json() for s in self.trace],
            "violations": [v.to_json() for v in self.violations],
            "steps_run": self.steps_run,
            "checks": self.checks,
            "fired": dict(self.fired),
            "verdict": "clean" if self.clean else "violation",
            # black box: the flight-recorder tail at artifact time —
            # what was in flight when the violation surfaced.  Replay
            # ignores it (the executable plan is `trace`), so the
            # determinism gate (trace bytes) is unaffected.
            "flight": flight.tail(200),
        }

    def trace_json(self) -> str:
        return trace_json(self.trace)


def load_artifact(path: str) -> dict:
    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, ValueError) as e:
        raise ChaosError(f"unreadable chaos artifact {path}: {e}") from e
    if not isinstance(art, dict) or art.get("version") != ARTIFACT_VERSION:
        raise ChaosError(
            f"{path}: not a v{ARTIFACT_VERSION} chaos artifact "
            f"(got version {art.get('version') if isinstance(art, dict) else '?'})"
        )
    return art


class ChaosRunner:
    """One run = one plan executed against one durable root.

    ``journal_path`` defaults to ``<root>/chaos-journal.jsonl``;
    ``artifact_path`` to ``<root>/chaos-artifact.json``.  Pass
    ``resume_from=K`` to continue a crashed run: the stack recovers
    from the durable dirs, the reference oracle regenerates from the
    journal, and execution starts at step K.
    """

    def __init__(self, cfg: ChaosConfig, root: str,
                 journal_path: Optional[str] = None,
                 artifact_path: Optional[str] = None):
        self.cfg = cfg
        self.root = root
        self.journal_path = journal_path or os.path.join(
            root, "chaos-journal.jsonl")
        self.artifact_path = artifact_path or os.path.join(
            root, "chaos-artifact.json")
        self.stack: Optional[ChaosStack] = None
        self.oracle: List = []
        self._journal = None

    # -- journal --------------------------------------------------------
    def _open_journal(self, append: bool) -> None:
        os.makedirs(self.root, exist_ok=True)
        self._journal = open(self.journal_path, "a" if append else "w")

    def _log(self, step: Step, **extra) -> None:
        rec = {"i": step.i, "kind": step.kind}
        rec.update(extra)
        self._journal.write(json.dumps(rec, sort_keys=True) + "\n")
        self._journal.flush()

    def _replay_journal(self, upto: int) -> dict:
        """Rebuild resume state from journal lines with ``i < upto``:
        oracle payload imports, per-family acked watermarks, surviving
        directory topology.  Returns the topology overrides."""
        from .. import LoroDoc

        self.oracle = [LoroDoc(peer=1) for _ in range(self.cfg.docs)]
        acked: Dict[str, int] = {}
        topo: dict = {}
        if not os.path.exists(self.journal_path):
            raise ChaosError(
                f"resume_from set but no journal at {self.journal_path}")
        with open(self.journal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    raise ChaosError(
                        f"corrupt chaos journal line: {line[:80]}") from e
                if int(rec.get("i", -1)) >= upto:
                    continue
                if rec.get("payload"):
                    di = int(rec["di"])
                    self.oracle[di].import_(
                        base64.b64decode(rec["payload"]))
                for fam, ep in (rec.get("acked") or {}).items():
                    acked[fam] = max(acked.get(fam, 0), int(ep))
                if rec.get("topo"):
                    topo.update(rec["topo"])
        topo["acked"] = acked
        return topo

    def _topo_snapshot(self) -> dict:
        return {
            p.family: {"dir": p.dir, "fol_gen": p.fol_gen}
            for p in self.stack.planes.values()
        }

    # -- lifecycle ------------------------------------------------------
    def _boot(self, resume_from: int) -> None:
        from .. import LoroDoc

        if resume_from:
            topo = self._replay_journal(resume_from)
            acked = topo.pop("acked")
            # disjoint peer range per resume segment: abandoned
            # pre-crash client peers must never be reused
            stack = ChaosStack(self.cfg, self.root, recover=True,
                              peer_base=1000 * (resume_from + 1))
            for fam, t in topo.items():
                if fam in stack.planes:
                    stack.planes[fam].fol_gen = t.get(
                        "fol_gen", stack.planes[fam].fol_gen)
            for fam, ep in acked.items():
                if fam in stack.planes:
                    stack.planes[fam].max_acked = ep
            self.stack = stack
            self._open_journal(append=True)
        else:
            self.oracle = [LoroDoc(peer=1) for _ in range(self.cfg.docs)]
            self.stack = ChaosStack(self.cfg, self.root)
            self._open_journal(append=False)

    def _hold(self) -> None:
        """Flush everything (accepted pushes committed + journaled —
        the WAL bytes are in the OS page cache, which a SIGKILL cannot
        touch), publish the READY marker, and sleep until the parent
        kills us."""
        for p in self.stack.planes.values():
            p.sync.flush()
        marker = self.stack.hold_marker()
        with open(marker + ".tmp", "w") as f:
            f.write("ready")
        os.replace(marker + ".tmp", marker)
        time.sleep(600.0)
        raise ChaosError(
            "hold point expired: the orchestrating parent never killed "
            "this process (it owns the SIGKILL; 600s is its deadline)")

    # -- the run --------------------------------------------------------
    def run(self, plan: Optional[List[Step]] = None, resume_from: int = 0,
            hold_at: Optional[int] = None) -> ChaosReport:
        plan = generate_plan(self.cfg) if plan is None else plan
        report = ChaosReport(config=self.cfg, trace=list(plan))
        self._boot(resume_from)
        checker = InvariantChecker(self.stack, self.oracle)
        try:
            for step in plan:
                if step.i < resume_from:
                    continue
                if hold_at is not None and step.i >= hold_at:
                    report.held = True
                    self._hold()  # never returns
                self._execute(step, report, checker)
                report.steps_run += 1
                if report.violations:
                    break
            if not report.violations and (
                    not plan or plan[-1].kind != "check"
                    or report.steps_run == 0):
                # shrunk subsets may have dropped the trailing barrier;
                # a run must never end unjudged
                self._barrier(Step(i=len(plan), kind="check"),
                              report, checker)
        finally:
            self._finish(report)
        return report

    def _finish(self, report: ChaosReport) -> None:
        fired: Dict[str, int] = {}
        for row in obs.counter("faultinject.fired_total").snapshot()["values"]:
            site = row["labels"].get("site", "?")
            fired[site] = fired.get(site, 0) + int(row["value"])
        report.fired = fired
        faultinject.clear()
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        if self.stack is not None:
            self.stack.close()
            self.stack = None
        if report.violations:
            with open(self.artifact_path + ".tmp", "w") as f:
                json.dump(report.to_artifact(), f, indent=1)
            os.replace(self.artifact_path + ".tmp", self.artifact_path)

    # -- step executors -------------------------------------------------
    def _execute(self, step: Step, report: ChaosReport,
                 checker: InvariantChecker) -> None:
        stack = self.stack
        kind, pr = step.kind, step.params
        obs.counter("chaos.steps_total",
                    "chaos plan steps executed").inc(kind=kind)
        if kind == "edit":
            c = stack.pick_client(int(pr["client"]))
            c.edit(Random(int(pr["seed"])))
            payload = c.export_delta()
            acked = stack.push_payload(c, payload, self.oracle)
            full = len(acked) == len(self.cfg.families)
            self._log(step, di=c.di, acked=acked,
                      payload=base64.b64encode(payload).decode()
                      if full else None)
        elif kind == "pull":
            c = stack.pick_client(int(pr["client"]))
            if not c.stalled:
                for detail in stack.pull_client(c):
                    report.violations.append(
                        Violation("pull_identity", "*", detail, step.i))
            self._log(step, stalled=c.stalled)
        elif kind == "fault":
            stack.arm_fault(pr)
            self._log(step, site=pr["site"])
        elif kind == "join":
            stack.new_client(int(pr["doc"]) % self.cfg.docs)
            self._log(step)
        elif kind == "leave":
            gone = stack.drop_client(int(pr["client"]))
            self._log(step, left=None if gone is None else gone.n)
        elif kind == "stall":
            stack.pick_client(int(pr["client"])).stalled = True
            self._log(step)
        elif kind == "checkpoint":
            stack.checkpoint(pr["family"])
            self._log(step)
        elif kind == "compact":
            stack.compact(pr["family"])
            self._log(step)
        elif kind == "net":
            for detail in stack.net_nemesis(pr["family"], int(pr["seed"])):
                report.violations.append(
                    Violation("net_identity", pr["family"], detail, step.i))
            self._log(step)
        elif kind == "demote":
            ok = stack.demote(pr["family"], int(pr["pick"]))
            self._log(step, demoted=ok)
        elif kind == "migrate":
            ok = stack.migrate(pr["family"], int(pr["doc"]))
            self._log(step, migrated=ok)
        elif kind == "reopen":
            stack.reopen(pr["family"])
            self._log(step, topo=self._topo_snapshot())
        elif kind == "promote":
            stack.promote(pr["family"])
            self._log(step, topo=self._topo_snapshot())
        elif kind == "kill":
            # no orchestrator reached this step in-process: downgrade
            # to the graceful-recovery nemesis on every family so the
            # plan stays executable (soak_chaos delivers the real
            # SIGKILL at these indexes via hold_at)
            obs.counter(
                "chaos.kill_downgraded_total",
                "kill steps executed in-process as reopen-all").inc()
            for fam in self.cfg.families:
                stack.reopen(fam)
            self._log(step, topo=self._topo_snapshot(), downgraded=True)
        elif kind == "plant":
            # test-only synthetic violation: corrupt the REFERENCE
            # oracle (never the stack) — the next barrier's
            # convergence/client checks must catch it
            d = self.oracle[0]
            d.get_map("m").set("__chaos_planted__", int(pr["seed"]))
            d.commit()
            self._log(step)
        elif kind == "check":
            self._barrier(step, report, checker)
        else:
            raise ChaosError(f"unknown chaos step kind {step.kind!r}")

    def _barrier(self, step: Step, report: ChaosReport,
                 checker: InvariantChecker) -> None:
        report.checks += 1
        found = checker.check(step.i)
        report.violations.extend(found)
        self._log(step, violations=[v.to_json() for v in found])
