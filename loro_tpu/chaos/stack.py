"""The composed serving stack the chaos plane drives.

One ``ChaosStack`` is the fully composed regime the ROADMAP's
"millions of users" north star implies, per container family:

    ShardedResidentServer (durable group-commit WAL + checkpoint
    ladder, tiered hot/warm/cold residency, per-shard PipelinedIngest)
      <- SyncServer (fan-in, sessions, presence, device read plane)
      <- replication.enable + a live ShardedFollower (WAL shipping)

plus N writer **clients** (each a real ``LoroDoc`` pushing deltas to
every family server and reconstructing itself from pulls — the
soak_sync pattern) and a runner-owned **reference oracle**: one host
``LoroDoc`` per doc index importing every ACKED push payload.  The
reference oracle is the independent ground truth the invariant checker
compares every plane against; it deliberately never touches any server
code path.

Client operations retry on *typed* injected failures (an armed
``sync_push`` fault fails the push; the retry runs with the fault
exhausted), so a convergent end state is reachable under any SAFE-arm
schedule; anything atypical (a raw ``DeviceFailure`` escaping to a
session, retries not sufficing) is recorded and surfaces as an
``obs_sanity`` violation at the next barrier — sessions observing raw
device errors is exactly what the degradation contract forbids.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..errors import (
    ChaosError,
    DeviceFailure,
    LoroError,
    ReplicationError,
    ShardingError,
    SyncError,
)
from ..obs import metrics as obs
from ..resilience import faultinject
from .plan import ChaosConfig

#: per-family construction caps (small: chaos runs are breadth tests)
CAPS = {
    "text": dict(capacity=1 << 12),
    "map": dict(slot_capacity=128),
    "tree": dict(move_capacity=1 << 11, node_capacity=256),
    "counter": dict(slot_capacity=32),
    "movable": dict(capacity=1 << 11, elem_capacity=256),
}

#: typed failures a client operation may legitimately see under an
#: armed fault — anything else escaping a session call is an
#: obs-sanity violation (DeviceFailure must NEVER reach a session)
_TYPED_CLIENT_ERRORS = (SyncError, ReplicationError,
                       faultinject.InjectedFault, TimeoutError)

_PUSH_RETRIES = 4


def family_cids() -> Dict[str, object]:
    """Served container ids per family.  Root container ids are
    name-derived (no peer component), so a scratch doc's ids are THE
    ids every client doc produces for the same root names."""
    from .. import LoroDoc

    d = LoroDoc(peer=1)
    d.get_text("t")
    d.get_tree("tr")
    d.get_movable_list("ml")
    return {
        "text": d.get_text("t").id,
        "tree": d.get_tree("tr").id,
        "movable": d.get_movable_list("ml").id,
        "map": None,
        "counter": None,
    }


class FamilyPlane:
    """One family's slice of the stack (leader fleet + sync front +
    follower) plus its per-family bookkeeping."""

    def __init__(self, family: str):
        self.family = family
        self.resident = None
        self.sync = None
        self.follower = None
        self.dir: Optional[str] = None
        self.fol_gen = 0
        self.max_acked = 0

    def fol_dir(self, root: str) -> str:
        return os.path.join(root, f"{self.family}-fol-g{self.fol_gen}")


class ChaosClient:
    """One writer replica: a client ``LoroDoc`` + one session per
    family server.  Every edit touches all five container families so
    every family server sees ops regardless of the configured family
    subset (extra containers ride along in the payload and are simply
    not served by that family's device plane)."""

    def __init__(self, stack: "ChaosStack", n: int, di: int, peer: int):
        from .. import LoroDoc

        self.stack = stack
        self.n = n
        self.di = di
        self.peer = peer
        self.stalled = False
        self.doc = LoroDoc(peer=peer)
        self.sess = {
            fam: stack.planes[fam].sync.connect(sid=f"c{n}-{fam}")
            for fam in stack.cfg.families
        }
        fam0 = stack.cfg.families[0]
        data = self.sess[fam0].pull(di)
        if data:
            self.doc.import_(bytes(data))
        self.mark = self.doc.oplog_vv()

    def edit(self, rng) -> None:
        """Deterministic multi-container edit burst (the soak_sync op
        mix) against the client's own doc; commit, no push."""
        d = self.doc
        for _ in range(rng.randint(2, 5)):
            kind = rng.randint(0, 4)
            if kind == 0:
                t = d.get_text("t")
                L = len(t)
                if L > 4 and rng.random() < 0.3:
                    t.delete(rng.randrange(L - 2), 2)
                else:
                    t.insert(rng.randint(0, L),
                             rng.choice(["xy", "q ", "lo"]))
            elif kind == 1:
                d.get_map("m").set(rng.choice(["k1", "k2"]),
                                   rng.randrange(99))
            elif kind == 2:
                tr = d.get_tree("tr")
                nodes = tr.nodes()
                if not nodes or rng.random() < 0.5:
                    tr.create(rng.choice(nodes) if nodes else None)
                else:
                    tr.delete(rng.choice(nodes))
            elif kind == 3:
                d.get_counter("c").increment(rng.randint(-9, 9))
            else:
                ml = d.get_movable_list("ml")
                L = len(ml)
                if L >= 2 and rng.random() < 0.4:
                    ml.move(rng.randrange(L), rng.randrange(L))
                else:
                    ml.insert(rng.randint(0, L), f"s{self.n}")
        d.commit()

    def export_delta(self) -> bytes:
        payload = bytes(self.doc.export_updates(self.mark))
        self.mark = self.doc.oplog_vv()
        return payload

    def close(self) -> None:
        for s in self.sess.values():
            try:
                s.close()
            except SyncError:
                pass  # server already closed underneath us


class ChaosStack:
    """Build (or recover) the composed stack and drive it.

    All mutation runs on the caller's single thread; the only
    background threads are the stack's OWN planes (fan-in workers,
    pipeline executors, read-plane windows) — which is the point: the
    chaos plan is deterministic, the stack under it is the real
    concurrent machine.
    """

    def __init__(self, cfg: ChaosConfig, root: str, recover: bool = False,
                 peer_base: int = 1000):
        self.cfg = cfg
        self.root = root
        self.cids = family_cids()
        self.planes: Dict[str, FamilyPlane] = {}
        self.clients: List[ChaosClient] = []
        self._next_peer = peer_base
        self._next_client = peer_base
        # raw (non-typed) errors a session call surfaced — the
        # obs-sanity invariant reads and drains this
        self.raw_errors: List[str] = []
        self.unresolved: List[str] = []  # ops retries could not land
        # resolved push-ticket breakdowns (bounded window) — the
        # attribution invariant checks each one's stages telescope to
        # its end-to-end total (docs/OBSERVABILITY.md)
        self.breakdowns: List[dict] = []
        os.makedirs(root, exist_ok=True)
        for fam in cfg.families:
            p = FamilyPlane(fam)
            p.dir = os.path.join(root, fam)
            self.planes[fam] = p
            if recover:
                self._recover_plane(p)
            else:
                self._build_plane(p)
        # health plane riding the stack (docs/OBSERVABILITY.md "Health &
        # heat"): ticked at settle + checked by the `health` invariant.
        # Ticks read the stack, never steer it — the plan stays a pure
        # function of (config, seed).
        from ..obs import health as health_mod

        self.health = health_mod.HealthPlane(window_s=300.0)
        self._refresh_health()
        for i in range(cfg.sessions):
            self.new_client(i % cfg.docs)

    # -- plane lifecycle ------------------------------------------------
    def _leader_kwargs(self) -> dict:
        cfg = self.cfg
        kw = dict(durable_fsync="group", fsync_window=cfg.fsync_window)
        if cfg.hot_slots is not None:
            kw["hot_slots"] = cfg.hot_slots
        return kw

    def _build_plane(self, p: FamilyPlane) -> None:
        from ..parallel.sharded import ShardedResidentServer

        cfg = self.cfg
        p.resident = ShardedResidentServer(
            p.family, cfg.docs, shards=cfg.shards, durable_dir=p.dir,
            **self._leader_kwargs(), **CAPS[p.family],
        )
        self._front(p)

    def _recover_plane(self, p: FamilyPlane) -> None:
        from ..persist import recover_sharded_server

        p.resident = recover_sharded_server(p.dir)
        self._front(p)

    def _front(self, p: FamilyPlane) -> None:
        """Attach replication + sync front + follower to ``p.resident``
        (shared by build, recover, reopen and promote)."""
        from ..replication import ShardedFollower, enable
        from ..sync import SyncServer

        cfg = self.cfg
        if cfg.follower:
            # re-claiming the same leader id after a reopen is
            # idempotent (manifest.claim_leader) — the fence, the
            # .visible marker and the retention pin re-install
            enable(p.resident, f"chaos-{p.family}")
        p.sync = SyncServer.over(p.resident, cid=self.cids[p.family],
                                 coalesce=cfg.coalesce)
        if cfg.follower:
            p.follower = ShardedFollower(
                p.dir, p.fol_dir(self.root),
                follower_id=f"chaos-fol-{p.family}", leader=p.resident,
            )

    def _refresh_health(self) -> None:
        """Point the health plane at the CURRENT topology (the first
        family's serving pair + every live follower) — called after
        build/recover/reopen/promote."""
        p0 = self.planes[self.cfg.families[0]]
        self.health.attach_resident(p0.resident)
        self.health.attach_sync(p0.sync)
        self.health.set_followers(
            [p.follower for p in self.planes.values()
             if p.follower is not None])

    def _teardown_plane(self, p: FamilyPlane) -> None:
        if p.follower is not None:
            p.follower.close()
            p.follower = None
        if p.sync is not None:
            p.sync.flush()
            p.sync.close()
            p.sync = None
        if p.resident is not None:
            p.resident.close()
            p.resident = None

    def _quiesce_faults(self) -> None:
        """Topology nemeses (reopen/promote/kill) run against a clean
        fault table: recovery replay on a device with an armed fatal
        fault fails typed BY CONTRACT (the operator retries) — inside
        a deterministic schedule the retry is this clear (counted)."""
        left = faultinject.active()
        if left:
            obs.counter("chaos.faults_cleared_total",
                        "armed-but-unfired faults cleared at barriers "
                        "and topology nemeses").inc(sum(left.values()))
        faultinject.clear()

    def reopen(self, family: str) -> None:
        """Graceful close + durable recovery + re-front + follower
        resume; clients reconnect from first-sync pulls (the recovered
        oracle is shallow, so a fresh client's first pull takes the
        snapshot path — load-bearing, same as docs/SYNC.md)."""
        self._quiesce_faults()
        p = self.planes[family]
        self._teardown_plane(p)
        self._recover_plane(p)
        self._refresh_health()
        obs.counter("chaos.reopens_total",
                    "in-process close+recover nemesis executions").inc(
            family=family)
        self.reset_clients()

    def promote(self, family: str) -> None:
        """Failover: drain + retire the leader, promote its follower
        to a writable fleet, re-front it, and start a fresh follower
        generation over the promoted directory."""
        p = self.planes[family]
        if p.follower is None:
            return
        self._quiesce_faults()
        p.sync.flush()
        p.resident.flush_durable()
        self.catch_up(p)
        promoted_dir = p.fol_dir(self.root)
        p.sync.close()
        p.resident.close()
        try:
            promoted = p.follower.promote(f"chaos-{family}")
        except (ReplicationError, faultinject.InjectedFault):
            # an armed repl_promote fault: a retried promote starts
            # clean (docs/REPLICATION.md)
            promoted = p.follower.promote(f"chaos-{family}")
        # discard the wrapper WITHOUT close(): a promoted follower's
        # per-shard residents ARE the promoted fleet
        p.follower = None
        p.resident = promoted
        p.dir = promoted_dir
        # pre-promote acked epochs are on the RETIRED leader's global
        # scale; the promoted fleet numbers its own.  The promote gate
        # (flush + catch_up to lag 0 before the flip) discharged them —
        # the durability watermark restarts on the promoted scale.
        p.max_acked = 0
        p.fol_gen += 1
        self._front(p)
        self._refresh_health()
        obs.counter("chaos.promotions_total",
                    "follower promotions executed").inc(family=family)
        self.reset_clients()

    # -- clients --------------------------------------------------------
    def new_client(self, di: int) -> ChaosClient:
        self._next_client += 1
        self._next_peer += 1
        c = ChaosClient(self, self._next_client, di, self._next_peer)
        self.clients.append(c)
        return c

    def drop_client(self, sel: int) -> Optional[ChaosClient]:
        if len(self.clients) <= 1:
            return None
        c = self.clients.pop(sel % len(self.clients))
        c.close()
        return c

    def pick_client(self, sel: int) -> ChaosClient:
        return self.clients[sel % len(self.clients)]

    def reset_clients(self) -> None:
        """Replace every client with a fresh replica reconstructed
        purely from pulls (fresh peer ids — abandoned local ops must
        never be resumed under a reused peer)."""
        old = list(self.clients)
        self.clients = []
        for c in old:
            c.close()
        for c in old:
            self.new_client(c.di)
        obs.counter("chaos.client_resets_total",
                    "client cohorts rebuilt from pulls").inc(len(old))

    # -- client operations (retry-on-typed protocol) --------------------
    def push_payload(self, c: ChaosClient, payload: bytes,
                     oracle_docs: List) -> Dict[str, int]:
        """Push one enveloped payload from client ``c`` to every family
        server — through ``c``'s OWN sessions: the commit hook advances
        the pushing session's pull frontier past the pushed ops
        ("the pusher holds its own ops"), so pushing through any other
        client's session silently desyncs that client's frontier from
        its doc.  Retries typed failures with the fault exhausted;
        applies the payload to the reference oracle once every family
        acked.  Returns per-family acked epochs ({} when the payload
        could not land — recorded, surfaces at the barrier)."""
        di = c.di
        acked: Dict[str, int] = {}
        for fam in self.cfg.families:
            p = self.planes[fam]
            err = None
            for _ in range(_PUSH_RETRIES):
                try:
                    tk = self._session_of(c, fam).push(di, payload)
                    acked[fam] = tk.epoch(120)
                    p.max_acked = max(p.max_acked, acked[fam])
                    bd = tk.breakdown()
                    bd["family"] = fam
                    self.breakdowns.append(bd)
                    if len(self.breakdowns) > 128:
                        del self.breakdowns[:64]
                    err = None
                    break
                except _TYPED_CLIENT_ERRORS as e:
                    err = e
                except Exception as e:  # tpulint: disable=LT-EXC(the chaos checker's business: a raw error reaching a session IS the obs_sanity violation being recorded)
                    err = e
                    self.raw_errors.append(
                        f"push {fam}/doc{di}: {type(e).__name__}: {e}")
                    break
            if err is not None and fam not in acked:
                self.unresolved.append(
                    f"push {fam}/doc{di}: {type(err).__name__}: {err}")
        if len(acked) == len(self.cfg.families):
            oracle_docs[di].import_(bytes(payload))
        return acked

    def _session_of(self, c: ChaosClient, fam: str):
        """``c``'s session on ``fam``, reconnected if the server closed
        it underneath (reopen churn).  A fresh session starts with an
        empty frontier — pulls re-serve ops the client already holds,
        which a CRDT import absorbs idempotently; the safe direction."""
        s = c.sess.get(fam)
        if s is None or s.closed:
            s = self.planes[fam].sync.connect(sid=f"c{c.n}-{fam}-r")
            c.sess[fam] = s
        return s

    def pull_client(self, c: ChaosClient) -> List[str]:
        """Pull every family for ``c``'s doc with the byte-identity
        gate: the served bytes must equal the serving oracle's own
        export from the session's frontier (ExportMode.Updates, or the
        first-sync snapshot on a shallow oracle).  Returns violation
        detail strings (empty = clean)."""
        from ..doc import ExportMode

        bad: List[str] = []
        fam0 = self.cfg.families[0]
        for fam in self.cfg.families:
            p = self.planes[fam]
            sess = c.sess[fam]
            if sess.closed:
                continue
            p.sync.flush()
            got = want = None
            for _ in range(3):
                try:
                    fvv = sess.frontier(c.di)
                    od = p.sync.oracle_doc(c.di)
                    if od.is_shallow() and not (od.shallow_since_vv() <= fvv) \
                            and len(fvv) == 0:
                        want = bytes(od.export(ExportMode.Snapshot))
                    else:
                        want = bytes(od.export(ExportMode.Updates(fvv)))
                    got = bytes(sess.pull(c.di))
                    break
                except _TYPED_CLIENT_ERRORS:
                    continue
                except Exception as e:  # tpulint: disable=LT-EXC(recorded as the obs_sanity violation, not swallowed)
                    self.raw_errors.append(
                        f"pull {fam}/doc{c.di}: {type(e).__name__}: {e}")
                    break
            if got is None:
                bad.append(f"pull {fam}/doc{c.di}: never served")
                continue
            if got != want:
                bad.append(
                    f"pull {fam}/doc{c.di}: served {len(got)}B != oracle "
                    f"export {len(want)}B")
            if fam == fam0 and got:
                c.doc.import_(got)
        c.mark = c.doc.oplog_vv()
        return bad

    # -- nemesis helpers ------------------------------------------------
    def net_nemesis(self, family: str, seed: int) -> List[str]:
        """Socket-edge nemesis (docs/NET.md): front ``family``'s LIVE
        SyncServer with a ``net.NetServer`` on an ephemeral port, pull
        one doc over a real TCP socket with the byte-identity gate
        (served bytes == the oracle's own export from the client's
        frontier), inject one seeded connection fault, kill the
        connection abruptly (the in-process SIGKILL stand-in) and
        reconnect-with-frontier — the resumed pull is gated the same
        way.  Pull-only by construction: pushes stay on the in-process
        sessions, so the reference oracle's acked-payload bookkeeping
        is untouched.  Returns violation detail strings."""
        import random as _random

        from ..doc import ExportMode
        from ..errors import DecodeError, NetError
        from ..net import NetClient, NetServer

        rng = _random.Random(seed)
        bad: List[str] = []
        p = self.planes[family]
        p.sync.flush()
        di = rng.randrange(self.cfg.docs)
        srv = cli = None
        try:
            srv = NetServer(p.sync)
            cli = NetClient("127.0.0.1", srv.port, family,
                            client_id=f"chaos-net-{seed}")
            cli.connect()

            def gate(tag: str) -> None:
                from ..core.version import VersionVector

                od = p.sync.oracle_doc(di)
                fvv = cli.frontiers.get(di) or VersionVector()
                if od.is_shallow() and not (od.shallow_since_vv() <= fvv) \
                        and len(fvv) == 0:
                    want = bytes(od.export(ExportMode.Snapshot))
                else:
                    want = bytes(od.export(ExportMode.Updates(fvv)))
                got = bytes(cli.pull(di))
                if got != want:
                    bad.append(
                        f"net {family}/doc{di} {tag}: socket pull "
                        f"{len(got)}B != oracle export {len(want)}B")

            gate("pre")
            arm = rng.randrange(3)
            if arm == 0:
                # writer stall: the pull's DELTA is delayed, never lost
                faultinject.inject("conn_stall", action="delay",
                                   delay_s=0.005, times=1)
                gate("stalled")
            elif arm == 1:
                # a bit-flipped inbound frame fails ONLY this
                # connection, typed; the reconnect below is the resume
                faultinject.inject("net_frame", action="bitflip", times=1)
                try:
                    cli.pull(di)
                    bad.append(
                        f"net {family}/doc{di}: bit-flipped frame was "
                        "served instead of failing typed")
                except (NetError, DecodeError):
                    pass
            else:
                # accept refusal: the FIRST reconnect attempt is
                # refused typed; the retry (fault exhausted) serves
                faultinject.inject("net_accept", action="raise", times=1)
                cli.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill = abrupt socket close, not a process signal)
                try:
                    cli.reconnect()
                    bad.append(
                        f"net {family}/doc{di}: accept fault did not "
                        "refuse the connection")
                except (NetError, DecodeError):
                    pass
            # abrupt kill + reconnect-with-frontier resume (retry once:
            # the armed fault above may have already torn the socket)
            cli.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill = abrupt socket close, not a process signal)
            for attempt in range(2):
                try:
                    cli.reconnect()
                    break
                except (NetError, DecodeError):
                    if attempt:
                        raise
            gate("resumed")
            obs.counter("chaos.net_nemeses_total",
                        "socket-edge nemesis executions").inc(
                family=family)
        finally:
            for site in ("conn_stall", "net_frame", "net_accept"):
                faultinject.clear(site)
            if cli is not None:
                cli.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill = abrupt socket close, not a process signal)
            if srv is not None:
                srv.close()
        return bad

    def checkpoint(self, family: str) -> bool:
        p = self.planes[family]
        try:
            p.sync.flush()
            p.resident.checkpoint()
            return True
        except DeviceFailure:
            # an armed fatal launch fault mid-checkpoint: typed refusal
            # (the ladder keeps its previous rung; retried next time)
            obs.counter("chaos.nemesis_refused_total",
                        "housekeeping steps refused typed under armed "
                        "faults").inc(kind="checkpoint", family=family)
            return False

    def compact(self, family: str) -> bool:
        try:
            self.planes[family].sync.compact()
            return True
        except DeviceFailure:
            obs.counter("chaos.nemesis_refused_total",
                        "housekeeping steps refused typed under armed "
                        "faults").inc(kind="compact", family=family)
            return False

    def demote(self, family: str, pick: int) -> bool:
        """Demote one warm doc of one shard to the cold tier (durable
        rung + WAL tail).  Typed ResidencyError (e.g. an armed
        evict_flush) leaves the doc hot — counted, not a violation."""
        from ..errors import ResidencyError

        p = self.planes[family]
        p.sync.flush()
        shards = p.resident.shards
        for off in range(len(shards)):
            srv = shards[(pick + off) % len(shards)]
            res = getattr(srv, "residency", None)
            if res is None:
                continue
            warm = res.tiers().get("warm", [])
            if not warm:
                continue
            try:
                srv.batch.demote(warm[pick % len(warm)])
                obs.counter("chaos.demotions_total",
                            "explicit warm->cold demotions").inc(
                    family=family)
                return True
            except (ResidencyError, faultinject.InjectedFault):
                obs.counter(
                    "chaos.demote_failures_total",
                    "typed demote failures (armed evict faults)",
                ).inc(family=family)
                return False
        return False

    def migrate(self, family: str, di: int) -> bool:
        p = self.planes[family]
        if p.resident.n_shards < 2:
            return False
        di = di % self.cfg.docs
        cur, _ = p.resident.placement.place(di)
        target = (cur + 1) % p.resident.n_shards
        try:
            p.resident.migrate(di, target)
            obs.counter("chaos.migrations_total",
                        "live doc migrations executed").inc(family=family)
            return True
        except (ShardingError, LoroError):
            # typed lifecycle refusal (no spare slot, degraded shard):
            # a legitimate outcome under chaos, never a violation
            obs.counter("chaos.migrate_refused_total",
                        "typed migrate refusals").inc(family=family)
            return False

    def arm_fault(self, params: dict) -> None:
        kw = {k: v for k, v in params.items() if k in (
            "action", "delay_s", "keep_bytes", "flip_at", "times")}
        if params.get("msg"):
            kw["exc"] = faultinject.InjectedFault(params["msg"])
        faultinject.inject(params["site"], **kw)
        obs.counter("chaos.faults_armed_total",
                    "fault arms scheduled by chaos plans").inc(
            site=params["site"])

    # -- quiesce (the barrier's settle phase) ---------------------------
    def catch_up(self, p: FamilyPlane, passes: int = 10) -> int:
        """Drive the follower's lag to 0 (armed repl faults make single
        passes fail/fall short; the loop retries with them exhausted).
        Returns the final lag."""
        if p.follower is None:
            return 0
        lag = -1
        for _ in range(passes):
            p.resident.flush_durable()
            try:
                p.follower.catch_up()
            except (ReplicationError, faultinject.InjectedFault, OSError):
                continue
            lag = p.follower.lag_epochs
            if lag == 0:
                return 0
        return lag

    def settle(self) -> None:
        """Quiesce before invariant checks: drain every plane, clear
        leftover armed faults (counted), heal degraded shards, bring
        followers to lag 0.  Mutates only toward the steady state the
        degradation contracts promise."""
        # sample BEFORE quiescing: an armed health_tick fault must hit
        # a real tick (the skip path), not be cleared unfired below
        self.health.tick()
        self._quiesce_faults()
        for p in self.planes.values():
            p.sync.flush()
            if p.resident.degraded:
                ok = p.resident.recover()
                obs.counter("chaos.shard_recoveries_total",
                            "degraded-shard recoveries at barriers").inc(
                    family=p.family)
                if not ok:
                    self.raw_errors.append(
                        f"{p.family}: degraded shard did not recover")
            p.resident.flush_durable()
            if p.follower is not None:
                for f in p.follower.shards:
                    if f.resident.degraded:
                        f.resident.recover()
        # unstall everyone: stalled clients catch up right after checks
        for c in self.clients:
            c.stalled = False

    # -- lifecycle ------------------------------------------------------
    def hold_marker(self) -> str:
        return os.path.join(self.root, "CHAOS_READY")

    def close(self) -> None:
        for c in self.clients:
            c.close()
        self.clients = []
        err: Optional[BaseException] = None
        for p in self.planes.values():
            try:
                self._teardown_plane(p)
            except LoroError as e:
                err = e
        if err is not None:
            raise ChaosError(f"stack teardown failed: {err}") from err
