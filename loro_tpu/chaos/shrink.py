"""Delta-debugging shrinker: minimize a violating chaos schedule.

Classic ddmin over the artifact's step trace: try dropping chunks (at
coarse granularity first, halving down to single steps) and keep any
subset that still reproduces the original violation keys.  Two
normalizations make subsets well-formed:

- the trace is pre-truncated to the violating barrier (everything
  after it cannot have contributed), and
- every probed subset gets a trailing ``check`` barrier appended if
  ddmin dropped it — a schedule nobody judges can never "violate", so
  the detector must always run.

Steps are index-stable (``Step.i`` is preserved), so a shrunk artifact
is still resumable/attributable against the original plan.  Probe
results are memoized by subset identity — ddmin revisits subsets.

``python -m loro_tpu.chaos.shrink <artifact.json> [out.json]`` writes
the minimized artifact (default: ``<artifact>.min.json``) and prints
the reduction (e.g. ``34 -> 3 steps in 12 probes``).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs
from .plan import ChaosConfig, Step, steps_from_json
from .runner import ChaosRunner, load_artifact


def _ensure_barrier(steps: List[Step]) -> List[Step]:
    if steps and steps[-1].kind == "check":
        return steps
    nxt = (steps[-1].i + 1) if steps else 0
    return steps + [Step(i=nxt, kind="check")]


class _Probe:
    """One shrink predicate evaluation: run the subset in a scratch
    dir, true iff the original violation keys all reproduce."""

    def __init__(self, cfg: ChaosConfig, expected: List[Tuple[str, str]],
                 work_dir: str):
        self.cfg = cfg
        self.expected = set(expected)
        self.work_dir = work_dir
        self.cache: Dict[tuple, bool] = {}
        self.runs = 0

    def __call__(self, steps: List[Step]) -> bool:
        key = tuple(s.i for s in steps)
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        self.runs += 1
        obs.counter("chaos.shrink_probes_total",
                    "shrink predicate runs executed").inc()
        root = os.path.join(self.work_dir, f"probe-{self.runs:03d}")
        report = ChaosRunner(self.cfg, root).run(_ensure_barrier(steps))
        got = {v.key() for v in report.violations}
        ok = self.expected <= got
        self.cache[key] = ok
        shutil.rmtree(root, ignore_errors=True)
        return ok


def ddmin(steps: List[Step], probe) -> List[Step]:
    """Zeller's ddmin, complement-first: find a 1-minimal violating
    subset (every single-step removal breaks reproduction)."""
    cur = list(steps)
    n = 2
    while len(cur) >= 2:
        chunk = max(1, len(cur) // n)
        reduced = False
        i = 0
        while i < len(cur):
            rest = cur[:i] + cur[i + chunk:]
            if rest and probe(rest):
                cur = rest
                n = max(n - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if n >= len(cur):
                break
            n = min(len(cur), n * 2)
    return cur


def shrink_artifact(path: str, out_path: Optional[str] = None,
                    work_dir: Optional[str] = None) -> dict:
    """Minimize the artifact at ``path``; writes and returns the
    shrunk artifact (original violations' keys preserved, trace
    reduced).  Adds a ``shrink`` stanza with the reduction stats."""
    art = load_artifact(path)
    cfg = ChaosConfig.from_json(art["config"])
    steps = steps_from_json(art["trace"])
    expected = sorted({(v["invariant"], v["family"])
                       for v in art.get("violations", [])})
    if not expected:
        from ..errors import ChaosError

        raise ChaosError(
            f"{path}: artifact has no violations — nothing to shrink")
    # truncate to the violating barrier: later steps never ran
    vstep = max((v.get("step", -1) for v in art["violations"]),
                default=-1)
    if vstep >= 0:
        steps = [s for s in steps if s.i <= vstep]
    own_tmp = work_dir is None
    if own_tmp:
        work_dir = tempfile.mkdtemp(prefix="chaos_shrink_")
    try:
        probe = _Probe(cfg, expected, work_dir)
        if not probe(steps):
            from ..errors import ChaosError

            raise ChaosError(
                f"{path}: original schedule does not reproduce its own "
                "violations — cannot shrink a flaky artifact")
        small = ddmin(steps, probe)
    finally:
        if own_tmp:
            shutil.rmtree(work_dir, ignore_errors=True)
    out = dict(art)
    out["trace"] = [s.to_json() for s in _ensure_barrier(small)]
    out["shrink"] = {
        "original_steps": len(art["trace"]),
        "shrunk_steps": len(out["trace"]),
        "probes": probe.runs,
    }
    out_path = out_path or (path[:-5] if path.endswith(".json")
                            else path) + ".min.json"
    with open(out_path + ".tmp", "w") as f:
        json.dump(out, f, indent=1)
    os.replace(out_path + ".tmp", out_path)
    out["path"] = out_path
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    out = shrink_artifact(argv[0], argv[1] if len(argv) > 1 else None)
    st = out["shrink"]
    print(f"shrunk {st['original_steps']} -> {st['shrunk_steps']} steps "
          f"in {st['probes']} probes -> {out['path']}")
    return 0


if __name__ == "__main__":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
