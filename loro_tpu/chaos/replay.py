"""Deterministic re-execution of a chaos artifact.

``python -m loro_tpu.chaos.replay <artifact.json> [work_dir]``

Reloads the artifact's config + step trace and runs it against a fresh
durable root.  The plan is taken from the artifact VERBATIM (never
regenerated from the seed), so shrunk artifacts — whose step subset no
PRNG would produce — replay exactly the same way full ones do.

Exit status matches ``chaos.run``: rc 1 when the replay reproduces a
violation (the expected outcome for a violation artifact — the
one-screen report says whether the SAME invariants broke), rc 0 on a
clean replay.
"""
from __future__ import annotations

import sys
import tempfile
from typing import List, Optional, Tuple

from .plan import ChaosConfig, Step, steps_from_json
from .runner import ChaosReport, ChaosRunner, load_artifact


def replay_artifact(path: str, work_dir: Optional[str] = None,
                    ) -> Tuple[ChaosReport, List[Tuple[str, str]]]:
    """Re-execute the artifact; returns ``(report, expected_keys)``
    where ``expected_keys`` are the original violations' stable keys
    (``(invariant, family)``) — compare with the report's to decide
    whether the replay reproduced the original failure."""
    art = load_artifact(path)
    cfg = ChaosConfig.from_json(art["config"])
    plan = steps_from_json(art["trace"])
    expected = sorted({(v["invariant"], v["family"])
                       for v in art.get("violations", [])})
    if work_dir is None:
        work_dir = tempfile.mkdtemp(prefix="chaos_replay_")
    report = ChaosRunner(cfg, work_dir).run(plan)
    return report, expected


def reproduces(report: ChaosReport, expected: List[Tuple[str, str]]) -> bool:
    got = {v.key() for v in report.violations}
    return bool(expected) and set(expected) <= got


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    report, expected = replay_artifact(argv[0],
                                       argv[1] if len(argv) > 1 else None)
    got = sorted({v.key() for v in report.violations})
    print(f"replay: {report.steps_run} steps, {report.checks} barriers, "
          f"{len(report.violations)} violation(s)")
    for v in report.violations[:8]:
        print(f"  [{v.invariant}/{v.family}] step {v.step}: {v.detail[:110]}")
    if expected:
        print("reproduced original violation: "
              + ("YES" if reproduces(report, expected) else
                 f"NO (wanted {expected}, got {got})"))
    return 1 if report.violations else 0


if __name__ == "__main__":
    import os

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
