"""loro_tpu.chaos: deterministic fault-schedule orchestration with a
fleet-wide invariant checker and replayable shrinking
(docs/RESILIENCE.md "Chaos plane").

The stack's ~20 typed fault sites (``resilience.faultinject.sites()``)
are exercised one-at-a-time by targeted tests; this package drives
them *composed* — against the fully-stacked serving regime (sharded +
tiered + durable group-commit + PipelinedIngest + SyncServer sessions
+ a live WAL-shipping follower) interleaved with nemesis actions
(crash/recover, failover promotion, live migration, tier churn,
checkpoint/compact, session churn).  Five pieces:

- ``plan``       — seeded ``ChaosConfig``/``generate_plan``: the whole
  schedule is a pure function of its seed (one PRNG, byte-identical
  step traces across runs)
- ``stack``      — ``ChaosStack``: the composed stack, its writer
  clients, and the runner-owned reference oracle
- ``invariants`` — ``InvariantChecker``: convergence, pull
  byte-identity, no-lost-acked-writes, follower lag-0 identity,
  ``persist.inspect`` rc==0, lock-witness acyclicity, obs sanity
- ``runner``     — ``ChaosRunner``: execute, journal, barrier, dump a
  replayable violation artifact; ``hold_at``/``resume_from`` are the
  SIGKILL orchestration hooks (tests/soak_chaos.py)
- ``replay`` / ``shrink`` — ``python -m loro_tpu.chaos.replay
  <artifact>`` re-executes deterministically; ``...chaos.shrink``
  ddmin-minimizes the schedule to the smallest violating subset

CLI: ``python -m loro_tpu.chaos.run --seed N --steps K`` (rc != 0 on
violation, artifact path on stderr).  Soak:
``tests/soak_chaos.py`` (SOAK_CHAOS_SEEDS/STEPS/DOCS), which
orchestrates real subprocess SIGKILLs around the runner's hold points.
Metrics: ``chaos.*`` (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

from .invariants import InvariantChecker, Violation
from .plan import ChaosConfig, Step, generate_plan, trace_json
from .runner import ChaosReport, ChaosRunner, load_artifact
from .replay import replay_artifact
from .shrink import ddmin, shrink_artifact
from .stack import ChaosStack

__all__ = [
    "ChaosConfig",
    "ChaosReport",
    "ChaosRunner",
    "ChaosStack",
    "InvariantChecker",
    "Step",
    "Violation",
    "ddmin",
    "generate_plan",
    "load_artifact",
    "replay_artifact",
    "shrink_artifact",
    "trace_json",
]
