"""Deterministic chaos plans: seeded fault/nemesis schedules.

A **ChaosPlan** is a flat list of steps drawn from ONE ``random.Random``
seeded with the config's seed — the plan (and therefore the whole run,
executed by the single-threaded ``chaos.runner``) is a pure function of
``(config, seed)``.  Step kinds:

- ``edit``      — one client edits every container family in its doc
                  and pushes the delta to every family server (the
                  soak_sync write pattern); carries its own derived
                  ``seed`` so the edit bytes are reproducible from the
                  step record alone
- ``pull``      — one client pulls (byte-identity-gated vs the serving
                  oracle's own export)
- ``fault``     — arm one entry of the SAFE arm matrix below through
                  the programmatic ``resilience.faultinject`` API
- ``join`` / ``leave`` / ``stall`` — session churn (a stalled client
                  skips pulls until the barrier after next clears it)
- ``checkpoint`` / ``compact`` — durability/retention housekeeping on
                  one family
- ``demote``    — push a warm doc to the cold tier (tiered servers)
- ``migrate``   — live-migrate one doc to the next shard
- ``net``       — socket-edge nemesis: front one family's SyncServer
                  with a ``net.NetServer``, pull over a REAL TCP
                  socket byte-identity-gated against the oracle's own
                  export, inject a seeded connection fault (writer
                  stall / frame bitflip / accept refusal), kill the
                  connection abruptly and reconnect-with-frontier —
                  the resumed pull is gated the same way.  Read-only
                  by construction: pushes stay on the in-process
                  sessions, so the reference oracle's acked-payload
                  bookkeeping is untouched
- ``reopen``    — graceful close + ``recover_sharded_server`` +
                  re-front + follower resume + client reset (the
                  in-process recovery nemesis)
- ``promote``   — failover: retire the leader, promote its follower,
                  reconnect everything (at most one per plan, late)
- ``kill``      — SIGKILL point: an orchestrating parent (soak_chaos /
                  ``chaos.run --hold-at``) kills the child here and
                  resumes from the durable dirs; executed in-process it
                  downgrades to ``reopen`` on every family (counted)
- ``check``     — invariant barrier (``chaos.invariants``)
- ``plant``     — test-only synthetic violation: corrupts the
                  REFERENCE oracle so the next barrier must catch it
                  (generated only when ``plant_at`` is set — the hook
                  the determinism/replay/shrink acceptance tests use)

**Safe arm matrix.**  Only fault arms whose documented degradation
contract preserves end-to-end convergence under a live SyncServer are
generated; the rest of the registry stays covered by targeted tests.
Excluded, with reasons: ``poison_doc`` (mangles bytes BELOW the sync
fan-in — the serving oracle has already accepted the push, so resident
reads diverge by design), ``decode`` under payload routing is included
(the native wrapper falls back to the Python decoder with the ORIGINAL
bytes), ``wal_write:raise`` (documented fail-stop — the server is DOWN
afterwards, which is a crash test, not a composition test),
``wal_torn_tail``/``ckpt_corrupt`` (byzantine-disk mangling: the
durable bytes no longer match what the server acked, which the
convergence oracle cannot model — targeted recovery tests own them),
``backend_init`` (probe-subprocess only), ``evict_flush`` (armed only
PAIRED directly before a ``demote`` step: fired mid-sync-ingest it
would fail the fan-in worker, a known contract documented in
docs/RESILIENCE.md), ``revive_replay`` (same pairing problem without a
pairable runner-side trigger — a revive fires inside the fan-in commit
path, where a typed per-round failure still closes the intake).
"""
from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ..errors import ChaosError, ConfigError

ALL_FAMILIES = ("text", "map", "tree", "counter", "movable")

#: fault arms the generator may compose mid-run (site, kwargs).  Every
#: entry is convergence-safe: it either retries clean, degrades to a
#: byte-identical host path, or fails typed to the runner which retries
#: the client operation with the fault exhausted.
SAFE_ARMS: Tuple[dict, ...] = (
    {"site": "launch", "action": "raise", "times": 1},            # transient
    {"site": "launch", "action": "raise", "times": 1,
     "msg": "injected fatal launch"},                             # degrade
    {"site": "fetch", "action": "delay", "delay_s": 0.005},
    {"site": "decode", "action": "truncate", "times": 1},
    {"site": "decode", "action": "bitflip", "times": 1},
    {"site": "wal_write", "action": "delay", "delay_s": 0.005},
    {"site": "sync_push", "action": "raise", "times": 1},
    {"site": "sync_push", "action": "bitflip", "times": 1},
    {"site": "sync_pull", "action": "raise", "times": 1},
    {"site": "sync_pull", "action": "delay", "delay_s": 0.005},
    {"site": "session_stall", "action": "delay", "delay_s": 0.005},
    {"site": "read_batch", "action": "raise", "times": 1},
    {"site": "export_launch", "action": "raise", "times": 1},
    {"site": "export_launch", "action": "raise", "times": 1,
     "msg": "injected fatal export"},
    {"site": "health_tick", "action": "raise", "times": 1},
)

#: arms that only make sense when a follower is riding along
REPL_ARMS: Tuple[dict, ...] = (
    {"site": "repl_ship", "action": "raise", "times": 1},
    {"site": "repl_ship", "action": "delay", "delay_s": 0.005},
    {"site": "repl_ship", "action": "truncate", "times": 1},
    {"site": "repl_apply", "action": "raise", "times": 1},
)


@dataclass(frozen=True)
class Step:
    """One schedulable action.  ``params`` must stay JSON-able — the
    step trace IS the replay/shrink artifact."""

    i: int
    kind: str
    params: Dict[str, object] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"i": self.i, "kind": self.kind, "params": dict(self.params)}

    @classmethod
    def from_json(cls, d: dict) -> "Step":
        try:
            return cls(i=int(d["i"]), kind=str(d["kind"]),
                       params=dict(d.get("params", {})))
        except (KeyError, TypeError, ValueError) as e:
            raise ChaosError(f"malformed step record {d!r}: {e}") from e


@dataclass
class ChaosConfig:
    """Plan/run parameters.  ``seed`` + this config fully determine the
    plan; the runner adds no randomness of its own."""

    seed: int = 0
    steps: int = 40
    families: Tuple[str, ...] = ALL_FAMILIES
    docs: int = 4
    shards: int = 2
    hot_slots: Optional[int] = 2
    sessions: int = 3
    fsync_window: int = 4
    barrier_every: int = 10
    coalesce: int = 4
    follower: bool = True
    allow_kill: bool = False
    plant_at: Optional[int] = None   # test-only synthetic violation

    def __post_init__(self):
        self.families = tuple(self.families)
        bad = [f for f in self.families if f not in ALL_FAMILIES]
        if bad or not self.families:
            raise ConfigError(
                "chaos families", ",".join(bad) or "(empty)",
                "non-empty subset of " + ",".join(ALL_FAMILIES),
            )
        for knob, v, lo in (("steps", self.steps, 1),
                            ("docs", self.docs, 1),
                            ("shards", self.shards, 1),
                            ("sessions", self.sessions, 1),
                            ("barrier_every", self.barrier_every, 1)):
            if int(v) < lo:
                raise ConfigError(f"chaos {knob}", v, f"integer >= {lo}")

    def to_json(self) -> dict:
        d = asdict(self)
        d["families"] = list(self.families)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ChaosConfig":
        try:
            d = dict(d)
            d["families"] = tuple(d.get("families", ALL_FAMILIES))
            return cls(**d)
        except TypeError as e:
            raise ChaosError(f"malformed chaos config: {e}") from e


def _weighted(rng: random.Random, table: List[Tuple[str, float]]) -> str:
    total = sum(w for _, w in table)
    x = rng.random() * total
    for kind, w in table:
        x -= w
        if x < 0:
            return kind
    return table[-1][0]


def generate_plan(cfg: ChaosConfig) -> List[Step]:
    """The seeded schedule: every draw comes from one PRNG, so two
    calls with equal configs produce byte-identical step traces."""
    rng = random.Random(cfg.seed)
    arms = list(SAFE_ARMS) + (list(REPL_ARMS) if cfg.follower else [])
    table: List[Tuple[str, float]] = [
        ("edit", 8.0), ("pull", 3.0), ("fault", 3.0), ("join", 0.7),
        ("leave", 0.7), ("stall", 1.0), ("checkpoint", 1.0),
        ("compact", 0.7), ("net", 0.6),
    ]
    if cfg.hot_slots is not None:
        table.append(("demote", 1.5))
    if cfg.shards > 1:
        table.append(("migrate", 1.0))
    table.append(("reopen", 0.4))
    # at most one promote, drawn up front so its position is stable
    promote_at = None
    if cfg.follower and cfg.steps >= 8 and rng.random() < 0.5:
        promote_at = rng.randrange(3 * cfg.steps // 4, cfg.steps)
    kill_ats: set = set()
    if cfg.allow_kill:
        for _ in range(max(1, cfg.steps // 25)):
            kill_ats.add(rng.randrange(cfg.steps // 4, cfg.steps))

    raw: List[Step] = []

    def emit(kind: str, **params) -> None:
        raw.append(Step(i=len(raw), kind=kind, params=params))

    for n in range(cfg.steps):
        if cfg.plant_at is not None and n == cfg.plant_at:
            emit("plant", seed=rng.randrange(1 << 30))
        if n == promote_at:
            if rng.random() < 0.4:
                emit("fault", site="repl_promote", action="raise", times=1)
            emit("promote", family=rng.choice(cfg.families))
        elif n in kill_ats:
            emit("kill")
        else:
            kind = _weighted(rng, table)
            if kind == "edit":
                emit("edit", client=rng.randrange(1 << 30),
                     seed=rng.randrange(1 << 30), ops=rng.randint(2, 5))
            elif kind == "pull":
                emit("pull", client=rng.randrange(1 << 30))
            elif kind == "fault":
                emit("fault", **rng.choice(arms))
            elif kind == "join":
                emit("join", doc=rng.randrange(cfg.docs))
            elif kind == "leave":
                emit("leave", client=rng.randrange(1 << 30))
            elif kind == "stall":
                emit("stall", client=rng.randrange(1 << 30))
            elif kind == "checkpoint":
                emit("checkpoint", family=rng.choice(cfg.families))
            elif kind == "compact":
                emit("compact", family=rng.choice(cfg.families))
            elif kind == "net":
                emit("net", family=rng.choice(cfg.families),
                     seed=rng.randrange(1 << 30))
            elif kind == "demote":
                emit("demote", family=rng.choice(cfg.families),
                     pick=rng.randrange(1 << 30))
            elif kind == "migrate":
                emit("migrate", family=rng.choice(cfg.families),
                     doc=rng.randrange(cfg.docs))
            elif kind == "reopen":
                emit("reopen", family=rng.choice(cfg.families))
        if (n + 1) % cfg.barrier_every == 0:
            # a fault armed since the last barrier may sit unfired; the
            # barrier's settle phase clears it (counted) so checks run
            # against a quiesced stack
            emit("check")
    if not raw or raw[-1].kind != "check":
        emit("check")
    return raw


def trace_json(steps: List[Step]) -> str:
    """Canonical serialized step trace (the determinism gate compares
    these byte-for-byte)."""
    return json.dumps([s.to_json() for s in steps],
                      sort_keys=True, separators=(",", ":"))


def steps_from_json(rows: List[dict]) -> List[Step]:
    return [Step.from_json(r) for r in rows]
