"""Chaos CLI: seeded fault/nemesis schedules against the composed stack.

    python -m loro_tpu.chaos.run --seed N --steps K [options]

One-screen verdict on stdout; rc != 0 on any invariant violation, with
the replayable artifact's path on stderr.  Options:

  --seed N           plan seed (default 0)
  --steps K          schedule length before barriers (default 40)
  --families a,b     family subset (default all five)
  --docs/--shards/--sessions/--hot-slots/--fsync-window/--barrier-every
                     stack shape knobs (plan.ChaosConfig defaults)
  --no-follower      drop the replication follower (and repl_* arms)
  --no-tiering       hot_slots=None (all-hot residency)
  --allow-kill       generate SIGKILL steps (in-process they downgrade
                     to reopen; tests/soak_chaos.py orchestrates real
                     kills around --hold-at)
  --plant-at I       test-only synthetic violation at step I (the
                     replay/shrink demo hook)
  --dir D            durable root (default: a fresh temp dir)
  --resume-from I    continue a crashed run from step I (needs the
                     journal in --dir)
  --hold-at I        execute steps < I, write CHAOS_READY, sleep for
                     the orchestrating parent's SIGKILL
  --artifact PATH    violation artifact path override
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

from .plan import ALL_FAMILIES, ChaosConfig
from .runner import ChaosRunner


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m loro_tpu.chaos.run",
        description="deterministic chaos schedule against the composed "
        "sharded+tiered+durable+sync+follower stack",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--families", default=",".join(ALL_FAMILIES))
    p.add_argument("--docs", type=int, default=4)
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--sessions", type=int, default=3)
    p.add_argument("--hot-slots", type=int, default=2)
    p.add_argument("--fsync-window", type=int, default=4)
    p.add_argument("--barrier-every", type=int, default=10)
    p.add_argument("--no-follower", action="store_true")
    p.add_argument("--no-tiering", action="store_true")
    p.add_argument("--allow-kill", action="store_true")
    p.add_argument("--plant-at", type=int, default=None)
    p.add_argument("--dir", default=None)
    p.add_argument("--resume-from", type=int, default=0)
    p.add_argument("--hold-at", type=int, default=None)
    p.add_argument("--artifact", default=None)
    return p


def config_from_args(args) -> ChaosConfig:
    return ChaosConfig(
        seed=args.seed, steps=args.steps,
        families=tuple(f for f in args.families.split(",") if f),
        docs=args.docs, shards=args.shards, sessions=args.sessions,
        hot_slots=None if args.no_tiering else args.hot_slots,
        fsync_window=args.fsync_window,
        barrier_every=args.barrier_every,
        follower=not args.no_follower, allow_kill=args.allow_kill,
        plant_at=args.plant_at,
    )


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    cfg = config_from_args(args)
    root = args.dir or tempfile.mkdtemp(prefix="chaos_run_")
    runner = ChaosRunner(cfg, root, artifact_path=args.artifact)
    report = runner.run(resume_from=args.resume_from, hold_at=args.hold_at)
    fams = ",".join(cfg.families)
    print(f"chaos seed={cfg.seed} steps={report.steps_run} "
          f"barriers={report.checks} families={fams} "
          f"shards={cfg.shards} hot_slots={cfg.hot_slots} "
          f"follower={cfg.follower}")
    if report.fired:
        fired = " ".join(f"{k}:{v}" for k, v in sorted(report.fired.items()))
        print(f"faults fired: {fired}")
    if report.clean:
        print("verdict: CLEAN — zero invariant violations")
        return 0
    print(f"verdict: {len(report.violations)} VIOLATION(S)")
    for v in report.violations[:10]:
        print(f"  [{v.invariant}/{v.family}] step {v.step}: {v.detail[:110]}")
    print(runner.artifact_path, file=sys.stderr)
    return 1


if __name__ == "__main__":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
