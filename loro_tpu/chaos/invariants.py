"""Fleet-wide invariant checker: what must hold at every barrier.

Run against a SETTLED stack (``ChaosStack.settle()`` first — faults
cleared, fan-ins drained, degraded shards healed, followers caught
up).  Violations are DATA, not exceptions: one barrier reports every
broken invariant so the artifact shows the full blast radius, and the
shrinker can key on a stable ``Violation.key()``.

The invariants (the ISSUE 13 list):

- ``convergence``   — every family server's reads match the runner's
  reference oracle (host LoroDocs that imported every acked push —
  regenerated from the journal across a crash), the "Version
  Reconciliation" convergence contract end-to-end
- ``client_convergence`` — every live, non-stalled client doc equals
  the reference oracle after its pulls
- ``pull_identity`` — ``Session.pull()`` bytes equal the serving
  oracle's own ``ExportMode.Updates`` export (collected by the pull
  path in ``stack.pull_client``)
- ``durability``    — no lost acked writes: every resolved PushTicket
  epoch <= the family's durable watermark once flushed (the crash-side
  half — recovered_epoch >= acked — is checked by the kill/recover
  orchestration in tests/soak_chaos.py)
- ``follower``      — catch-up returned lag to 0 and the follower's
  merged reads are byte-identical to the reference oracle
- ``inspect``       — ``persist.inspect`` rc==0 on every surviving
  durable directory (leader and follower copies)
- ``lock_witness``  — the witnessed lock graph stays acyclic and
  conformant to the declared order (when the witness is enabled)
- ``obs_sanity``    — no raw (untyped) error ever reached a session,
  every client operation eventually landed, and the serving oracle
  never failed an apply (``sync.oracle_apply_errors_total``)
- ``attribution``   — every resolved push ticket's per-stage timing
  breakdown telescopes to its end-to-end total (stages sum == total
  within float tolerance, no stage negative beyond jitter) — the
  request-tracing plane's own sanity gate (ISSUE 14,
  docs/OBSERVABILITY.md "Request tracing")
- ``health``        — the stack's health plane stays sane at every
  barrier: ``status()`` is JSON-serializable, the verdict is a known
  severity at least as severe as every open alert, skipped sampler
  ticks never exceed taken ones, and after ``settle()`` healed the
  fleet the status must not still claim degraded shards
  (docs/OBSERVABILITY.md "Health & heat")
"""
from __future__ import annotations

import io
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..obs import metrics as obs
from .stack import ChaosStack


@dataclass(frozen=True)
class Violation:
    invariant: str
    family: str
    detail: str
    step: int = -1

    def key(self) -> Tuple[str, str]:
        """Stable identity for replay comparison and shrink
        predicates: the step index and free-form detail vary across
        schedule subsets, the broken invariant does not."""
        return (self.invariant, self.family)

    def to_json(self) -> dict:
        return {"invariant": self.invariant, "family": self.family,
                "detail": self.detail, "step": self.step}


def _oracle_views(doc) -> dict:
    t = doc.get_text("t")
    tr = doc.get_tree("tr")
    c = doc.get_counter("c")
    return {
        "text": t.to_string(),
        "richtext": t.get_richtext_value(),
        "map": doc.get_map("m").get_value(),
        "tree": {x: tr.parent(x) for x in tr.nodes()},
        "counter": float(c.get_value()),
        "counter_id": c.id,
        "movable": doc.get_movable_list("ml").get_value(),
    }


class InvariantChecker:
    """Stateless apart from the stack handle; ``check()`` returns the
    violations found at one barrier (and ticks ``chaos.*`` metrics)."""

    def __init__(self, stack: ChaosStack, oracle_docs: List):
        self.stack = stack
        self.oracle = oracle_docs

    # -- individual invariants -----------------------------------------
    def _convergence(self, step: int) -> List[Violation]:
        out: List[Violation] = []
        views = [_oracle_views(d) for d in self.oracle]
        for fam, p in self.stack.planes.items():
            reads = self._family_reads(p)
            for i, v in enumerate(views):
                got = reads[i]
                if not self._matches(fam, got, v):
                    out.append(Violation(
                        "convergence", fam,
                        f"doc {i}: server read diverged from the "
                        f"reference oracle (got {got!r:.120}, want "
                        f"{self._want(fam, v)!r:.120})", step))
        return out

    def _family_reads(self, p) -> list:
        fam = p.family
        if fam == "text":
            texts, riches = p.sync.texts(), p.sync.richtexts()
            return list(zip(texts, riches))
        if fam == "map":
            return p.sync.root_value_maps("m")
        if fam == "tree":
            return p.sync.parent_maps()
        if fam == "counter":
            return p.sync.value_maps()
        return p.sync.value_lists()

    @staticmethod
    def _want(fam: str, v: dict):
        if fam == "text":
            return (v["text"], v["richtext"])
        if fam == "map":
            return v["map"]
        if fam == "tree":
            return v["tree"]
        if fam == "counter":
            return {v["counter_id"]: v["counter"]}
        return v["movable"]

    @classmethod
    def _matches(cls, fam: str, got, v: dict) -> bool:
        if fam == "counter":
            # soak idiom: compare through .get — a counter the doc
            # never touched reads as an absent key, not 0.0
            return got.get(v["counter_id"], 0.0) == v["counter"]
        return got == cls._want(fam, v)

    def _clients(self, step: int) -> List[Violation]:
        out: List[Violation] = []
        for c in list(self.stack.clients):
            if c.stalled:
                continue
            for d in self.stack.pull_client(c):
                out.append(Violation("pull_identity",
                                     d.split()[1].split("/")[0], d, step))
            if c.doc.get_deep_value() != self.oracle[c.di].get_deep_value():
                out.append(Violation(
                    "client_convergence", "*",
                    f"client {c.n} (doc {c.di}) diverged from the "
                    "reference oracle after pulls", step))
        return out

    def _durability(self, step: int) -> List[Violation]:
        out: List[Violation] = []
        for fam, p in self.stack.planes.items():
            p.resident.flush_durable()
            mark = p.resident.durable_epoch
            if mark < p.max_acked:
                out.append(Violation(
                    "durability", fam,
                    f"durable watermark {mark} < max acked push epoch "
                    f"{p.max_acked} after flush — an acked write would "
                    "not survive a crash", step))
        return out

    def _follower(self, step: int) -> List[Violation]:
        out: List[Violation] = []
        views = [_oracle_views(d) for d in self.oracle]
        for fam, p in self.stack.planes.items():
            if p.follower is None:
                continue
            lag = self.stack.catch_up(p)
            if lag != 0:
                out.append(Violation(
                    "follower", fam,
                    f"catch_up left lag {lag} (applied "
                    f"{p.follower.applied_epoch})", step))
                continue
            reads = self._follower_reads(p)
            for i, v in enumerate(views):
                if not self._matches(fam, reads[i], v):
                    out.append(Violation(
                        "follower", fam,
                        f"doc {i}: follower read diverged at lag 0 "
                        f"(got {reads[i]!r:.120}, want "
                        f"{self._want(fam, v)!r:.120})", step))
        return out

    def _follower_reads(self, p) -> list:
        fam = p.family
        f = p.follower
        if fam == "text":
            return list(zip(f.texts(), f.richtexts()))
        if fam == "map":
            return f.root_value_maps("m")
        if fam == "tree":
            return f.parent_maps()
        if fam == "counter":
            return f.value_maps()
        return f.value_lists()

    def _inspect(self, step: int) -> List[Violation]:
        from ..persist.inspect import inspect_dir

        out: List[Violation] = []
        for fam, p in self.stack.planes.items():
            dirs = [("leader", p.dir)]
            if p.follower is not None:
                dirs.append(("follower", p.follower.follower_dir))
            for role, d in dirs:
                buf = io.StringIO()
                rc = inspect_dir(d, out=buf)
                if rc != 0:
                    tail = buf.getvalue().strip().splitlines()[-3:]
                    out.append(Violation(
                        "inspect", fam,
                        f"{role} dir {d}: persist.inspect rc={rc}: "
                        + " | ".join(tail), step))
        return out

    def _lock_witness(self, step: int) -> List[Violation]:
        from ..analysis.lockwitness import witness
        from ..errors import LockOrderViolation

        w = witness()
        if not getattr(w, "enabled", False):
            return []
        out: List[Violation] = []
        try:
            w.assert_acyclic()
        except LockOrderViolation as e:
            out.append(Violation("lock_witness", "*", str(e), step))
        for v in w.check_declared():
            out.append(Violation("lock_witness", "*", v, step))
        return out

    def _obs_sanity(self, step: int) -> List[Violation]:
        out: List[Violation] = []
        for msg in self.stack.raw_errors:
            out.append(Violation(
                "obs_sanity", "*",
                f"raw (untyped) error reached a session: {msg}", step))
        for msg in self.stack.unresolved:
            out.append(Violation(
                "obs_sanity", "*",
                f"client operation never landed through retries: {msg}",
                step))
        self.stack.raw_errors = []
        self.stack.unresolved = []
        napply = obs.counter("sync.oracle_apply_errors_total").total()
        if napply:
            out.append(Violation(
                "obs_sanity", "*",
                f"serving oracle failed {int(napply)} committed "
                "applies (planes can diverge)", step))
        return out

    def _attribution(self, step: int) -> List[Violation]:
        """Stage sums must telescope to the end-to-end total: a stage
        mark recorded out of order (or a path that double-counts a
        boundary) makes the breakdown lie, and a lying attribution
        plane is worse than none.  Tolerance covers float summation
        only — the marks are constructed telescoping."""
        out: List[Violation] = []
        for bd in self.stack.breakdowns:
            stages = {k: v for k, v in bd.items()
                      if k.endswith("_ms") and k != "total_ms"}
            ssum = sum(stages.values())
            if abs(ssum - bd.get("total_ms", 0.0)) > 0.01:
                out.append(Violation(
                    "attribution", bd.get("family", "*"),
                    f"push {bd.get('trace_id')}: stage sum "
                    f"{ssum:.3f}ms != total {bd.get('total_ms'):.3f}ms "
                    f"(stages {sorted(stages)})", step))
            for k, v in stages.items():
                if v < -0.01:
                    out.append(Violation(
                        "attribution", bd.get("family", "*"),
                        f"push {bd.get('trace_id')}: negative stage "
                        f"{k}={v:.3f}ms (marks out of order)", step))
        self.stack.breakdowns = []
        return out

    def _health(self, step: int) -> List[Violation]:
        """The health plane's own sanity at a settled barrier: the
        status payload must serialize, compose severities correctly,
        keep its tick accounting monotone, and agree with the healed
        fleet.  Detector alerts themselves are NOT violations — faults
        legitimately fire them mid-schedule; a status surface that
        *lies* about them is the failure mode this gates."""
        import json as _json

        from ..obs import health as health_mod

        out: List[Violation] = []
        plane = self.stack.health
        plane.tick()  # the barrier sample (settle took the base one)
        st = plane.status()
        try:
            _json.dumps(st)
        except (TypeError, ValueError) as e:
            out.append(Violation(
                "health", "*",
                f"status payload is not JSON-serializable: {e}", step))
            return out
        if st["verdict"] not in health_mod.SEVERITIES:
            out.append(Violation(
                "health", "*",
                f"unknown verdict {st['verdict']!r}", step))
            return out
        rank = health_mod.SEVERITIES.index
        for a in st["alerts"]:
            if rank(st["verdict"]) < rank(a["severity"]):
                out.append(Violation(
                    "health", "*",
                    f"verdict {st['verdict']} milder than open "
                    f"{a['severity']} alert {a['kind']} — the status "
                    "surface understates a firing detector", step))
        if st["ticks"] < 1:
            out.append(Violation(
                "health", "*",
                "no sampler tick landed by the barrier "
                f"(skipped {st['skipped_ticks']})", step))
        sh = st.get("shards")
        if sh and sh.get("degraded"):
            out.append(Violation(
                "health", "*",
                f"status claims degraded shards {sh['degraded']} AFTER "
                "settle healed the fleet — the surface is stale", step))
        skew = (st.get("heat") or {}).get("skew_ratio")
        if skew is not None and skew < 1.0 - 1e-6:
            out.append(Violation(
                "health", "*",
                f"impossible skew ratio {skew} < 1.0 (max over uniform "
                "share cannot be below 1)", step))
        return out

    # -- the barrier ----------------------------------------------------
    def check(self, step: int = -1) -> List[Violation]:
        """One barrier: settle, then run every invariant.  Returns all
        violations (empty = clean)."""
        self.stack.settle()
        obs.counter("chaos.checks_total", "invariant barriers run").inc()
        out: List[Violation] = []
        out += self._durability(step)
        out += self._convergence(step)
        out += self._clients(step)
        out += self._follower(step)
        out += self._inspect(step)
        out += self._lock_witness(step)
        out += self._obs_sanity(step)
        out += self._attribution(step)
        out += self._health(step)
        for v in out:
            obs.counter("chaos.violations_total",
                        "invariant violations detected at barriers").inc(
                invariant=v.invariant)
        return out
