"""Checkout/diff history cache.

reference: crates/loro-internal/src/history_cache.rs:36-54 — the
reference builds per-container BTree indexes keyed
(container, key, lamport, peer) so its DiffCalculators can find the
ops between two versions in O(changed).

TPU-first re-design: this framework's container states are
structure-holding (elements + tombstones), so a materialized state at
any version is itself the perfect "index" — replaying forward from the
nearest cached state costs O(ops between the versions).  The cache
therefore keeps a small LRU of compressed state snapshots at recently
visited versions; checkout / diff / undo (all of which funnel through
LoroDoc._state_at_vv) replay from the best cached floor instead of the
empty/shallow floor.  Repeated time travel in a region of history is
O(changed), not O(history).
"""
from __future__ import annotations

import zlib
from typing import List, Optional, Tuple

from .core.version import Frontiers, VersionVector


class StateCheckpointCache:
    """LRU of (vv, frontiers, compressed state bytes).

    States are cached by value (encoded + compressed) so cache entries
    can never alias the live mutable DocState.  History is append-only
    and states are version-determined, so entries never invalidate.
    """

    def __init__(self, capacity: int = 12):
        self.capacity = capacity
        # most-recently-used last
        self._entries: List[Tuple[VersionVector, Frontiers, bytes]] = []
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def put(self, vv: VersionVector, frontiers: Frontiers, state) -> None:
        from .codec.snapshot import encode_doc_state

        for i, (evv, _f, _b) in enumerate(self._entries):
            if evv == vv:
                self._entries.append(self._entries.pop(i))
                return
        z = zlib.compress(encode_doc_state(state, state.parents), 1)
        self._entries.append((vv.copy(), frontiers, z))
        if len(self._entries) > self.capacity:
            self._entries.pop(0)

    def best_floor(self, target_vv: VersionVector):
        """Decoded state at the largest cached version <= target_vv, or
        None.  Returns (state, vv, frontiers)."""
        from .codec.snapshot import decode_doc_state
        from .state import DocState

        best_i = -1
        best_ops = -1
        for i, (evv, _f, _b) in enumerate(self._entries):
            if evv <= target_vv:
                ops = evv.total_ops()
                if ops > best_ops:
                    best_ops, best_i = ops, i
        if best_i < 0:
            self.misses += 1
            return None
        self.hits += 1
        vv, f, z = self._entries.pop(best_i)
        self._entries.append((vv, f, z))  # LRU touch
        states, parents = decode_doc_state(zlib.decompress(z))
        st = DocState()
        st.states = states
        st.parents.update(parents)
        st.vv = vv.copy()
        st.frontiers = f
        return st, vv, f

    def clear(self) -> None:
        self._entries.clear()
