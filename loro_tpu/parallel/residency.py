"""Tiered doc residency: an HBM hot set over a host-warm, disk-cold
fleet (docs/RESIDENCY.md).

A plain ResidentServer pins EVERY doc it owns into device batch rows
for its whole life, so capacity is HBM-bound and ingest/rank cost
scales with the resident set, not the *active* set.  Real traces are
heavily skewed (the run-locality the Eg-walker paper exploits,
PAPERS.md), so a small hot set captures almost all traffic — the
delta/main-store split of "Fast Updates on Read-Optimized Databases
Using Multi-Core CPUs" (PAPERS.md) applied to the resident fleet: the
device batch is the in-memory delta the hot traffic folds into, the
mirror-anchor + WAL/checkpoint plane (PR 4) is the merged main store,
and the persistence ladder turns from crash insurance into the serving
memory hierarchy.

Three tiers per doc:

- **hot**  — the doc occupies a slot in a ``hot_slots``-wide device
  batch; ingest and reads ride the ordinary device path.
- **warm** — the doc's rows are released; its state lives host-side as
  a live ``LoroDoc`` mirror (built from the deep mirror anchor + the
  journal tail — the exact replay ``seed_mirror_engine`` uses).  Reads
  are answered from the mirror; the next ingest touch revives it.
- **cold** — durable servers only: the mirror AND the in-memory anchor
  blob are dropped; the doc's state is exactly one checkpoint rung in
  the persist ladder plus the WAL rounds after it (the
  ``recover_server`` replay, scoped to one doc).  First touch revives
  it through that bounded replay.

Mechanism (all five resident families):

- **evict** = build the warm mirror (anchor + journal replay — every
  fallible step happens FIRST, so an injected ``evict_flush`` fault
  leaves the doc hot with no torn tier state), then
  ``release_doc(slot)`` on the device batch and recycle the slot.
  Eviction only ever picks JOURNAL-STABLE docs (their last touching
  round is journaled, hence its device work committed), so a release
  can never race a staged or in-flight coalesced group.
- **revive** = re-export the doc's full history from its mirror (deep
  anchors keep history exportable — the PR 8 migration landing) and
  land it in a free slot through one ordinary batch append; inside a
  coalesced group the landing rides the SAME deferred scatter, ordered
  before the touching round's rows.  A ``revive_replay`` fault fails
  only the triggering round with a typed ``ResidencyError`` — the doc
  stays warm/cold, the server stays healthy.

``TieredBatch`` presents the full doc-space batch surface
(append/coalesce/compact/reads/export_state) to an UNCHANGED
ResidentServer, so the journal, WAL, acks, degradation, pipeline,
SyncServer and sharded planes all compose without knowing about tiers:
``ResidentServer(family, n, hot_slots=K)`` (or the
``TieredResidentServer`` convenience wrapper) is the only opt-in.
Promotion/demotion policy is clock-LRU over per-doc touch counters
with an injected clock (LT-TIME); all tier state sits behind the named
``residency.plan`` lock (analysis/lockorder.py: above ``fleet.dev``,
below ``pipeline.queue``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.lockwitness import named_rlock
from ..errors import LoroError, ResidencyError
from ..obs import heat as heat_acct
from ..obs import metrics as obs
from ..resilience import faultinject
from .server import _FAMILIES, ResidentServer

faultinject.register_site(
    "evict_flush", "TieredBatch eviction: fires after the warm mirror "
    "is built, before any tier state mutates (failure leaves the doc "
    "HOT, typed ResidencyError)")
faultinject.register_site(
    "revive_replay", "TieredBatch revive: fires after the history "
    "export, before the slot landing (fails only the triggering "
    "round/ticket, typed ResidencyError)")

TIER_HOT = "hot"
TIER_WARM = "warm"
TIER_COLD = "cold"

MANIFEST_NAME = "residency.json"
MANIFEST_VERSION = 1

# revive-latency buckets: ms-scale (the default obs buckets are fine,
# but the report percentiles come from the instance list below so the
# bench sidecar reflects THIS server, not the process)
_REVIVE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class ResidencyManager:
    """Tier state + promotion/demotion policy for one TieredBatch.

    Owns the per-doc tier map, the slot free-list, the clock-LRU touch
    bookkeeping (``clock`` is injectable — fake-clock tests control
    eviction order without sleeping), and the counters the
    ``residency.*`` obs family and the bench ``tier`` sidecar report.
    Mechanism (device releases, mirror builds, rung loads) lives in the
    owning ``TieredBatch``; every mutation happens under the shared
    ``residency.plan`` lock.
    """

    def __init__(self, family: str, n_docs: int, hot_slots: int,
                 clock=None):
        self.family = family
        self.n_docs = n_docs
        self.hot_slots = hot_slots
        self.clock = clock if clock is not None else time.monotonic
        self._plan_lock = named_rlock("residency.plan")
        self.slot_of: Dict[int, int] = {}
        self.doc_of: Dict[int, int] = {}
        self.free: deque = deque(range(hot_slots))
        # cold tier: doc -> backing checkpoint rung name ("" = restored
        # cold, rung not yet known — treated warm until note_restored_rung)
        self.cold: Dict[int, str] = {}
        # warm tier mirrors: doc -> (LoroDoc, first-seen cid dict)
        self.mirrors: Dict[int, Tuple[object, Dict]] = {}
        self.last_touch_t: List[float] = [0.0] * n_docs
        self.last_touch_seq: List[int] = [0] * n_docs
        self.touch_count: List[int] = [0] * n_docs
        # optional warm budget: after each checkpoint, warm docs beyond
        # it demote to cold LRU-first (durable servers only)
        self.warm_slots: Optional[int] = None
        # report counters (instance-local; the obs registry is global)
        self.hits = 0
        self.misses = 0
        self.promotions = 0
        self.evictions = 0
        self.demotions = 0
        self.cold_revives = 0
        self.revive_s: List[float] = []
        self._set_gauges()

    # -- tier queries ---------------------------------------------------
    def tier_of(self, di: int) -> str:
        with self._plan_lock:
            if di in self.slot_of:
                return TIER_HOT
            if di in self.cold:
                return TIER_COLD
            return TIER_WARM

    def tiers(self) -> Dict[str, List[int]]:
        """Doc indexes per tier (a snapshot, for inspect/manifest)."""
        with self._plan_lock:
            hot = sorted(self.slot_of)
            cold = sorted(self.cold)
            known = set(hot) | set(cold)
            warm = [d for d in range(self.n_docs) if d not in known]
            return {TIER_HOT: hot, TIER_WARM: warm, TIER_COLD: cold}

    def counts(self) -> Dict[str, int]:
        with self._plan_lock:
            hot = len(self.slot_of)
            cold = len(self.cold)
            return {
                TIER_HOT: hot,
                TIER_COLD: cold,
                TIER_WARM: self.n_docs - hot - cold,
            }

    def hit_rate(self) -> float:
        n = self.hits + self.misses
        return (self.hits / n) if n else 1.0

    def _set_gauges(self) -> None:
        g = obs.gauge("residency.docs", "docs per residency tier")
        c = self.counts()
        for tier, n in c.items():
            g.set(n, family=self.family, tier=tier)

    # -- policy ---------------------------------------------------------
    def pick_victim(self, safe_seq: int) -> Optional[int]:
        """LRU victim among hot docs whose last touching round is
        journaled (``last_touch_seq <= safe_seq``): journaled means the
        round's device work is committed, so releasing the slot cannot
        race a staged or in-flight coalesced group.  None when no hot
        doc is evictable."""
        best, best_t = None, None
        for di in self.slot_of:
            if self.last_touch_seq[di] > safe_seq:
                continue
            t = self.last_touch_t[di]
            if best_t is None or t < best_t:
                best, best_t = di, t
        return best

    # -- reporting ------------------------------------------------------
    def report(self) -> dict:
        """Compact outcome dict (the bench ``tier`` sidecar core)."""
        with self._plan_lock:
            rs = sorted(self.revive_s)
            out = {
                "hot_slots": self.hot_slots,
                "docs": self.n_docs,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hit_rate(), 4),
                "promotions": self.promotions,
                "evictions": self.evictions,
                "demotions": self.demotions,
                "cold_revives": self.cold_revives,
                "revive_ms_p50": round(_pct(rs, 0.50) * 1e3, 3),
                "revive_ms_p99": round(_pct(rs, 0.99) * 1e3, 3),
            }
            out.update(self.counts())
            return out


class TieredBatch:
    """Doc-space virtual batch over a ``hot_slots``-wide device batch.

    Presents the resident-batch surface in DOC space (``n_docs`` wide)
    while the real device arrays are ``hot_slots`` wide: appends
    revive/evict through the ResidencyManager and route per-doc entries
    to slots; reads merge device rows (hot) with host mirrors
    (warm/cold); ``compact``/coalesce/``export_state`` translate.  The
    owning ResidentServer journals doc-space rounds against
    ``self.epoch`` (the inner batch clock — revive landings tick it
    too, so visible epochs may skip; every consumer already tolerates
    that via the epoch-offset machinery).

    ``bind(server)`` attaches the owning server — the anchor + journal
    it maintains ARE the warm/cold source of truth; this class never
    duplicates per-round host work on the hot path (within-10%-of-
    untiered is an acceptance gate)."""

    def __init__(self, family: str, n_docs: int, hot_slots: int, mesh,
                 auto_grow: bool, caps: dict, clock=None):
        if family not in _FAMILIES:
            raise ValueError(
                f"unknown family {family!r} (one of {sorted(_FAMILIES)})"
            )
        hot_slots = int(hot_slots)
        if hot_slots < 1:
            raise ResidencyError(
                f"hot_slots={hot_slots} invalid: need at least one device slot"
            )
        self.family = family
        self.n_docs = n_docs
        self.d = n_docs  # doc-space width (virtual)
        self.hot_slots = hot_slots
        self.inner = _FAMILIES[family][1](hot_slots, mesh, auto_grow, caps)
        self.mgr = ResidencyManager(family, n_docs, hot_slots, clock=clock)
        self._plan_lock = self.mgr._plan_lock
        self._server: Optional[ResidentServer] = None
        # journal-safety clock: every completed client append gets a
        # sequence number; the server's journaling hook pops them FIFO,
        # so ``_safe_seq`` = newest append whose round is journaled
        # (hence device-committed) — the eviction eligibility floor
        self._append_seq = 0
        self._safe_seq = 0
        self._pending_journal: deque = deque()
        self._plan_cv = threading.Condition(self._plan_lock)
        # first append seq of the OPEN coalesce group (pending rounds at
        # or below it belong to prior groups and will journal without
        # us — the slot acquirer may wait on them; pending rounds above
        # it are OURS and journal only after we finish: waiting on them
        # would deadlock, so the acquirer fails typed instead)
        self._group_start_seq = 0
        self._coalesce_open = False
        # single-entry decoded-rung cache for cold loads (name, anchor)
        self._rung_cache: Optional[Tuple[str, object]] = None
        # cold docs restored from a checkpoint, pending the rung name
        # (persist.recover_server calls note_restored_rung)
        self._restored_cold: Dict[int, str] = {}
        if hasattr(self.inner, "append_payloads"):
            # instance attr on purpose: ResidentServer routes payload
            # rounds by hasattr(batch, "append_payloads") — counter has
            # no native payload path and must keep reading False
            self.append_payloads = self._append_payloads_impl

    def bind(self, server: ResidentServer) -> None:
        self._server = server

    @property
    def device_batch(self):
        """The real device batch (drain fetches and debugging reach the
        jax arrays through this)."""
        return self.inner

    @property
    def epoch(self) -> int:
        return self.inner.epoch

    # -- journal-safety hooks (called by the owning server) -------------
    def note_journaled(self) -> None:
        """One client round reached the in-memory journal (and the WAL
        when durable).  Journaled implies its device work committed —
        appends happen strictly before journaling on every path — so
        the popped append seq becomes the eviction-eligibility floor
        (and a slot acquirer waiting for victims wakes up)."""
        with self._plan_cv:
            if self._pending_journal:
                self._safe_seq = max(
                    self._safe_seq, self._pending_journal.popleft()
                )
            self._plan_cv.notify_all()

    # -- appends (doc space) --------------------------------------------
    def append_changes(self, per_doc_updates: Sequence, cid=None) -> None:
        self._append(per_doc_updates, cid, payloads=False)

    def _append_payloads_impl(self, per_doc_updates: Sequence, cid=None) -> None:
        self._append(per_doc_updates, cid, payloads=True)

    def _inner_append(self, slot_updates, cid, payloads: bool) -> None:
        inner = self.inner
        if payloads and not hasattr(inner, "append_payloads"):
            from ..codec.binary import decode_changes

            slot_updates = [
                decode_changes(bytes(u)) if isinstance(u, (bytes, bytearray))
                else u
                for u in slot_updates
            ]
            payloads = False
        if self.family in ("map", "counter"):
            if payloads:
                inner.append_payloads(slot_updates)
            else:
                inner.append_changes(slot_updates)
        else:
            if payloads:
                inner.append_payloads(slot_updates, cid)
            else:
                inner.append_changes(slot_updates, cid)

    def _append(self, per_doc_updates: Sequence, cid, payloads: bool) -> None:
        per_doc_updates = list(per_doc_updates)
        if len(per_doc_updates) > self.n_docs:
            raise ValueError(
                f"round has {len(per_doc_updates)} entries for "
                f"{self.n_docs} docs"
            )
        mgr = self.mgr
        with self._plan_lock:
            if not self._coalesce_open:
                # serial append: it commits (and journals) before the
                # next one, so nothing older can still be in flight
                self._group_start_seq = self._append_seq
            touched = [
                di for di, u in enumerate(per_doc_updates) if u is not None
            ]
            # pending-protect every touched doc BEFORE any promotion:
            # a promotion later in this round must never evict a doc
            # this round also touches (its rows would be staged, not
            # committed)
            next_seq = self._append_seq + 1
            for di in touched:
                mgr.last_touch_seq[di] = next_seq
            for di in touched:
                self._ensure_hot(di, cid)
            slot_updates: List = [None] * self.hot_slots
            for di in touched:
                slot_updates[mgr.slot_of[di]] = per_doc_updates[di]
            self._inner_append(slot_updates, cid, payloads)
            self._append_seq = next_seq
            self._pending_journal.append(next_seq)
            now = mgr.clock()
            for di in touched:
                mgr.last_touch_t[di] = now
                mgr.touch_count[di] += 1
                heat_acct.tick_doc(di, "touch")

    # -- promotion / revive ---------------------------------------------
    def _ensure_hot(self, di: int, cid) -> None:
        mgr = self.mgr
        if di in mgr.slot_of:
            mgr.hits += 1
            obs.counter(
                "residency.touch_total", "ingest touches by tier outcome"
            ).inc(family=self.family, outcome="hit")
            return
        mgr.misses += 1
        obs.counter(
            "residency.touch_total", "ingest touches by tier outcome"
        ).inc(family=self.family, outcome="miss")
        heat_acct.tick_revive()
        was_cold = di in mgr.cold
        t0 = mgr.clock()
        try:
            doc, _seen = self._mirror(di)
            payload = self._export_history(doc)
            faultinject.check("revive_replay", doc=di)
        except LoroError:
            raise
        except Exception as e:
            raise ResidencyError(
                f"doc {di}: revive failed before landing "
                f"({type(e).__name__}: {e}) — the doc stays "
                f"{mgr.tier_of(di)}, only this round is lost"
            ) from e
        slot = self._acquire_slot(di)
        if payload is not None:
            landing: List = [None] * self.hot_slots
            landing[slot] = payload
            try:
                self._inner_append(landing, cid, payloads=True)
            except BaseException:
                # the landing never committed host-atomically (staged-
                # before-validation contract) — hand the slot back and
                # surface; a real device failure degrades the whole
                # round at the server layer as usual
                mgr.free.appendleft(slot)
                raise
        if was_cold:
            # cold exit: the doc's anchor blob must become authoritative
            # again BEFORE the cold entry drops (the eviction/mirror
            # paths rebuild from anchor + journal)
            self._rehydrate_doc_locked(di)
        mgr.slot_of[di] = slot
        mgr.doc_of[slot] = di
        mgr.mirrors.pop(di, None)
        mgr.cold.pop(di, None)
        self._restored_cold.pop(di, None)
        mgr.promotions += 1
        if was_cold:
            mgr.cold_revives += 1
        dt = mgr.clock() - t0
        mgr.revive_s.append(dt)
        obs.histogram(
            "residency.revive_seconds",
            "warm/cold doc revive wall time (mirror + landing)",
            buckets=_REVIVE_BUCKETS,
        ).observe(dt, family=self.family, tier=TIER_COLD if was_cold else TIER_WARM)
        obs.counter("residency.promotions_total").inc(family=self.family)
        mgr._set_gauges()

    def _acquire_slot(self, for_doc: int) -> int:
        """A free slot, evicting the LRU journal-stable hot doc if
        needed.  When every hot doc is pinned by a PRIOR group still in
        flight, wait for its journal notifications (the condition is
        transient — this is the pipeline's natural backpressure when
        the hot budget is tight); when the pinning rounds are our own
        group's, no wait can help — the group genuinely needs more
        co-resident docs than hot_slots — so fail typed."""
        mgr = self.mgr
        stalls = 0
        while True:
            if mgr.free:
                return mgr.free.popleft()
            victim = mgr.pick_victim(self._safe_seq)
            if victim is not None:
                self._evict(victim)
                return mgr.free.popleft()
            prior_pending = bool(
                self._pending_journal
                and self._pending_journal[0] <= self._group_start_seq
            )
            if not prior_pending:
                raise ResidencyError(
                    f"doc {for_doc}: no free device slot and no "
                    f"evictable hot doc — this group needs more "
                    f"co-resident docs than hot_slots={self.hot_slots} "
                    "can hold; raise hot_slots or split the round"
                )
            # a prior group's commit will journal and wake us; the
            # bounded wait guards against a commit that died without
            # ever notifying (the pipeline fails typed around us)
            if not self._plan_cv.wait(timeout=0.05):
                stalls += 1
                if stalls >= 600:  # ~30s of genuine silence
                    raise ResidencyError(
                        f"doc {for_doc}: stalled waiting for the "
                        "in-flight group's journal notifications — "
                        "commit thread dead? (pipeline failure)"
                    )

    def _evict(self, di: int) -> None:
        """Hot -> warm.  Every fallible step (mirror build, the
        ``evict_flush`` fault site) runs BEFORE any tier mutation, so a
        failure leaves the doc hot with no torn state."""
        mgr = self.mgr
        try:
            self._mirror(di)  # builds + caches the warm mirror
            faultinject.check("evict_flush", doc=di)
        except LoroError:
            raise
        except Exception as e:
            raise ResidencyError(
                f"doc {di}: evict failed before the slot release "
                f"({type(e).__name__}: {e}) — the doc stays hot"
            ) from e
        slot = mgr.slot_of.pop(di)
        del mgr.doc_of[slot]
        self.inner.release_doc(slot)
        mgr.free.append(slot)
        mgr.evictions += 1
        obs.counter("residency.evictions_total").inc(family=self.family)
        mgr._set_gauges()

    # -- warm/cold mirrors ----------------------------------------------
    def _srv(self) -> ResidentServer:
        if self._server is None:
            raise ResidencyError(
                "TieredBatch is not bound to a ResidentServer — the "
                "anchor/journal plane is the warm-tier source of truth"
            )
        return self._server

    def _mirror(self, di: int):
        """The doc's live host mirror: cached warm mirror, else built
        from its base (anchor blob, or the backing checkpoint rung for
        cold docs) plus the journal/WAL rounds after the base epoch —
        ``recover_server``'s bounded replay scoped to one doc."""
        mgr = self.mgr
        ent = mgr.mirrors.get(di)
        if ent is not None:
            return ent
        srv = self._srv()
        if di in mgr.cold and mgr.cold[di]:
            blob, seen_cids, base_epoch = self._cold_base(di)
            tail = self._wal_tail(di, base_epoch)
        else:
            anchor = srv._anchor
            blob = anchor.doc_blobs[di]
            seen_cids = list(anchor.seen_cids[di])
            base_epoch = anchor.epoch
            tail = [
                (e, ups[di] if di < len(ups) else None)
                for e, ups, _c in srv._history
                if e > base_epoch
            ]
        ent = self._replay_doc(di, blob, seen_cids, tail)
        mgr.mirrors[di] = ent
        return ent

    @staticmethod
    def _replay_doc(di: int, blob: bytes, seen_cids, tail):
        """THE one doc-mirror replay: seed a LoroDoc from its base blob
        and fold the tail rounds, tracking first-seen container ids.
        Shared by the warm-mirror build and cold-blob rehydration so
        the two can never drift.  Returns ``(doc, seen)``."""
        from ..codec.binary import decode_changes
        from ..doc import LoroDoc

        doc = LoroDoc(peer=(1 << 40) + di)
        if blob:
            doc.import_(blob, origin="residency-anchor")
        seen: Dict = {c: None for c in seen_cids}
        for _e, u in tail:
            if u is None:
                continue
            chs = (
                decode_changes(bytes(u))
                if isinstance(u, (bytes, bytearray)) else list(u)
            )
            for ch in chs:
                for op in ch.ops:
                    seen.setdefault(op.container)
            doc._import_changes(chs, origin="residency")
        return doc, seen

    def _export_history(self, doc) -> Optional[bytes]:
        """Full-history payload for the revive landing (None = empty
        doc, nothing to land — the slot alone suffices)."""
        from ..doc import strip_envelope

        if not len(doc.oplog_vv()):
            return None
        return strip_envelope(doc.export_updates())

    def _wal_tail(self, di: int, after_epoch: int):
        """The doc's WAL rounds after ``after_epoch`` (cold revive /
        rehydration: rounds between the backing rung and now)."""
        srv = self._srv()
        if srv._durable is None:
            raise ResidencyError(
                f"doc {di}: cold with no durable log attached — "
                "cold state needs the WAL to replay from"
            )
        return [
            (e, ups[di] if di < len(ups) else None)
            for e, _c, ups in srv._durable.wal.rounds_after(
                after_epoch, doc=di
            )
        ]

    def _cold_base(self, di: int):
        """(blob, seen_cids, epoch) of the doc at its backing rung."""
        anchor = self._load_rung_anchor(self.mgr.cold[di])
        if anchor.n_docs <= di:
            raise ResidencyError(
                f"doc {di}: backing rung anchor is {anchor.n_docs} docs wide"
            )
        return anchor.doc_blobs[di], list(anchor.seen_cids[di]), anchor.epoch

    def _load_rung_anchor(self, name: str):
        """Decode the mirror anchor out of a checkpoint rung (cached —
        one decode serves every cold doc backed by the same rung)."""
        if self._rung_cache is not None and self._rung_cache[0] == name:
            return self._rung_cache[1]
        from ..persist import MirrorAnchor
        from ..storage import MemKvStore

        srv = self._srv()
        if srv._durable is None:
            raise ResidencyError(
                f"cold backing rung {name!r} unreachable: no durable log"
            )
        mgr = srv._durable.checkpoints
        info = next((c for c in mgr.list() if c.name == name), None)
        if info is None:
            raise ResidencyError(
                f"cold backing rung {name!r} is gone from the ladder — "
                "the retention policy must never prune the newest rung"
            )
        blob = mgr.load(info)  # typed DecodeError on damage
        kv = MemKvStore()
        kv.import_all(blob)
        anchor_b = kv.get(b"anchor")
        if anchor_b is None:
            raise ResidencyError(
                f"cold backing rung {name!r} holds no mirror anchor"
            )
        anchor = MirrorAnchor.decode(anchor_b)
        self._rung_cache = (name, anchor)
        return anchor

    # -- anchor rehydration / demotion (checkpoint integration) ---------
    def rehydrate_anchor(self) -> None:
        """Put every cold doc's blob back into the server's in-memory
        anchor (transiently RAM-resident): checkpoint() folds and
        re-encodes the anchor, and the degradation / sync-oracle
        seeding paths need every doc readable.  Rounds between the
        backing rung and the anchor epoch (possible after a ladder
        fallback) are replayed and re-exported deep."""
        with self._plan_lock:
            for di in list(self.mgr.cold):
                self._rehydrate_doc_locked(di)

    def _rehydrate_doc_locked(self, di: int) -> None:
        """Restore one cold doc's anchor blob (state exactly at the
        anchor epoch) from its backing rung + the WAL rounds up to the
        anchor epoch.  The invariant every other path relies on: a
        NON-cold doc's anchor blob is authoritative — so every
        cold-tier EXIT (read, touch, rehydration) must run this before
        the cold entry is dropped."""
        anchor = self._srv()._anchor
        if anchor.doc_blobs[di]:
            return  # already present
        blob, seen_cids, base_epoch = self._cold_base(di)
        tail = [
            (e, u) for e, u in self._wal_tail(di, base_epoch)
            if e <= anchor.epoch and u is not None
        ]
        if tail:
            from ..doc import ExportMode

            doc, seen = self._replay_doc(di, blob, seen_cids, tail)
            blob = doc.export(ExportMode.Snapshot)
            seen_cids = list(seen)
        anchor.doc_blobs[di] = blob
        anchor.seen_cids[di] = list(seen_cids)

    def after_checkpoint(self, rung_name: Optional[str]) -> None:
        """Checkpoint landed: re-back every cold doc onto the fresh
        rung (it carries every doc's rehydrated blob) and drop their
        anchor blobs again; then run the warm-budget demotion policy
        and refresh the residency manifest.  ``rung_name`` is None for
        non-durable checkpoints — no cold tier to maintain."""
        with self._plan_lock:
            mgr = self.mgr
            srv = self._server
            if rung_name:
                anchor = self._srv()._anchor
                for di in list(mgr.cold):
                    mgr.cold[di] = rung_name
                    anchor.doc_blobs[di] = b""
                self._rung_cache = None
                budget = mgr.warm_slots
                if budget is not None:
                    tiers = mgr.tiers()
                    warm = sorted(
                        tiers[TIER_WARM], key=lambda d: mgr.last_touch_t[d]
                    )
                    for di in warm[: max(0, len(warm) - budget)]:
                        self._demote_locked(di, rung_name)
            if srv is not None and srv._durable is not None:
                self._write_manifest()

    def demote(self, di: int) -> None:
        """Warm -> cold (durable servers with at least one checkpoint
        rung): drop the live mirror AND the in-memory anchor blob; the
        doc's state becomes its backing rung + the WAL tail."""
        with self._plan_lock:
            if di in self.mgr.slot_of:
                raise ResidencyError(
                    f"doc {di} is hot — it must be evicted before it "
                    "can demote to cold"
                )
            if di in self.mgr.cold and self.mgr.cold[di]:
                return
            srv = self._srv()
            if srv._durable is None:
                raise ResidencyError(
                    "cold tier needs a durable server (durable_dir=): "
                    "cold state lives on the checkpoint ladder + WAL"
                )
            newest = srv._durable.checkpoints.load_newest()
            if newest is None:
                raise ResidencyError(
                    f"doc {di}: no valid checkpoint rung to back cold "
                    "state — checkpoint() first"
                )
            self._demote_locked(di, newest[0].name)
            self._write_manifest()

    def _demote_locked(self, di: int, rung_name: str) -> None:
        mgr = self.mgr
        mgr.cold[di] = rung_name
        mgr.mirrors.pop(di, None)
        self._restored_cold.pop(di, None)
        srv = self._server
        if srv is not None and srv._anchor is not None:
            srv._anchor.doc_blobs[di] = b""
        mgr.demotions += 1
        obs.counter("residency.demotions_total").inc(family=self.family)
        mgr._set_gauges()

    def flatten_cold(self) -> int:
        """Lift every cold doc back to the warm tier, rehydrating its
        anchor blob from the backing rung + WAL tail first.  The
        follower bootstrap runs this while the recovered server still
        holds its durable log: a following replica detaches
        ``_durable`` (the ship path owns the WAL files), which makes
        every cold-tier exit — reads, oracle seeding, the shipped-
        checkpoint rehydrate — unreachable.  Nothing re-demotes while
        following (``checkpoint()`` without a durable log skips the
        demotion policy), so the flatten holds until promotion
        re-attaches the log.  Returns the number of docs lifted."""
        with self._plan_lock:
            cold = sorted(self.mgr.cold)
            for di in cold:
                self._rehydrate_doc_locked(di)
                del self.mgr.cold[di]
            if cold:
                self._rung_cache = None
                self.mgr._set_gauges()
                obs.counter(
                    "residency.cold_flattens_total",
                    "cold docs lifted warm with their rung state folded "
                    "into the anchor (follower bootstrap)",
                ).inc(len(cold), family=self.family)
            return len(cold)

    def note_restored_rung(self, rung_name: str) -> None:
        """Recovery restored this batch from ``rung_name``: re-demote
        the docs that were cold at checkpoint time (their blobs are in
        the restored anchor; the rung now backs them), unless the WAL
        replay already revived them."""
        with self._plan_lock:
            for di in list(self._restored_cold):
                if di not in self.mgr.slot_of:
                    self._demote_locked(di, rung_name)
            self._restored_cold = {}
            srv = self._server
            if srv is not None and srv._durable is not None:
                self._write_manifest()

    def _write_manifest(self) -> None:
        """Atomic ``residency.json`` next to the WAL/ladder: the
        operator's (and persist.inspect's) view of per-tier occupancy
        and which rung backs each cold doc.  Advisory — recovery
        rebuilds tier state from the checkpoint blob itself."""
        srv = self._server
        if srv is None or srv._durable is None:
            return
        tiers = self.mgr.tiers()
        path = os.path.join(srv._durable.dir, MANIFEST_NAME)
        data = {
            "version": MANIFEST_VERSION,
            "family": self.family,
            "n_docs": self.n_docs,
            "hot_slots": self.hot_slots,
            "hot": {str(d): self.mgr.slot_of[d] for d in tiers[TIER_HOT]},
            "warm": tiers[TIER_WARM],
            "cold": {str(d): self.mgr.cold[d] for d in tiers[TIER_COLD]},
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- coalesced ingest (straight passthrough: landings ride the
    # inner deferral; releases only ever touch journal-stable slots) ---
    def begin_coalesce(self) -> None:
        with self._plan_lock:
            self._group_start_seq = self._append_seq
            self._coalesce_open = True
        self.inner.begin_coalesce()

    def detach_coalesce(self):
        with self._plan_lock:
            self._coalesce_open = False
        return self.inner.detach_coalesce()

    def commit_detached(self, d) -> None:
        self.inner.commit_detached(d)

    def flush_coalesce(self) -> None:
        # through detach on purpose: the group-boundary flag must reset
        # on the abort path too (ingest_stage flushes then re-raises)
        self.commit_detached(self.detach_coalesce())

    # -- compaction -----------------------------------------------------
    def compact(self, stable_epochs: Sequence[Optional[int]]) -> int:
        """Doc-space floors -> slot-space floors for the hot set (warm
        and cold docs hold no device rows)."""
        floors: List[Optional[int]] = [None] * self.hot_slots
        with self._plan_lock:
            for di, e in enumerate(stable_epochs):
                if e is None:
                    continue
                slot = self.mgr.slot_of.get(di)
                if slot is not None:
                    floors[slot] = e
        if all(f is None for f in floors):
            return 0
        return self.inner.compact(floors)

    # -- read plane (docs/SYNC.md) --------------------------------------
    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection over the TIERED fleet: the
        change-span index is tier-blind (fed from the sync commit
        path, never from device rows), so a batched pull serves warm
        and cold docs without touching tier state — NO revive, no slot
        landing, no mirror build.  The launch still routes through the
        inner hot-set batch's device lock/supervisor (the one device
        queue)."""
        from .fleet import _batch_export_select

        return _batch_export_select(
            self.inner, self.family, index, requests, sup
        )

    # -- reads (hot from device, warm/cold from mirrors) ----------------
    _EMPTY_READS = {
        "texts": "", "richtexts": [], "values": [], "value_lists": [],
        "value_maps": {}, "root_value_maps": {}, "parent_maps": {},
        "children_maps": {},
    }

    def _read_merge(self, name: str, *args):
        with self._plan_lock:
            inner_out = getattr(self.inner, name)(*args)
            out = []
            for di in range(self.n_docs):
                slot = self.mgr.slot_of.get(di)
                if slot is not None:
                    out.append(inner_out[slot])
                else:
                    out.append(self._mirror_read(di, name, *args))
            return out

    def _mirror_read(self, di: int, name: str, *args):
        from ..resilience.hostpath import HostEngine

        doc, seen = self._mirror(di)
        if di in self.mgr.cold:
            # reading a cold doc materialized its mirror: it is warm
            # now — restore its anchor blob first (cold-exit invariant)
            self._rehydrate_doc_locked(di)
            self.mgr.cold.pop(di, None)
            self.mgr._set_gauges()
        if not len(doc.oplog_vv()):
            empty = self._EMPTY_READS[name]
            return empty.copy() if hasattr(empty, "copy") else empty
        eng = HostEngine(self.family, 1)
        eng.docs[0] = doc
        eng._seen_cids[0] = seen
        eng._cid = self._server._cid if self._server is not None else None
        return getattr(eng, name)(*args)[0]

    def texts(self, use_solver: bool = False) -> List[str]:
        return self._read_merge("texts", use_solver)

    def richtexts(self) -> List[list]:
        return self._read_merge("richtexts")

    def values(self, use_solver: bool = False) -> List[list]:
        return self._read_merge("values", use_solver)

    def value_lists(self) -> List[list]:
        return self._read_merge("value_lists")

    def value_maps(self):
        return self._read_merge("value_maps")

    def root_value_maps(self, name: str):
        return self._read_merge("root_value_maps", name)

    def parent_maps(self) -> List[dict]:
        return self._read_merge("parent_maps")

    def children_maps(self) -> List[dict]:
        return self._read_merge("children_maps")

    # -- checkpoint/resume ----------------------------------------------
    STATE_VERSION = 1

    def export_state(self) -> bytes:
        """Inner batch state + the tier map as one LTKV store.  Warm
        mirrors are NOT serialized — they are derivable from the
        server's anchor + journal, which the server checkpoint already
        carries."""
        from ..codec.binary import Writer
        from ..storage import MemKvStore

        kv = MemKvStore()
        with self._plan_lock:
            w = Writer()
            w.u8(self.STATE_VERSION)
            w.str_(self.family)
            w.varint(self.n_docs)
            w.varint(self.hot_slots)
            w.varint(len(self.mgr.slot_of))
            for di in sorted(self.mgr.slot_of):
                w.varint(di)
                w.varint(self.mgr.slot_of[di])
            cold = {
                di: name for di, name in self.mgr.cold.items() if name
            }
            w.varint(len(cold))
            for di in sorted(cold):
                w.varint(di)
                w.str_(cold[di])
            kv.set(b"tiered", bytes(w.buf))
            kv.set(b"inner", self.inner.export_state())
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "TieredBatch":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, inner_b = kv.get(b"tiered"), kv.get(b"inner")
        if meta_b is None or inner_b is None:
            raise DecodeError("TieredBatch state: missing sections")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"TieredBatch state v{version} too new")
            family = r.str_()
            n_docs = r.varint()
            hot_slots = r.varint()
            slot_of = {r.varint(): r.varint() for _ in range(r.varint())}
            restored_cold = {r.varint(): r.str_() for _ in range(r.varint())}
        except (IndexError, ValueError, UnicodeDecodeError) as e:
            raise DecodeError(f"TieredBatch state: malformed ({e})") from None
        if family not in _FAMILIES:
            raise DecodeError(f"TieredBatch state: unknown family {family!r}")
        if any(s >= hot_slots for s in slot_of.values()) or any(
            d >= n_docs for d in slot_of
        ):
            raise DecodeError("TieredBatch state: slot map out of range")
        if len(set(slot_of.values())) != len(slot_of):
            raise DecodeError("TieredBatch state: duplicate slot assignment")
        if any(d >= n_docs for d in restored_cold):
            raise DecodeError("TieredBatch state: cold map out of range")
        obj = cls.__new__(cls)
        obj.family = family
        obj.n_docs = n_docs
        obj.d = n_docs
        obj.hot_slots = hot_slots
        obj.inner = _FAMILIES[family][0].import_state(inner_b, mesh=mesh)
        obj.mgr = ResidencyManager(family, n_docs, hot_slots)
        obj._plan_lock = obj.mgr._plan_lock
        obj._server = None
        obj._append_seq = 0
        obj._safe_seq = 0
        obj._pending_journal = deque()
        obj._plan_cv = threading.Condition(obj._plan_lock)
        obj._group_start_seq = 0
        obj._coalesce_open = False
        obj._rung_cache = None
        # restored cold docs keep their blobs (the restoring checkpoint
        # carries every doc) until recovery names the rung that backs
        # them (note_restored_rung) — a bare restore() leaves them warm
        obj._restored_cold = {
            di: name for di, name in restored_cold.items()
            if di not in slot_of
        }
        obj.mgr.slot_of = dict(slot_of)
        obj.mgr.doc_of = {s: d for d, s in slot_of.items()}
        obj.mgr.free = deque(
            s for s in range(hot_slots) if s not in obj.mgr.doc_of
        )
        obj.mgr._set_gauges()
        if hasattr(obj.inner, "append_payloads"):
            obj.append_payloads = obj._append_payloads_impl
        return obj

    # -- reporting -------------------------------------------------------
    def report(self) -> dict:
        return self.mgr.report()


class TieredResidentServer(ResidentServer):
    """Convenience wrapper: ``TieredResidentServer(family, n_docs,
    hot_slots=K, ...)`` is exactly ``ResidentServer(family, n_docs,
    hot_slots=K, ...)`` — a doc-space server whose device batch holds
    only the K-doc hot set, with warm/cold tiers behind it
    (docs/RESIDENCY.md)."""

    def __init__(self, family: str, n_docs: int, hot_slots: int,
                 mesh=None, **kw):
        super().__init__(family, n_docs, mesh=mesh, hot_slots=hot_slots, **kw)
