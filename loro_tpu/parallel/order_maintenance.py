"""Incremental Fugue order maintenance for device-resident batches.

The batched solver (ops/fugue_batch.py) re-ranks the whole element
table per launch — right for cold bulk merges, wasteful for a resident
fleet where a sync appends a few rows to a large standing table
(VERDICT round-1 item 4).  This module maintains, per document, a
*shadow order*: a compact host mirror of the Fugue tree that places
each new row in O(local structure) and assigns it a 64-bit integer key
such that ascending key == Fugue traversal order.  The device then
materializes visible content with one multi-key sort over the standing
key columns instead of an Euler-tour + Wyllie rank solve.

Per-sync cost is O(delta), not O(table):
- run-continuation appends (the steady state) are O(1) splices;
- branch inserts bisect the sibling list and find the traversal
  predecessor exactly as the host engine does (seq_crdt.py `_place`):
  subtree-last walks only run at real branch points;
- keys come from gap midpoints (negative keys allowed, so front
  inserts never collide); a middle gap survives ~20 nested same-spot
  concurrent inserts before one O(rows) renumber walk reassigns
  uniform keys — no semantic recomputation, the caller just re-uploads
  that doc's key column.

Sibling semantics mirror models/seq_crdt.py exactly (ascending
(peer, counter); L-children before the node, R-children after); the
differential fuzz in tests/test_order_maint.py checks the key order
against FugueSeq on random multi-peer histories.

Memory: ~40 B/row in numpy arrays + dict entries only at branch
points — a deliberate trade: host RAM buys removing the per-sync
O(table) rank solve from the device hot path.
"""
from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

KEY_STEP = 1 << 20
# Run-continuation inserts take a SMALL biased step instead of the gap
# midpoint: a typing run of L chars then consumes L*RUN_STEP of the gap
# instead of halving it L times (which exhausted a fresh 2^20 gap after
# ~2 nearby runs and made renumbers ~35% of epoch ingests — r5 profile).
# 2^20 / 2^8 = 4096 sequential chars fit in one gap before a renumber.
RUN_STEP = 1 << 8
KEY_BIAS = 1 << 62  # added before the u32-halves split (order-preserving)
HEAD = -2  # linked-list sentinel: before the first element


class ShadowOrder:
    """Shadow Fugue order for one document's element rows.

    Rows are referenced by their device row index (the same index the
    resident batch uses).  `append_rows` places a batch of new rows and
    returns their keys — or None after a renumber, in which case the
    caller re-uploads the full key column from `all_keys()`.
    """

    def __init__(self, capacity_hint: int = 256):
        n = max(16, capacity_hint)
        self.n = 0
        self.peer = np.zeros(n, np.uint64)
        self.ctr = np.zeros(n, np.int64)
        self.prev = np.full(n, HEAD, np.int32)  # order links
        self.next = np.full(n, -1, np.int32)
        self.spine = np.full(n, -1, np.int32)  # single R-run child (fast path)
        self.key = np.zeros(n, np.int64)
        self.first_row = -1  # order head
        # branch points only: (row, side) -> child rows sorted by
        # (peer, ctr); side=1 lists INCLUDE the former spine child so
        # sibling order is explicit wherever a node has >1 child
        self.branches: Dict[Tuple[int, int], List[int]] = {}
        self.root_children: List[int] = []
        self.renumbers = 0

    # -- storage -------------------------------------------------------
    def _grow(self, need: int) -> None:
        cap = len(self.peer)
        if need <= cap:
            return
        new = max(need, cap * 2)
        for f in ("peer", "ctr", "prev", "next", "spine", "key"):
            a = getattr(self, f)
            b = np.empty(new, a.dtype)
            b[: self.n] = a[: self.n]
            setattr(self, f, b)

    def all_keys(self) -> np.ndarray:
        return self.key[: self.n]

    # -- navigation ----------------------------------------------------
    def _sib_key(self, row: int) -> Tuple[int, int]:
        return (int(self.peer[row]), int(self.ctr[row]))

    def _last_r_child(self, row: int) -> int:
        br = self.branches.get((row, 1))
        if br:
            return br[-1]
        return int(self.spine[row])

    def _subtree_last(self, row: int) -> int:
        x = row
        while True:
            nxt = self._last_r_child(x)
            if nxt < 0:
                return x
            x = nxt

    def _subtree_first(self, row: int) -> int:
        x = row
        while True:
            br = self.branches.get((x, 0))
            if not br:
                return x
            x = br[0]

    # -- linked list + keys -------------------------------------------
    def _splice_after(self, pred: int, row: int) -> None:
        if pred == HEAD:
            succ = self.first_row
            self.first_row = row
        else:
            succ = int(self.next[pred])
            self.next[pred] = row
        self.prev[row] = pred
        self.next[row] = succ
        if succ >= 0:
            self.prev[succ] = row

    def _assign_key(self, row: int, run: bool = False) -> bool:
        """Gap key from order neighbors: midpoint for branch inserts, a
        small low-biased step for run continuations (see RUN_STEP).
        False = gap empty (caller renumbers)."""
        pred = int(self.prev[row])
        succ = int(self.next[row])
        if pred < 0 and succ < 0:
            self.key[row] = 0
        elif pred < 0:
            self.key[row] = int(self.key[succ]) - KEY_STEP
        elif succ < 0:
            self.key[row] = int(self.key[pred]) + KEY_STEP
        else:
            lo, hi = int(self.key[pred]), int(self.key[succ])
            if hi - lo < 2:
                return False
            step = (hi - lo) // 2
            if run and step > RUN_STEP:
                step = RUN_STEP
            self.key[row] = lo + step
        return True

    def _renumber(self) -> None:
        """Reassign uniform keys along the order list (O(rows), rare)."""
        self.renumbers += 1
        k = 0
        x = self.first_row
        while x >= 0:
            self.key[x] = k
            k += KEY_STEP
            x = int(self.next[x])

    # -- placement -----------------------------------------------------
    def append_rows(
        self, rows: Sequence[Tuple[int, int, int, int]], base_row: int
    ) -> Optional[List[int]]:
        """Place rows (parent_row, side, peer, ctr); row j gets device
        row base_row + j.  Returns per-row keys, or None if a renumber
        happened (caller re-uploads all_keys())."""
        self._grow(base_row + len(rows))
        keys: List[int] = []
        renumbered = False
        for j, (parent_row, side, peer, ctr) in enumerate(rows):
            row = base_row + j
            self.n = max(self.n, row + 1)
            self.peer[row] = np.uint64(peer)
            self.ctr[row] = ctr
            self.spine[row] = -1
            run = self._place(parent_row, side, row)
            if not self._assign_key(row, run):
                self._renumber()
                renumbered = True
            keys.append(int(self.key[row]))
        return None if renumbered else keys

    def append_arrays(self, parent, side, peer, ctr, base_row: int):
        """Columnar adapter matching NativeShadowOrder.append_arrays
        (the fallback pays the tuple conversion; the native engine
        takes the arrays directly)."""
        return self.append_rows(
            list(zip(parent.tolist(), side.tolist(), peer.tolist(), ctr.tolist())),
            base_row,
        )

    def _place(self, parent_row: int, side: int, row: int) -> bool:
        """Place `row`; True = run-continuation fast path (the caller
        assigns a low-biased key so runs don't bisect the gap)."""
        # run-continuation fast path: R-insert under a childless parent
        # from the same peer with a contiguous counter
        if (
            parent_row >= 0
            and side == 1
            and self.spine[parent_row] < 0
            and (parent_row, 1) not in self.branches
            and int(self.peer[parent_row]) == int(self.peer[row])
            and int(self.ctr[parent_row]) == int(self.ctr[row]) - 1
        ):
            self.spine[parent_row] = row
            self._splice_after(parent_row, row)
            return True
        sibs = self._sibling_list(parent_row, side)
        i = bisect_left(sibs, self._sib_key(row), key=self._sib_key)
        sibs.insert(i, row)
        if side == 1 or parent_row < 0:
            if i == 0:
                # smallest R-sibling: immediately after the parent
                pred = parent_row if parent_row >= 0 else HEAD
            else:
                pred = self._subtree_last(sibs[i - 1])
            self._splice_after(pred, row)
        else:
            if i > 0:
                self._splice_after(self._subtree_last(sibs[i - 1]), row)
            else:
                # new leftmost of the parent's subtree: before its old
                # subtree-first (next L-sibling's first, or the parent)
                nxt = sibs[i + 1] if len(sibs) > i + 1 else -1
                old_first = self._subtree_first(nxt) if nxt >= 0 else parent_row
                self._splice_after(int(self.prev[old_first]), row)
        return False

    def _sibling_list(self, parent_row: int, side: int) -> List[int]:
        if parent_row < 0:
            return self.root_children
        key = (parent_row, side)
        lst = self.branches.get(key)
        if lst is None:
            lst = []
            if side == 1:
                sp = int(self.spine[parent_row])
                if sp >= 0:
                    lst.append(sp)
                    self.spine[parent_row] = -1  # now tracked in branches
            self.branches[key] = lst
        return lst


def split_keys(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Order-preserving (hi, lo) u32 split of signed int64 keys for the
    device sort (TPU path avoids x64)."""
    biased = (keys.astype(np.int64) + np.int64(KEY_BIAS)).view(np.uint64)
    u = biased
    return (u >> np.uint64(32)).astype(np.uint32), (
        u & np.uint64(0xFFFFFFFF)
    ).astype(np.uint32)
