"""Pipelined resident ingest: host/device overlap + round coalescing.

The serial resident serving loop pays four sequential costs per sync
round: host staging (decode, order maintenance, id maps), the WAL
append(+fsync), the device scatter launch, and the draining fetch that
bounds the async queue (the honest sync under the axon tunnel —
docs/RESILIENCE.md).  At serving granularity the launch + drain +
fsync floor dominates, which is why BENCH_r05 measured the resident
path at ~1M rows/s against 5.9M on the bulk chain path.

``PipelinedIngest`` attacks the fixed costs the way a read-optimized
differential store overlaps its delta buffer with the batch merge
(arXiv:1109.6885), and the way eg-walker keeps the incremental path
cheap per delta (arXiv:2409.14252):

- **round coalescing** — queued rounds drain into coalesced groups of
  up to ``coalesce`` rounds (``server.ingest_stage``): one device
  scatter/fold per structure per group instead of per round, with the
  host epoch clock, journal records, poison isolation and per-round
  ack epochs untouched (the coalesced state is byte-for-byte the
  serial state — tests/test_resident_server.py gates it);
- **double-buffered host/device overlap** — a stage thread runs group
  N+1's host work (decode, ShadowOrder/id-map staging, per-round epoch
  stamps) while the commit thread has group N's merged scatter in
  flight on the device; the stage phase touches no device arrays (a
  rare capacity grow serializes on the batch's device lock), so the
  two phases genuinely overlap;
- **bounded depth + backpressure** — at most ``depth`` groups' worth
  of rounds queue before ``submit`` blocks, and exactly one staged
  group waits behind the in-flight commit, so a stalled device never
  accumulates unbounded staged work; the launch queue itself stays
  under the DeviceSupervisor drain budget (never-SIGKILL rules hold:
  nothing here ever signals a process).

With ``durable_fsync="group"`` the group's journal records share one
fsync and a round's epoch future resolves only after it — an acked
round is never lost to a crash (``ResidentServer.durable_epoch``).

Every outcome feeds the obs registry (``pipeline.*``) and ``report()``
returns the compact dict bench.py banks as the ``pipeline`` sidecar.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from ..analysis.lockwitness import named_lock
from ..obs import metrics as obs
from ..utils import tracing


class PendingRound:
    """Handle for one submitted round: ``epoch()`` blocks until the
    round's group has been applied (and, in group-commit mode, fsynced)
    and returns the visible epoch clients ack.

    ``trace_id`` (set by the submitter) rides into the commit thread's
    ambient trace context so the WAL round record is stamped with the
    request that caused it; ``marks`` carries the stage-boundary
    timestamps the owning PushTickets fold into their breakdowns
    (docs/OBSERVABILITY.md "Request tracing")."""

    __slots__ = ("_ev", "_epoch", "_error", "trace_id", "marks")

    def __init__(self):
        self._ev = threading.Event()
        self._epoch: Optional[int] = None
        self._error: Optional[BaseException] = None
        self.trace_id: Optional[str] = None
        self.marks: List[tuple] = []  # (stage_name, perf_counter)

    def _resolve(self, epoch: int) -> None:
        self._epoch = epoch
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    @property
    def done(self) -> bool:
        return self._ev.is_set()

    def epoch(self, timeout: Optional[float] = None) -> int:
        if not self._ev.wait(timeout):
            raise TimeoutError("round not applied yet")
        if self._error is not None:
            raise self._error
        return self._epoch


class PipelinedIngest:
    """Two-stage ingest executor over one ``ResidentServer``.

    ``coalesce``: max rounds per device group; ``depth``: max groups'
    worth of rounds queued before ``submit`` blocks (backpressure).
    ``cid``: default container id for submitted rounds (map/counter
    families need none); per-submit ``cid`` overrides, and a group
    never mixes cids.

    Construct via ``ResidentServer.pipeline(...)`` so ``close()`` /
    ``checkpoint()`` can drain it.  Thread contract: ``submit`` may be
    called from any ONE producer thread at a time; reads of the server
    are safe after ``flush()``.
    """

    def __init__(self, server, cid=None, coalesce: int = 4, depth: int = 2):
        self._server = server
        self._cid = cid
        self._coalesce = max(1, int(coalesce))
        # tiered residency (parallel/residency.py): the server may bound
        # how many DISTINCT docs one group touches — a group's docs
        # co-reside in device slots until it commits, so unbounded
        # grouping could outgrow the hot set.  None = no bound.
        self._doc_budget = getattr(server, "pipeline_doc_budget", None)
        self._max_queued = self._coalesce * max(1, int(depth))
        self._lock = named_lock("pipeline.queue")
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()        # (updates, cid, PendingRound)
        self._commit_q: deque = deque() # (handle, [PendingRound]) — len <= 1
        self._staging = False
        self._committing = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self._stage_thread: Optional[threading.Thread] = None
        self._commit_thread: Optional[threading.Thread] = None
        # report counters
        self._rounds = 0
        self._groups = 0
        self._coalesced_rounds = 0
        self._max_group = 0
        self._max_depth_seen = 0
        self._backpressure_waits = 0
        self._stage_s = 0.0
        self._commit_s = 0.0
        self._overlap_s = 0.0
        self._t0: Optional[float] = None

    # -- producer side -------------------------------------------------
    def submit(self, per_doc_updates: Sequence, cid=None,
               trace: Optional[str] = None) -> PendingRound:
        """Queue one sync round (same payload contract as
        ``ResidentServer.ingest``).  Blocks while the queue is at the
        backpressure bound; returns a ``PendingRound`` whose
        ``epoch()`` resolves once the round's group lands.

        Change-list entries are FROZEN here (codec round trip): the
        live Change objects are aliased with the producing doc's oplog,
        which extends them in place on later commits (change RLE) — and
        unlike serial ingest, a queued round survives across those
        commits.  Freezing at submit pins the round to the ops it held
        when submitted, exactly what a prompt serial ingest would have
        applied.  Bytes payloads are immutable and ride as-is (this is
        the recommended form: zero extra host work)."""
        from ..codec.binary import decode_changes, encode_changes

        per_doc_updates = [
            u if u is None or isinstance(u, (bytes, bytearray))
            else decode_changes(bytes(encode_changes(list(u))))
            for u in per_doc_updates
        ]
        pr = PendingRound()
        # set BEFORE the round is visible to the workers: the commit
        # thread reads it for the ambient WAL trace stamp
        pr.trace_id = trace if trace is not None else tracing.current()
        with self._cv:
            self._check_open()
            if self._t0 is None:
                self._t0 = time.perf_counter()
            if len(self._q) >= self._max_queued:
                self._backpressure_waits += 1
                obs.counter("pipeline.backpressure_waits_total").inc(
                    family=self._server.family
                )
            while len(self._q) >= self._max_queued and self._error is None \
                    and not self._stop:
                self._cv.wait()
            self._check_open()
            self._q.append((list(per_doc_updates), cid if cid is not None
                            else self._cid, pr))
            self._rounds += 1
            self._max_depth_seen = max(self._max_depth_seen, len(self._q))
            obs.gauge(
                "pipeline.depth", "rounds staged behind the device group"
            ).set(len(self._q), family=self._server.family)
            if self._stage_thread is None:
                self._stage_thread = threading.Thread(
                    target=self._stage_run, name="loro-pipeline-stage",
                    daemon=True,
                )
                self._commit_thread = threading.Thread(
                    target=self._commit_run, name="loro-pipeline-commit",
                    daemon=True,
                )
                self._stage_thread.start()
                self._commit_thread.start()
            self._cv.notify_all()
        return pr

    def _check_open(self) -> None:
        if self._stop:
            raise RuntimeError("pipeline is closed")
        if self._error is not None:
            raise RuntimeError(
                "pipeline failed; no further rounds accepted"
            ) from self._error

    def flush(self) -> None:
        """Block until every submitted round is applied (and its group
        fsynced).  Re-raises the first worker error.  No-op from the
        pipeline's own threads (the auto-checkpoint a worker ingest
        triggers calls back into the server's drain hook)."""
        me = threading.current_thread()
        if me is self._stage_thread or me is self._commit_thread:
            return
        with self._cv:
            while (self._q or self._commit_q or self._staging
                   or self._committing) and self._error is None:
                self._cv.wait()
            if self._error is not None:
                raise RuntimeError("pipeline failed") from self._error

    def close(self) -> None:
        """Drain, then stop the workers.  Idempotent."""
        err = None
        try:
            self.flush()
        except RuntimeError as e:
            err = e
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        me = threading.current_thread()
        for t in (self._stage_thread, self._commit_thread):
            if t is not None and me is not t:
                t.join(timeout=30.0)
        if err is not None:
            raise err

    @property
    def closed(self) -> bool:
        return self._stop

    # -- stage worker --------------------------------------------------
    def _pop_group(self) -> List[tuple]:
        """Up to ``coalesce`` queued rounds sharing one cid (groups
        never mix container ids — ingest_stage takes one).  With a
        server doc budget, the group also stops before its DISTINCT
        touched docs would exceed it (tiered hot-set bound); the first
        round is always taken, so an over-budget single round reaches
        the server and fails typed there."""
        group: List[tuple] = []
        docs_seen: set = set()
        while self._q and len(group) < self._coalesce:
            if group and self._q[0][1] != group[0][1]:
                break
            if self._doc_budget is not None and group:
                nxt = {
                    di for di, u in enumerate(self._q[0][0]) if u is not None
                }
                if len(docs_seen | nxt) > self._doc_budget:
                    break
            group.append(self._q.popleft())
            if self._doc_budget is not None:
                docs_seen.update(
                    di for di, u in enumerate(group[-1][0]) if u is not None
                )
        return group

    def _fail_all(self, e: BaseException, group=None) -> None:
        """Mark the pipeline failed and resolve every waiter (the
        in-flight group, the staged group, and the whole queue)."""
        with self._cv:
            self._error = e
            self._staging = self._committing = False
            for _ups, _c, pr in group or ():
                pr._fail(e)
            while self._commit_q:
                _h, futs = self._commit_q.popleft()
                for pr in futs:
                    pr._fail(e)
            while self._q:
                _ups, _c, pr = self._q.popleft()
                pr._fail(e)
            self._cv.notify_all()

    def _stage_run(self) -> None:
        srv = self._server
        while True:
            with self._cv:
                while not self._q and not self._stop and self._error is None:
                    self._cv.notify_all()  # wake flushers: stage idle
                    self._cv.wait()
                if (self._stop and not self._q) or self._error is not None:
                    self._cv.notify_all()
                    return
                group = self._pop_group()
                self._staging = True
                obs.gauge(
                    "pipeline.depth", "rounds staged behind the device group"
                ).set(len(self._q), family=srv.family)
                self._cv.notify_all()  # backpressured producers refill
            t0 = time.perf_counter()
            try:
                handle = srv.ingest_stage(
                    [ups for ups, _c, _p in group], group[0][1]
                )
            except BaseException as e:  # noqa: BLE001 — fail every waiter
                self._fail_all(e, group)
                return
            dt = time.perf_counter() - t0
            futs = [pr for _ups, _c, pr in group]
            for pr in futs:
                # attribution: waited-for-grouping, then host staging
                pr.marks.append(("coalesce_wait", t0))
                pr.marks.append(("stage", t0 + dt))
            exclusive = (
                handle.mode != "group" or handle.error_index is not None
            )
            with self._cv:
                self._stage_s += dt
                if self._committing:
                    # this stage ran while a commit was on the device —
                    # the overlap the executor exists for
                    self._overlap_s += dt
                # double buffering: exactly one staged group may wait
                # behind the in-flight commit
                while self._commit_q and self._error is None:
                    self._cv.wait()
                if self._error is not None:
                    for pr in futs:
                        pr._fail(self._error)
                    return
                self._commit_q.append((handle, futs))
                self._staging = False
                self._cv.notify_all()
                if exclusive:
                    # serial-completion handles (poison round, degraded
                    # server) mutate host state in the commit thread:
                    # stall staging until this group fully commits
                    while self._commit_q and self._error is None \
                            and not self._stop:
                        self._cv.wait()

    # -- commit worker -------------------------------------------------
    def _commit_run(self) -> None:
        srv = self._server
        while True:
            with self._cv:
                while not self._commit_q and not self._stop \
                        and self._error is None:
                    self._cv.notify_all()  # wake flushers: commit idle
                    self._cv.wait()
                if self._error is not None or (
                    self._stop and not self._commit_q
                ):
                    self._cv.notify_all()
                    return
                handle, futs = self._commit_q[0]
                self._committing = True
                self._cv.notify_all()
            t0 = time.perf_counter()
            try:
                # ambient trace: the WAL appends inside ingest_commit
                # stamp their round records with the request that led
                # the group (group granularity — one fsync window)
                with tracing.ambient(next(
                    (pr.trace_id for pr in futs if pr.trace_id), None
                )):
                    epochs = srv.ingest_commit(handle)
            except BaseException as e:  # noqa: BLE001 — fail every waiter
                with self._cv:
                    self._commit_q.popleft()
                for pr in futs:
                    pr._fail(e)
                self._fail_all(e)
                return
            dt = time.perf_counter() - t0
            with self._cv:
                self._commit_q.popleft()
                self._commit_s += dt
                self._groups += 1
                self._max_group = max(self._max_group, len(futs))
                if len(futs) > 1:
                    self._coalesced_rounds += len(futs)
                now = t0 + dt
                for pr, ep in zip(futs, epochs):
                    pr.marks.append(("commit", now))
                    pr._resolve(ep)
                self._committing = False
                self._cv.notify_all()

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        """Compact outcome dict (the bench ``pipeline`` sidecar).
        ``overlap_fraction`` is the share of host staging time that ran
        while a device commit was in flight — the double-buffering
        actually achieved, not a modeled number."""
        with self._lock:
            wall = (
                time.perf_counter() - self._t0 if self._t0 is not None else 0.0
            )
            return {
                "rounds": self._rounds,
                "groups": self._groups,
                "coalesced_rounds": self._coalesced_rounds,
                "max_group": self._max_group,
                "coalesce_limit": self._coalesce,
                "max_depth_seen": self._max_depth_seen,
                "queue_bound": self._max_queued,
                "backpressure_waits": self._backpressure_waits,
                "stage_s": round(self._stage_s, 3),
                "commit_s": round(self._commit_s, 3),
                "overlap_s": round(self._overlap_s, 3),
                "overlap_fraction": (
                    round(self._overlap_s / self._stage_s, 3)
                    if self._stage_s > 0 else 0.0
                ),
                "wall_s": round(wall, 3),
            }
