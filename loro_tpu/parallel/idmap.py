"""Per-doc (peer, counter) -> device-row id maps for resident batches.

Two interchangeable implementations of one contract:

- ``NativeIdMap`` (native/__init__.py): C++ hash map behind a ctypes
  handle — the hot path; batch stage/lookup/insert calls release the
  GIL so docs shard across threads.
- ``PyIdMap`` (here): a plain dict subclass with the same batch/staging
  surface for when the native library is unavailable (and as the
  differential oracle in tests).

The staging contract (shared with the order engine's caller,
DeviceDocBatch._commit_rows): ``stage_base`` makes rows visible to
``lookup``/``get`` WITHOUT committing; ``commit`` publishes them;
``abort`` discards them — so a capacity error or a per-doc native
fallback leaves the map untouched.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


class PyIdMap(dict):
    """Dict-backed fallback with the batch/staging API of NativeIdMap.

    Inherits dict for the committed view, so all dict-like uses
    (``get``, ``[]``, ``len``, truthiness, ``update``) work natively.
    """

    __slots__ = ("_staged",)

    def __init__(self):
        super().__init__()
        self._staged: Dict[Tuple[int, int], int] = {}

    # -- staged-aware reads -------------------------------------------
    def get(self, key, default=None):
        v = self._staged.get(key)
        if v is not None:
            return v
        return super().get(key, default)

    def __getitem__(self, key):
        v = self._staged.get(key)
        if v is not None:
            return v
        return super().__getitem__(key)

    def __contains__(self, key) -> bool:
        return key in self._staged or super().__contains__(key)

    # -- columnar API --------------------------------------------------
    def insert_arrays(self, peer, ctr, rows) -> None:
        self.update(zip(zip(peer.tolist(), ctr.tolist()), rows.tolist()))

    def stage_base(self, peer, ctr, base_row: int) -> None:
        n = len(peer)
        self._staged.update(
            zip(zip(peer.tolist(), ctr.tolist()), range(base_row, base_row + n))
        )

    def lookup(self, peer, ctr) -> np.ndarray:
        out = np.empty(len(peer), np.int32)
        for i, k in enumerate(zip(peer.tolist(), ctr.tolist())):
            out[i] = self.get(k, -1)
        return out

    def commit(self) -> None:
        if self._staged:
            self.update(self._staged)
            self._staged.clear()

    def abort(self) -> None:
        self._staged.clear()


def make_idmap():
    """The native map when the C++ library is available, else PyIdMap.
    LORO_PY_IDMAP=1 forces the Python map (the differential oracle)."""
    import os

    if os.environ.get("LORO_PY_IDMAP", "0") not in ("1", "true", "yes"):
        from ..native import native_idmap

        m = native_idmap()
        if m is not None:
            return m
    from ..obs import metrics as obs

    obs.counter("fleet.host_fallback_total").inc(kind="idmap")
    return PyIdMap()
