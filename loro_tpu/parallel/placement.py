"""Pure-host placement + epoch-translation primitives of the sharded
resident fleet (docs/SHARDING.md).

Split from ``parallel/sharded.py`` so consumers that must stay off the
jax import graph — ``persist.inspect`` translates the fleet durable
watermark with the REAL `_EpochMap`, not a hand-kept mirror — can
import them directly.  Nothing here touches a device.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from ..errors import ShardingError


def rendezvous_shard(key: str, n_shards: int) -> int:
    """Highest-random-weight (rendezvous) shard for ``key``: the shard
    whose keyed digest of ``key`` is largest.  Deterministic across
    runs and processes (blake2b, never Python's seeded hash()), and
    resize-stable: adding shard N changes a doc's placement only if
    shard N wins it — docs never move between surviving shards."""
    best, best_w = 0, b""
    for s in range(n_shards):
        w = hashlib.blake2b(
            f"{key}|{s}".encode("utf-8"), digest_size=8
        ).digest()
        if w > best_w:
            best, best_w = s, w
    return best


class ShardPlacement:
    """Doc→(shard, local slot) assignment for a sharded fleet.

    Slots are assigned in global-doc order within each shard; every
    shard is built ``spare_slots`` wider than its placed docs so live
    migration has somewhere to land (a migrated-away slot is RETIRED —
    its device rows keep the doc's pre-move state and are simply never
    read again — so each shard accepts at most ``spare_slots`` inbound
    moves over the server's life)."""

    def __init__(self, n_docs: int, n_shards: int,
                 keys: Optional[Sequence[str]] = None,
                 spare_slots: int = 1):
        if keys is not None and len(keys) != n_docs:
            raise ValueError(
                f"doc_keys has {len(keys)} entries for {n_docs} docs"
            )
        self.n_docs = n_docs
        self.n_shards = n_shards
        self.spare_slots = max(0, int(spare_slots))
        self.keys = (
            [str(k) for k in keys] if keys is not None
            else [str(i) for i in range(n_docs)]
        )
        self.shard_of = [rendezvous_shard(k, n_shards) for k in self.keys]
        counts = [0] * n_shards
        self.slot_of: List[int] = []
        for s in self.shard_of:
            self.slot_of.append(counts[s])
            counts[s] += 1
        self.widths = [c + self.spare_slots for c in counts]
        # unclaimed migration slots per shard, FIFO
        self.free = [
            list(range(counts[s], self.widths[s])) for s in range(n_shards)
        ]

    @classmethod
    def from_manifest(cls, m: dict) -> "ShardPlacement":
        p = cls.__new__(cls)
        p.n_docs = int(m["n_docs"])
        p.n_shards = int(m["shards"])
        p.spare_slots = int(m.get("spare_slots", 0))
        p.keys = [str(k) for k in m["keys"]]
        p.shard_of = [int(s) for s in m["shard_of"]]
        p.slot_of = [int(s) for s in m["slot_of"]]
        p.widths = [int(w) for w in m["widths"]]
        p.free = [[int(x) for x in f] for f in m["free"]]
        if not (len(p.keys) == len(p.shard_of) == len(p.slot_of) == p.n_docs
                and len(p.widths) == len(p.free) == p.n_shards):
            raise ShardingError("shard manifest: inconsistent placement")
        return p

    def place(self, di: int) -> Tuple[int, int]:
        return self.shard_of[di], self.slot_of[di]

    def docs_of(self, shard: int) -> List[int]:
        return [g for g, s in enumerate(self.shard_of) if s == shard]

    def move(self, di: int, to_shard: int) -> int:
        """Claim a spare slot on ``to_shard`` for ``di`` and flip the
        assignment; the old slot is retired.  Returns the new local
        slot; raises typed when the target has none left."""
        if not self.free[to_shard]:
            raise ShardingError(
                f"shard {to_shard} has no free migration slot left "
                f"(built with spare_slots={self.spare_slots}; rebuild "
                "the fleet with more headroom to keep migrating into it)"
            )
        slot = self.free[to_shard].pop(0)
        self.shard_of[di] = to_shard
        self.slot_of[di] = slot
        return slot


class _EpochMap:
    """Global-round → shard-visible-epoch translation (and back).

    Identity while the clocks run in lockstep; a breakpoint ``(g, e)``
    is recorded whenever a shard's clock skews (per-doc poison
    isolation journals one shard round per doc; a durable reopen can
    recover shards at different epochs).  Interpolation between
    breakpoints is clamped by the NEXT breakpoint so translated ack
    epochs never lead the true shard epoch (a floor that led could
    reclaim a tombstone a replica still references)."""

    def __init__(self, g: int = 0, e: int = 0):
        self._bp: List[Tuple[int, int]] = [(g, e)]

    def note(self, g: int, e: int) -> None:
        g0, e0 = self._bp[-1]
        if e - e0 != g - g0:
            self._bp.append((g, e))

    def to_shard(self, g: int) -> int:
        bp = self._bp
        if g <= bp[0][0]:  # below the first breakpoint: extrapolate down
            g0, e0 = bp[0]
            return max(0, e0 - (g0 - g))
        out = 0
        for i, (g0, e0) in enumerate(bp):
            if g0 > g:
                break
            out = e0 + (g - g0)
            if i + 1 < len(bp):
                out = min(out, bp[i + 1][1])
        return max(0, out)

    def to_global(self, e: int) -> int:
        out = 0
        for i, (g0, e0) in enumerate(self._bp):
            if e0 > e:
                break
            out = g0 + (e - e0)
            if i + 1 < len(self._bp):
                out = min(out, self._bp[i + 1][0])
        return max(0, out)

    def encode(self) -> List[List[int]]:
        return [[g, e] for g, e in self._bp]

    @classmethod
    def decode(cls, bps) -> "_EpochMap":
        m = cls.__new__(cls)
        m._bp = [(int(g), int(e)) for g, e in bps] or [(0, 0)]
        return m
