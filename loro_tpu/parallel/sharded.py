"""Sharded resident fleet: one logical ResidentServer over a ("docs",)
device mesh.

Everything below L5 treats a batch's device placement as an assumption
— one ResidentServer pins one device (or one NamedSharding) for its
whole life.  This module makes placement a *parameter*: a
``ShardedResidentServer`` partitions its doc set across the doc axis
of a mesh (``parallel/mesh.py``), one **per-shard ResidentServer** per
contiguous doc-axis slice, so N chips buy N× resident capacity and N
concurrent ingest launches (ROADMAP "millions of users"; the scale-out
argument of "Operational Concurrency Control in the Face of Arbitrary
Scale and Latency", PAPERS.md).

Design points (docs/SHARDING.md has the full story):

- **deterministic placement** — doc→shard via rendezvous hashing on a
  per-doc key (highest-random-weight over a keyed blake2b digest):
  the same key always lands on the same shard across runs and
  processes, and growing the shard count moves only the docs the NEW
  shards win — never a doc between surviving shards.
- **lockstep epoch clocks** — every ingest round fans out to every
  shard (untouched shards get an all-None round: an epoch bump and a
  small journal record, no device launch), so per-shard visible epochs
  advance in lockstep with the fleet-global epoch.  The rare skew
  (per-doc poison isolation journals extra shard rounds) is absorbed
  by a per-shard breakpoint translation map, so client acks on the
  global clock always reach each shard's compaction floors at or
  below the true shard epoch — floors may lag, never lead.
- **per-shard everything** — each shard has its own DeviceSupervisor
  (retry budgets and deadlines never couple shards), its own WAL +
  checkpoint ladder under ``<durable_dir>/shard-NN/`` (reopened
  independently by ``recover_sharded_server``; the fleet
  ``durable_epoch`` is the min over shards), and its own
  PipelinedIngest executor (``pipeline()`` returns a ShardedPipeline
  whose per-shard stage/commit threads launch coalesced groups
  concurrently across chips).  A DeviceFailure degrades ONE shard's
  batch onto its host mirror; the other shards never notice.
- **live migration** — ``migrate(di, to_shard)`` drains the pipeline,
  re-exports the doc's full history from the source shard's mirror
  (per-shard servers run history-complete "deep" mirror anchors for
  exactly this), and lands it in a spare slot on the target through
  one ordinary fleet round — epoch stream contiguous, a round fed
  mid-migration simply waits on the routing lock and lands exactly
  once under the new placement.

The sync front-end (``loro_tpu/sync``) rides on top unchanged: the
wrapper exposes the same serving surface as ResidentServer
(``ingest``/``ingest_coalesced``/``pipeline``/``subscribe_epochs``/
``seed_mirror_engine``/acks/reads/``durable_epoch``), so
``SyncServer.over(sharded)`` just works.
"""
from __future__ import annotations

import json
import os
import threading
import time as _time
from collections import deque
from typing import List, Optional, Sequence

from ..analysis.lockwitness import named_lock, named_rlock
from ..errors import ConfigError, LoroError, PersistError, ShardingError
from ..obs import heat as heat_acct
from ..obs import metrics as obs
from .mesh import make_mesh, shard_meshes
from .pipeline import PendingRound
from .placement import ShardPlacement, _EpochMap, rendezvous_shard
from .server import ResidentServer

MANIFEST_NAME = "sharding.json"
MANIFEST_VERSION = 1


# ---------------------------------------------------------------------------
# placement (rendezvous_shard / ShardPlacement / _EpochMap live in the
# jax-free parallel/placement.py, re-exported here; persist.inspect
# imports them directly for the watermark translation)
# ---------------------------------------------------------------------------


def _resolve_shards(shards, mesh) -> int:
    """Shard-count knob resolution with typed first-use validation:
    explicit ``shards=`` wins, else ``LORO_SHARDS``, else one shard per
    doc-axis device row.  Divisibility against the mesh is validated by
    ``shard_meshes`` (also typed ConfigError)."""
    import numpy as np

    if shards is None:
        env = os.environ.get("LORO_SHARDS")
        if env is not None:
            try:
                shards = int(env)
            except ValueError:
                raise ConfigError(
                    "LORO_SHARDS", env, "positive integer"
                ) from None
            if shards < 1:
                raise ConfigError("LORO_SHARDS", env, "positive integer")
        else:
            shards = int(np.asarray(mesh.devices).shape[0])
    return shards


# ---------------------------------------------------------------------------
# per-shard pipelined ingest
# ---------------------------------------------------------------------------


class ShardedPipeline:
    """Per-shard ``PipelinedIngest`` executors behind one ``submit``.

    A submitted round splits by placement and every slice rides its
    own shard's pipeline — per-shard stage/commit threads run
    concurrently, so coalesced device groups launch in parallel across
    chips.  A collector thread resolves each round's fleet-global
    epoch once EVERY shard has committed it (FIFO, so global epochs
    resolve in submit order), fires the wrapper's epoch subscribers,
    and with ``durable_fsync="group"`` shards a resolved epoch is
    covered by every shard's fsync window exactly as in the
    single-server pipeline."""

    def __init__(self, server: "ShardedResidentServer", cid=None,
                 coalesce: int = 4, depth: int = 2):
        self._server = server
        self._pipes = [
            srv.pipeline(cid=cid, coalesce=coalesce, depth=depth)
            for srv in server.shards
        ]
        self._lock = named_lock("sharded.collect")
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()  # (aggregate PendingRound, [shard prs])
        self._collecting = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._rounds = 0

    def submit(self, per_doc_updates: Sequence, cid=None,
               trace: Optional[str] = None) -> PendingRound:
        agg = PendingRound()
        agg.trace_id = trace
        with self._server._route_lock:
            with self._cv:
                self._check_open()
            if cid is not None:
                # keep the wrapper's served-cid current (migrate()
                # and the empty-round contract need it), exactly as
                # the direct ingest paths do
                self._server._cid = cid
            parts = self._server._split(list(per_doc_updates))
            self._server._tick_shard_rounds(parts)
            try:
                prs = [
                    pipe.submit(part, cid, trace=trace)
                    for pipe, part in zip(self._pipes, parts)
                ]
            except BaseException as e:  # noqa: BLE001 — fail-stop
                # a mid-fan-out failure (freeze/encode error, closed
                # shard pipe) may have enqueued earlier shards' slices
                # already — the round can no longer land exactly-once,
                # so the whole pipeline fails terminally rather than
                # accepting further rounds over a half-applied one
                with self._cv:
                    self._error = e
                    agg._fail(e)
                    while self._q:
                        a2, _ = self._q.popleft()
                        a2._fail(e)
                    self._cv.notify_all()
                raise
            with self._cv:
                self._q.append((agg, prs))
                self._rounds += 1
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="loro-sharded-collect",
                        daemon=True,
                    )
                    self._thread.start()
                self._cv.notify_all()
        return agg

    def _check_open(self) -> None:
        if self._stop:
            raise RuntimeError("sharded pipeline is closed")
        if self._error is not None:
            raise RuntimeError(
                "sharded pipeline failed; no further rounds accepted"
            ) from self._error

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop and self._error is None:
                    self._cv.notify_all()  # wake flushers: collector idle
                    self._cv.wait()
                if self._error is not None or (self._stop and not self._q):
                    self._cv.notify_all()
                    return
                agg, prs = self._q.popleft()
                self._collecting = True
            try:
                eps = [pr.epoch() for pr in prs]
            except BaseException as e:  # noqa: BLE001 — fail every waiter
                agg._fail(e)
                with self._cv:
                    self._error = e
                    self._collecting = False
                    while self._q:
                        a2, _ = self._q.popleft()
                        a2._fail(e)
                    self._cv.notify_all()
                return
            g = self._server._commit_global(eps)
            # attribution: one commit boundary for the aggregate round
            # (per-shard stage/commit detail lives in the shard pipes)
            agg.marks.append(("commit", _time.perf_counter()))
            agg._resolve(g)
            with self._cv:
                self._collecting = False
                self._cv.notify_all()

    def flush(self) -> None:
        """Block until every submitted round is committed on every
        shard and its global epoch resolved."""
        for p in self._pipes:
            p.flush()
        with self._cv:
            while (self._q or self._collecting) and self._error is None:
                self._cv.wait()
            if self._error is not None:
                raise RuntimeError(
                    "sharded pipeline failed"
                ) from self._error

    def close(self) -> None:
        err = None
        try:
            self.flush()
        except RuntimeError as e:
            err = e
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None and \
                threading.current_thread() is not self._thread:
            self._thread.join(timeout=30.0)
        close_err = None
        for p in self._pipes:
            try:
                p.close()
            except RuntimeError as e:
                close_err = close_err or e
        if err or close_err:
            raise err or close_err

    @property
    def closed(self) -> bool:
        return self._stop

    def report(self) -> dict:
        """Aggregate of the per-shard pipeline reports (the bench
        ``shard`` sidecar core)."""
        per = [p.report() for p in self._pipes]
        return {
            "shards": len(per),
            "rounds": self._rounds,
            "groups": sum(p["groups"] for p in per),
            "coalesced_rounds": sum(p["coalesced_rounds"] for p in per),
            "max_group": max((p["max_group"] for p in per), default=0),
            "backpressure_waits": sum(
                p["backpressure_waits"] for p in per
            ),
            "stage_s": round(sum(p["stage_s"] for p in per), 3),
            "commit_s": round(sum(p["commit_s"] for p in per), 3),
            "overlap_s": round(sum(p["overlap_s"] for p in per), 3),
        }


# ---------------------------------------------------------------------------
# the sharded server
# ---------------------------------------------------------------------------


class ShardedResidentServer:
    """One logical resident server over N doc-axis shards.

    ``ShardedResidentServer(family, n_docs, shards=|mesh=, **caps)``:
    ``shards`` defaults to ``LORO_SHARDS`` (typed ConfigError on a bad
    value) and then to one shard per doc-axis device row of ``mesh``
    (the ambient ``make_mesh()`` when omitted); the shard count must
    divide the mesh's doc axis.  Capacity kwargs apply per shard.
    ``doc_keys`` are the rendezvous placement keys (default: the doc
    index as a string; pass stable names — e.g. stringified container
    ids — when the fleet will be resized, so placement survives the
    resize).  ``spare_slots`` is the per-shard migration headroom.

    The serving surface matches ResidentServer — ``ingest`` /
    ``ingest_coalesced`` / ``pipeline`` / reads / acks / ``compact`` /
    ``checkpoint``/``restore`` / ``subscribe_epochs`` /
    ``seed_mirror_engine`` — with epochs on the fleet-global clock, so
    ``sync.SyncServer.over(...)`` fronts it unchanged.  With
    ``durable_dir`` each shard journals under ``shard-NN/`` and a
    ``sharding.json`` manifest records placement;
    ``persist.recover_sharded_server`` reopens every shard
    independently after a crash."""

    def __init__(self, family: str, n_docs: int, shards: Optional[int] = None,
                 mesh=None, doc_keys: Optional[Sequence[str]] = None,
                 spare_slots: int = 1, supervisors=None,
                 auto_grow: bool = True, host_fallback: bool = True,
                 auto_checkpoint: bool = True,
                 durable_dir: Optional[str] = None, durable_fsync=True,
                 fsync_window: int = 8, hot_slots: Optional[int] = None,
                 **caps):
        from ..resilience import DeviceSupervisor

        mesh = mesh if mesh is not None else make_mesh()
        n_shards = _resolve_shards(shards, mesh)
        self.family = family
        self.n_docs = n_docs
        self.mesh = mesh
        self.meshes = shard_meshes(mesh, n_shards)  # typed ConfigError
        self.n_shards = n_shards
        self.placement = ShardPlacement(
            n_docs, n_shards, keys=doc_keys, spare_slots=spare_slots
        )
        if supervisors is not None and len(supervisors) != n_shards:
            raise ValueError(
                f"supervisors has {len(supervisors)} entries for "
                f"{n_shards} shards"
            )
        self.supervisors = (
            list(supervisors) if supervisors is not None
            else [DeviceSupervisor() for _ in range(n_shards)]
        )
        self._durable_dir = durable_dir
        self._host_fallback_flag = host_fallback
        self.hot_slots = hot_slots
        self.shards: List[ResidentServer] = []
        try:
            for s in range(n_shards):
                kw = dict(caps)
                if durable_dir is not None:
                    kw["durable_dir"] = os.path.join(
                        durable_dir, f"shard-{s:02d}"
                    )
                    kw["durable_fsync"] = durable_fsync
                    kw["fsync_window"] = fsync_window
                if hot_slots is not None:
                    # tiered residency per shard (docs/RESIDENCY.md):
                    # each shard manages its own hot set over its slice
                    # of the doc space — eviction never crosses shards.
                    # The budget is per shard, clamped to the shard's
                    # width (spares included, so migration landings can
                    # always go hot).
                    kw["hot_slots"] = min(
                        int(hot_slots), self.placement.widths[s]
                    )
                self.shards.append(ResidentServer(
                    family, self.placement.widths[s], mesh=self.meshes[s],
                    auto_grow=auto_grow, supervisor=self.supervisors[s],
                    host_fallback=host_fallback,
                    auto_checkpoint=auto_checkpoint,
                    # deep anchors keep per-doc history exportable for
                    # live migration (docs/SHARDING.md)
                    mirror_anchor="deep" if host_fallback else True,
                    **kw,
                ))
        except BaseException:
            for srv in self.shards:
                try:
                    srv.close()
                except Exception:  # tpulint: disable=LT-EXC(best-effort shard close while the constructor error propagates)
                    pass
            raise
        self._init_runtime(cid=None, global_epoch=0,
                           emaps=[_EpochMap() for _ in range(n_shards)])
        if durable_dir is not None:
            self._write_manifest()

    def _init_runtime(self, cid, global_epoch: int, emaps) -> None:
        self._route_lock = named_rlock("sharded.route")
        self._epoch_lock = named_lock("sharded.epoch")
        self._emaps = emaps
        self._global_epoch = global_epoch
        self._epoch_subs: List = []
        self._pipeline = None
        self._cid = cid
        self.last_poison_docs: List[int] = []
        obs.gauge("shard.count", "shards in the resident fleet").set(
            self.n_shards, family=self.family
        )
        for s in range(self.n_shards):
            obs.gauge("shard.docs", "docs placed on the shard").set(
                len(self.placement.docs_of(s)),
                family=self.family, shard=str(s),
            )

    # -- routing -------------------------------------------------------
    def _split(self, per_doc_updates: Sequence) -> List[list]:
        """One global round → per-shard local rounds (every shard gets
        a round, possibly all-None: the lockstep epoch contract)."""
        if len(per_doc_updates) > self.n_docs:
            raise ValueError(
                f"round has {len(per_doc_updates)} entries for "
                f"{self.n_docs} docs"
            )
        parts = [[None] * w for w in self.placement.widths]
        for g, u in enumerate(per_doc_updates):
            if u is None:
                continue
            s, l = self.placement.place(g)
            parts[s][l] = u
        return parts

    def _tick_shard_rounds(self, parts: List[list],
                           launches: bool = False) -> None:
        for s, part in enumerate(parts):
            if any(u is not None for u in part):
                obs.counter(
                    "shard.rounds_total",
                    "ingest rounds carrying payloads for the shard",
                ).inc(family=self.family, shard=str(s))
                heat_acct.tick_shard(s, "ingest", of=self.n_shards)
                if launches:
                    heat_acct.tick_shard(s, "launch", of=self.n_shards)

    def _globals_of(self, shard: int, locals_: Sequence[int]) -> List[int]:
        back = {
            self.placement.slot_of[g]: g
            for g in self.placement.docs_of(shard)
        }
        return [back[l] for l in locals_ if l in back]

    # -- ingest --------------------------------------------------------
    def ingest(self, per_doc_updates: Sequence, cid=None) -> int:
        """Feed one fleet round: slices route to their shards by
        placement, every shard's epoch clock ticks, and the returned
        fleet-global epoch is what clients ack."""
        with self._route_lock:
            self._drain_pipeline()
            if cid is not None:
                self._cid = cid
            parts = self._split(list(per_doc_updates))
            self._tick_shard_rounds(parts, launches=True)
            eps = []
            poison: List[int] = []
            for s, srv in enumerate(self.shards):
                eps.append(srv.ingest(parts[s], cid))
                if srv.last_poison_docs:
                    poison.extend(
                        self._globals_of(s, srv.last_poison_docs)
                    )
            self.last_poison_docs = poison
            return self._commit_global(eps)

    def ingest_coalesced(self, rounds: Sequence[Sequence], cid=None) -> List[int]:
        """Apply several rounds as one coalesced group per shard (one
        device launch per shard per group).  Returns one fleet-global
        epoch per round, in order."""
        rounds = [list(r) for r in rounds]
        if not rounds:
            return []
        with self._route_lock:
            self._drain_pipeline()
            if cid is not None:
                self._cid = cid
            split_rounds = []
            for r in rounds:
                parts = self._split(r)
                self._tick_shard_rounds(parts)
                split_rounds.append(parts)
            # one device launch per shard per coalesced GROUP
            for s in range(self.n_shards):
                if any(
                    any(u is not None for u in split_rounds[j][s])
                    for j in range(len(rounds))
                ):
                    heat_acct.tick_shard(s, "launch", of=self.n_shards)
            self.last_poison_docs = []
            per_shard = []
            for s, srv in enumerate(self.shards):
                per_shard.append(srv.ingest_coalesced(
                    [split_rounds[j][s] for j in range(len(rounds))], cid
                ))
                if srv.last_poison_docs:
                    self.last_poison_docs.extend(
                        self._globals_of(s, srv.last_poison_docs)
                    )
            out = []
            for j in range(len(rounds)):
                out.append(self._commit_global(
                    [per_shard[s][j] for s in range(self.n_shards)]
                ))
            return out

    def _commit_global(self, eps: List[int]) -> int:
        with self._epoch_lock:
            self._global_epoch += 1
            g = self._global_epoch
            for s, e in enumerate(eps):
                self._emaps[s].note(g, e)
        self._notify_epoch(g)
        degraded = self.degraded_shards()
        obs.gauge(
            "shard.degraded_shards", "shards degraded to their host mirror"
        ).set(len(degraded), family=self.family)
        for s in degraded:
            heat_acct.tick_shard(s, "degradation", of=self.n_shards)
        return g

    # -- epoch-commit subscription (sync fan-out) ----------------------
    def subscribe_epochs(self, cb):
        """Register ``cb(global_epoch)``: fires once per fleet round,
        after EVERY shard has committed it (same visibility contract as
        ``ResidentServer.subscribe_epochs``)."""
        self._epoch_subs.append(cb)
        return lambda: self._epoch_subs.remove(cb)

    def _notify_epoch(self, epoch: int) -> None:
        for cb in list(self._epoch_subs):
            try:
                cb(epoch)
            except Exception:  # tpulint: disable=LT-EXC(subscriber isolation: a broken epoch subscriber must never poison ingest; counted below)
                obs.counter(
                    "server.epoch_sub_errors_total",
                    "epoch-commit subscriber callbacks that raised",
                ).inc(family=self.family)

    # -- pipeline ------------------------------------------------------
    def pipeline(self, cid=None, coalesce: int = 4, depth: int = 2):
        """Attach per-shard PipelinedIngest executors behind one
        submit() (see ShardedPipeline)."""
        if self._pipeline is not None and not self._pipeline.closed:
            raise RuntimeError(
                "server already has a live pipeline — close() it first"
            )
        if cid is not None:
            self._cid = cid
        self._pipeline = ShardedPipeline(
            self, cid=cid, coalesce=coalesce, depth=depth
        )
        return self._pipeline

    def _drain_pipeline(self) -> None:
        if self._pipeline is not None and not self._pipeline.closed:
            self._pipeline.flush()

    # -- reads (placement-merged across shards) ------------------------
    def _read(self, name: str, *args):
        outs = [getattr(srv, name)(*args) for srv in self.shards]
        merged = [None] * self.n_docs
        for g in range(self.n_docs):
            s, l = self.placement.place(g)
            merged[g] = outs[s][l]
        return merged

    def texts(self) -> List[str]:
        return self._read("texts")

    def richtexts(self) -> List[list]:
        return self._read("richtexts")

    def values(self) -> List[list]:
        return self._read("values")

    def value_maps(self):
        return self._read("value_maps")

    def root_value_maps(self, name: str):
        return self._read("root_value_maps", name)

    def parent_maps(self) -> List[dict]:
        return self._read("parent_maps")

    def children_maps(self) -> List[dict]:
        return self._read("children_maps")

    def value_lists(self) -> List[list]:
        return self._read("value_lists")

    @property
    def epoch(self) -> int:
        return self._global_epoch

    # -- degradation (per shard) ---------------------------------------
    @property
    def degraded(self) -> bool:
        return any(srv.degraded for srv in self.shards)

    def degraded_shards(self) -> List[int]:
        return [s for s, srv in enumerate(self.shards) if srv.degraded]

    def recover(self, shard: Optional[int] = None) -> bool:
        """Recover the given shard (or every degraded one) back onto
        its device batch; True when nothing is left degraded."""
        targets = [shard] if shard is not None else self.degraded_shards()
        ok = True
        for s in targets:
            if self.shards[s].degraded:
                ok = self.shards[s].recover(mesh=self.meshes[s]) and ok
        obs.gauge(
            "shard.degraded_shards", "shards degraded to their host mirror"
        ).set(len(self.degraded_shards()), family=self.family)
        return ok

    # -- acks / compaction (global clock in, shard clocks inside) ------
    def register_replica(self, di: int, replica: str) -> None:
        s, l = self.placement.place(di)
        self.shards[s].register_replica(l, replica)

    def ack(self, di: int, replica: str, epoch: int) -> None:
        s, l = self.placement.place(di)
        self.shards[s].ack(l, replica, self._emaps[s].to_shard(epoch))

    def drop_replica(self, di: int, replica: str) -> None:
        s, l = self.placement.place(di)
        self.shards[s].drop_replica(l, replica)

    def stable_epoch(self, di: int) -> int:
        s, l = self.placement.place(di)
        return self._emaps[s].to_global(self.shards[s].stable_epoch(l))

    def compact(self) -> int:
        self._drain_pipeline()
        return sum(srv.compact() for srv in self.shards)

    # -- durability ----------------------------------------------------
    @property
    def _durable(self):
        logs = [srv._durable for srv in self.shards]
        return logs if any(lg is not None for lg in logs) else None

    @property
    def durable_epoch(self) -> int:
        """Fleet durable watermark: the min over shards of each
        shard's acked-epoch watermark translated to the global clock —
        a crash loses no round at or below it on ANY shard."""
        if self._durable is None:
            return 0
        return min(
            self._emaps[s].to_global(srv.durable_epoch)
            for s, srv in enumerate(self.shards)
        )

    def flush_durable(self) -> int:
        return sum(srv.flush_durable() for srv in self.shards)

    def _manifest(self) -> dict:
        with self._epoch_lock:
            return {
                "version": MANIFEST_VERSION,
                "family": self.family,
                "n_docs": self.n_docs,
                "shards": self.n_shards,
                "spare_slots": self.placement.spare_slots,
                "keys": self.placement.keys,
                "shard_of": list(self.placement.shard_of),
                "slot_of": list(self.placement.slot_of),
                "widths": list(self.placement.widths),
                "free": [list(f) for f in self.placement.free],
                "global_epoch": self._global_epoch,
                "emaps": [m.encode() for m in self._emaps],
                # informational (recovery reads per-shard WAL meta caps;
                # inspect and operators read this)
                "hot_slots": self.hot_slots,
            }

    def _write_manifest(self) -> None:
        path = os.path.join(self._durable_dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._manifest(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(self._durable_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    # -- checkpoint / restore ------------------------------------------
    def checkpoint(self) -> bytes:
        """Per-shard checkpoints (each lands on its own ladder when
        durable) + the placement manifest, as one LTKV blob.  Holds
        the routing lock: a round landing between two shards'
        checkpoints would tear the fleet blob (shard A pre-round,
        shard B post-round — a state that never existed)."""
        from ..storage import MemKvStore

        with self._route_lock:
            self._drain_pipeline()
            kv = MemKvStore()
            kv.set(b"manifest",
                   json.dumps(self._manifest()).encode("utf-8"))
            for s, srv in enumerate(self.shards):
                kv.set(f"shard-{s:02d}".encode(), srv.checkpoint())
            if self._durable_dir is not None:
                self._write_manifest()
            return kv.export_all()

    @classmethod
    def restore(cls, data: bytes, mesh=None) -> "ShardedResidentServer":
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        mb = kv.get(b"manifest")
        if mb is None:
            raise DecodeError("ShardedResidentServer state: missing manifest")
        manifest = json.loads(mb.decode("utf-8"))
        if manifest.get("version", 0) > MANIFEST_VERSION:
            raise DecodeError(
                f"shard manifest v{manifest.get('version')} too new"
            )
        n_shards = int(manifest["shards"])
        mesh = mesh if mesh is not None else make_mesh()
        meshes = shard_meshes(mesh, n_shards)
        shard_srvs = []
        for s in range(n_shards):
            blob = kv.get(f"shard-{s:02d}".encode())
            if blob is None:
                raise DecodeError(
                    f"ShardedResidentServer state: missing shard {s}"
                )
            shard_srvs.append(ResidentServer.restore(blob, mesh=meshes[s]))
        return cls._assemble(manifest, shard_srvs, mesh, meshes,
                             durable_dir=None)

    @classmethod
    def _assemble(cls, manifest: dict, shard_srvs: List[ResidentServer],
                  mesh, meshes, durable_dir: Optional[str]
                  ) -> "ShardedResidentServer":
        """Shared tail of restore() and recover_sharded_server():
        wire recovered per-shard servers back into one fleet.  Shards
        may come back at different epochs (independent WAL tails): the
        global clock resumes at the furthest shard and each epoch map
        gets a breakpoint, so translations stay conservative."""
        from ..resilience import DeviceSupervisor

        self = cls.__new__(cls)
        self.family = manifest["family"]
        self.n_docs = int(manifest["n_docs"])
        self.n_shards = int(manifest["shards"])
        self.mesh = mesh
        self.meshes = meshes
        self.placement = ShardPlacement.from_manifest(manifest)
        self.shards = shard_srvs
        self._durable_dir = durable_dir
        self.hot_slots = manifest.get("hot_slots")
        self._host_fallback_flag = all(
            srv._host_fallback for srv in shard_srvs
        )
        self.supervisors = []
        for srv in shard_srvs:
            if srv._supervisor is None:
                srv._supervisor = DeviceSupervisor()
            self.supervisors.append(srv._supervisor)
        # stale-manifest guard: a crash between a migration round's
        # WAL fsync and the manifest write leaves the target's spare
        # slot populated while the recovered manifest still lists it
        # free.  Retire any "free" slot the recovered journal tail or
        # anchor shows content in, so a later migrate() can never land
        # a second doc on top of it (the half-migrated doc itself keeps
        # serving from its source slot — the pre-move placement).
        for s, srv in enumerate(shard_srvs):
            if not self.placement.free[s]:
                continue
            occupied = set()
            for _e, ups, _c in (getattr(srv, "_history", None) or ()):
                for l, u in enumerate(ups):
                    if u is not None:
                        occupied.add(l)
            anchor = getattr(srv, "_anchor", None)
            if anchor is not None:
                for l, blob in enumerate(anchor.doc_blobs):
                    if blob:
                        occupied.add(l)
            self.placement.free[s] = [
                l for l in self.placement.free[s] if l not in occupied
            ]
        g_m = int(manifest.get("global_epoch", 0))
        emaps = [_EpochMap.decode(b) for b in manifest.get(
            "emaps", [[[0, 0]]] * self.n_shards
        )]
        # global rounds since the manifest = journal-tail ROUNDS past
        # the manifest-time shard epoch, NOT the epoch delta: a round
        # with deletes ticks a batch clock twice (scatter + tombstone
        # launch), so epochs overcount rounds.  Shards may disagree
        # (independent fsync tails) — the furthest shard defines how
        # many global rounds were issued.
        deltas = []
        for s, srv in enumerate(shard_srvs):
            floor = emaps[s].to_shard(g_m)
            hist = getattr(srv, "_history", None)
            if srv._host_fallback and hist is not None:
                delta = sum(1 for rec in hist if rec[0] > floor)
            else:
                delta = max(0, srv.epoch - floor)
            rung = getattr(srv, "last_recovery", None)
            if rung is not None and rung.checkpoint_epoch > floor:
                # the manifest predates the restored rung (a crash
                # inside checkpoint(), between the per-shard rungs and
                # the manifest write): the journal tail counts only
                # rounds AFTER the rung, so take the epoch delta — an
                # OVERestimate of rounds (clocks tick >= 1 per round).
                # An inflated global clock is never reused; an
                # undercounted one would re-issue epochs clients
                # already acked and let translated floors lead.
                delta = max(delta, srv.epoch - floor)
            deltas.append(delta)
        g = g_m + max([0] + deltas)
        for s, srv in enumerate(shard_srvs):
            emaps[s].note(g, srv.epoch)
        cid = next(
            (srv._cid for srv in shard_srvs if srv._cid is not None), None
        )
        self._init_runtime(cid=cid, global_epoch=g, emaps=emaps)
        return self

    # -- host mirror (sync oracle / degradation seed) -------------------
    @property
    def _host_fallback(self) -> bool:
        return self._host_fallback_flag

    @property
    def _history_complete(self) -> bool:
        return all(srv._history_complete for srv in self.shards)

    @property
    def _anchor(self):
        # the sync front-end only tests truthiness (can this server
        # seed a mirror without history since birth?)
        return self.shards[0]._anchor

    def seed_mirror_engine(self):
        """A fleet-wide ``hostpath.HostEngine`` at the current applied
        state: per-shard mirror engines grafted back into global doc
        order (the sync front-end's delta-export oracle)."""
        from ..resilience.hostpath import HostEngine

        subs = [srv.seed_mirror_engine() for srv in self.shards]
        eng = HostEngine(self.family, self.n_docs)
        eng._cid = self._cid if self._cid is not None else subs[0]._cid
        eng.epoch = self._global_epoch
        for g in range(self.n_docs):
            s, l = self.placement.place(g)
            eng.docs[g] = subs[s].docs[l]
            eng._seen_cids[g] = subs[s]._seen_cids[l]
        return eng

    # -- live migration -------------------------------------------------
    def migrate(self, di: int, to_shard: int) -> int:
        """Move doc ``di`` onto ``to_shard`` live: drain the pipeline,
        re-export the doc's full history from the source shard's
        (deep-anchored) mirror, flip the placement, and land the
        history in the target's spare slot through ONE ordinary fleet
        round — every other shard sees an empty round, so the global
        epoch stream stays contiguous and a round fed mid-migration
        waits on the routing lock and lands exactly once under the new
        placement.  Replicas carry over with their floors reset (the
        migrated rows are all dated at the migration epoch, so nothing
        compacts until clients ack past the move).  Returns the
        migration round's global epoch."""
        from ..doc import strip_envelope

        with self._route_lock:
            if not (0 <= di < self.n_docs):
                raise ValueError(
                    f"doc index {di} out of range [0, {self.n_docs})"
                )
            if not (0 <= to_shard < self.n_shards):
                raise ValueError(
                    f"target shard {to_shard} out of range "
                    f"[0, {self.n_shards})"
                )
            src, src_slot = self.placement.place(di)
            if src == to_shard:
                return self._global_epoch
            if not self._host_fallback_flag:
                raise ShardingError(
                    "migration needs host_fallback=True shards (the "
                    "doc's history is re-exported from the source "
                    "shard's mirror)"
                )
            if self.shards[src].degraded or self.shards[to_shard].degraded:
                raise ShardingError(
                    f"cannot migrate doc {di}: shard "
                    f"{src if self.shards[src].degraded else to_shard} "
                    "is degraded — recover() it first"
                )
            if self.family not in ("map", "counter") and self._cid is None:
                raise ShardingError(
                    "migration needs the served container id — ingest "
                    "at least one round (with cid) first"
                )
            self._drain_pipeline()
            # full-history export from the source mirror (deep anchors
            # keep it exportable across checkpoints)
            eng = self.shards[src].seed_mirror_engine()
            doc = eng.docs[src_slot]
            payload = None
            if len(doc.oplog_vv()):
                try:
                    payload = strip_envelope(doc.export_updates())
                except LoroError as e:
                    raise ShardingError(
                        f"doc {di}: source mirror cannot export full "
                        f"history ({e}) — the shard was restored from a "
                        "non-deep anchor; rebuild it from a fleet "
                        "checkpoint to migrate"
                    ) from e
            replicas = list(self.shards[src].acks[src_slot])
            new_slot = self.placement.move(di, to_shard)
            # the migration round: ONE ordinary fleet round whose only
            # payload is the doc's history at its new slot
            ups: List = [None] * self.n_docs
            ups[di] = payload
            parts = self._split(ups)
            self._tick_shard_rounds(parts)
            eps: List[int] = []
            try:
                for s, srv in enumerate(self.shards):
                    eps.append(srv.ingest(parts[s], self._cid))
            except BaseException:
                # roll the placement back: the doc must keep serving
                # from its (untouched) source slot, never point at a
                # slot the round may not have populated.  The spare
                # slot is re-freed only if the target shard never
                # applied its slice — a populated orphan slot is
                # retired, the same rule the recovery guard enforces
                # (a free-but-populated slot could absorb a second
                # doc).  Shard clocks that already ticked re-sync
                # through the epoch maps at the next commit.
                target_done = (
                    len(eps) > to_shard
                    and new_slot not in
                    self.shards[to_shard].last_poison_docs
                )
                self.placement.shard_of[di] = src
                self.placement.slot_of[di] = src_slot
                if not target_done:
                    self.placement.free[to_shard].insert(0, new_slot)
                raise
            g = self._commit_global(eps)
            if new_slot in self.shards[to_shard].last_poison_docs:
                # the history payload was poison-skipped: NOTHING
                # landed in the spare slot, so reclaim it, point the
                # doc back at its (untouched) source slot and surface
                # typed — never serve a silently-empty doc
                self.placement.shard_of[di] = src
                self.placement.slot_of[di] = src_slot
                self.placement.free[to_shard].insert(0, new_slot)
                raise ShardingError(
                    f"doc {di}: migration round was poison-skipped on "
                    f"shard {to_shard} — placement rolled back, the "
                    "doc still serves from its source shard"
                )
            # replica set carries over; floors restart at 0 (every
            # migrated row/tombstone is dated at the migration epoch,
            # so nothing reclaims until clients ack past the move)
            s_new, l_new = self.placement.place(di)
            for rep in replicas:
                self.shards[s_new].register_replica(l_new, rep)
            self.shards[src].acks[src_slot] = {}
            obs.counter(
                "shard.migrations_total", "live doc migrations"
            ).inc(family=self.family)
            for s in (src, to_shard):
                obs.gauge("shard.docs", "docs placed on the shard").set(
                    len(self.placement.docs_of(s)),
                    family=self.family, shard=str(s),
                )
            if self._durable_dir is not None:
                # fsync BEFORE publishing the new placement: in group
                # fsync mode the migration round is only appended so
                # far — a manifest that durably pointed the doc at a
                # never-fsynced slot would serve it empty after a
                # crash.  (The opposite ordering — round durable,
                # manifest lost — is the recovery guard's case: the
                # doc keeps serving from its source slot.)
                self.flush_durable()
                self._write_manifest()
            return g

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        err = None
        if self._pipeline is not None and not self._pipeline.closed:
            try:
                self._pipeline.close()
            except RuntimeError as e:
                err = e
        for srv in self.shards:
            try:
                srv.close()
            except PersistError as e:
                err = err or e
        if err is not None:
            raise err


# ---------------------------------------------------------------------------
# durable recovery
# ---------------------------------------------------------------------------


def load_manifest(durable_dir: str) -> Optional[dict]:
    """The ``sharding.json`` manifest of a sharded durable dir, or
    None when the directory is not sharded."""
    path = os.path.join(durable_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, "r") as f:
        m = json.load(f)
    if m.get("version", 0) > MANIFEST_VERSION:
        raise PersistError(
            f"{durable_dir}: shard manifest v{m.get('version')} newer "
            "than supported"
        )
    return m


def recover_sharded_server(durable_dir: str, mesh=None,
                           fsync: bool = True) -> ShardedResidentServer:
    """Reopen a sharded durable directory after a crash: every shard
    recovers independently (``persist.recover_server`` per
    ``shard-NN/`` — newest valid rung + bounded WAL replay), then the
    fleet reassembles from the ``sharding.json`` manifest.  Shards may
    recover at different epochs (independent fsync tails); the global
    clock resumes at the furthest shard and the fleet
    ``durable_epoch`` stays the min over shards."""
    from ..persist import recover_server

    manifest = load_manifest(durable_dir)
    if manifest is None:
        raise PersistError(
            f"{durable_dir}: no {MANIFEST_NAME} — not a sharded durable "
            "dir (use persist.recover_server for single-server dirs)"
        )
    n_shards = int(manifest["shards"])
    mesh = mesh if mesh is not None else make_mesh()
    meshes = shard_meshes(mesh, n_shards)
    shard_srvs: List[ResidentServer] = []
    try:
        for s in range(n_shards):
            sub = os.path.join(durable_dir, f"shard-{s:02d}")
            if not os.path.isdir(sub):
                raise PersistError(
                    f"{durable_dir}: manifest names {n_shards} shards "
                    f"but shard-{s:02d}/ is missing"
                )
            shard_srvs.append(
                recover_server(sub, mesh=meshes[s], fsync=fsync)
            )
    except BaseException:
        for srv in shard_srvs:
            try:
                srv.close()
            except Exception:  # tpulint: disable=LT-EXC(best-effort shard close while the recovery error propagates)
                pass
        raise
    srv = ShardedResidentServer._assemble(
        manifest, shard_srvs, mesh, meshes, durable_dir=durable_dir
    )
    obs.counter("shard.recoveries_total", "sharded fleet reopens").inc(
        family=srv.family
    )
    return srv
