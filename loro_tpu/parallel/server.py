"""Batteries-included resident sync server: one device batch + the
ack bookkeeping that makes its lifecycle (grow/compact) safe to use.

The resident batches expose a precise but easy-to-misuse contract:
``compact(stable_epochs)`` may only receive epochs that EVERY replica
of a doc has acknowledged integrating — passing a too-new epoch can
reclaim a tombstone some replica still references (see
DeviceDocBatch.compact).  This wrapper owns that bookkeeping:

- ``ingest(per_doc_updates)`` feeds a sync round into the batch and
  returns the epoch to hand to clients with the round's fan-out;
- ``ack(di, replica, epoch)`` records a replica's acknowledgment;
- ``compact()`` reclaims with each doc's stability floor =
  min over its registered replicas' acked epochs (docs with no
  registered replicas never compact — safe default);
- ``checkpoint()/restore()`` round-trip batch + acks through LTKV
  bytes, so a restarted server resumes with its compaction floors.

Resilience (docs/RESILIENCE.md): every device append routes through
the DeviceSupervisor; the server auto-checkpoints before its first
risky (first-compile) launch; a data error in one round isolates to
the offending doc (host-decode fallback, then poison-skip with a
typed record); a supervisor-declared DeviceFailure transparently
degrades the epoch to the host ``models/`` engine (byte-identical by
the differential-fuzz contract) and ``recover()`` replays the round
journal back onto a fresh device batch.

Reference analog: the two-round sync loop of the reference's README
(crates/loro/README) plus its shallow-snapshot floor
(crates/loro-internal/src/encoding/shallow_snapshot.rs:16-40), packaged
server-side at fleet scale.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Sequence

from ..errors import DeviceFailure, ResilienceError
from ..obs import flight
from ..obs import metrics as obs
from ..resilience import faultinject, get_supervisor
from ..utils import tracing

faultinject.register_site(
    "poison_doc", "ResidentServer.ingest: corrupt one doc's payload in "
    "a round (per-doc poison isolation)")
from .fleet import (
    DeviceCounterBatch,
    DeviceDocBatch,
    DeviceMapBatch,
    DeviceMovableBatch,
    DeviceTreeBatch,
)

# ONE table per family: (batch class for restore, constructor) — both
# checkpoint/restore and __init__ resolve from it, so they cannot drift
_FAMILIES = {
    "text": (DeviceDocBatch, lambda n, mesh, auto_grow, kw: DeviceDocBatch(
        n, kw.get("capacity", 1 << 14), mesh=mesh, auto_grow=auto_grow
    )),
    "list": (DeviceDocBatch, lambda n, mesh, auto_grow, kw: DeviceDocBatch(
        n, kw.get("capacity", 1 << 14), mesh=mesh, as_text=False,
        auto_grow=auto_grow,
    )),
    "map": (DeviceMapBatch, lambda n, mesh, auto_grow, kw: DeviceMapBatch(
        n, kw.get("slot_capacity", 1 << 10), mesh=mesh, auto_grow=auto_grow
    )),
    "tree": (DeviceTreeBatch, lambda n, mesh, auto_grow, kw: DeviceTreeBatch(
        n, kw.get("move_capacity", 1 << 12), kw.get("node_capacity", 1 << 10),
        mesh=mesh, auto_grow=auto_grow,
    )),
    "movable": (DeviceMovableBatch, lambda n, mesh, auto_grow, kw: DeviceMovableBatch(
        n, kw.get("capacity", 1 << 13), kw.get("elem_capacity", 1 << 10),
        mesh=mesh, auto_grow=auto_grow,
    )),
    "counter": (DeviceCounterBatch, lambda n, mesh, auto_grow, kw: DeviceCounterBatch(
        n, kw.get("slot_capacity", 1 << 6), mesh=mesh, auto_grow=auto_grow
    )),
}
_COMPACTABLE = ("text", "list", "tree", "movable")

# host-side data errors: poison payloads / bad change lists.  These
# route to the per-doc isolation pass — anything else escaping an
# append is a config/logic error that must surface to the caller.
import struct as _struct  # noqa: E402  (stdlib, for _struct.error)

_DATA_ERRORS = (ValueError, TypeError, KeyError, IndexError, _struct.error)


class _StagedGroup:
    """Handle between ``ingest_stage`` and ``ingest_commit``: the
    normalized rounds, their stage-time epochs, and the detached device
    work.  ``mode``: "group" (normal), "serial" (server was degraded at
    stage time), "done" (stage already produced the final epochs, e.g.
    the auto-checkpoint launch degraded)."""

    __slots__ = ("mode", "rounds", "staged", "cid", "epochs", "pending",
                 "error_index")

    def __init__(self, rounds, cid):
        self.mode = "group"
        self.rounds = rounds
        self.staged: List[tuple] = []
        self.cid = cid
        self.epochs: List[int] = []
        self.pending = None
        self.error_index: Optional[int] = None


class ResidentServer:
    """One resident device batch + per-doc replica-ack bookkeeping.

    ``family``: "text" | "list" | "map" | "tree" | "movable" |
    "counter".  Capacity knobs pass through (capacity, slot_capacity,
    move_capacity, node_capacity, elem_capacity).  The underlying batch
    is ``self.batch`` — every read API (texts/richtexts/values/
    value_lists/parent_maps/...) is available directly on it, or
    through the same-named delegating methods here, which keep working
    when the server is degraded to the host engine.

    ``host_fallback=True`` keeps a round journal (frozen as encoded
    wire bytes) so a supervisor-declared device failure can rebuild
    the state host-side.  The journal is BOUNDED by checkpoints: every
    ``checkpoint()`` folds the journaled rounds into a per-doc
    shallow-snapshot *mirror anchor* (persist.MirrorAnchor) and drops
    rounds at/under the checkpoint epoch, so journal length stays
    O(rounds since the last checkpoint) and both the host mirror and
    ``recover()`` re-anchor on the checkpoint instead of on birth.
    Memory-constrained deployments pass ``host_fallback=False``
    (degradation then surfaces as a typed DeviceFailure instead).
    ``auto_checkpoint=True`` snapshots the server into
    ``last_checkpoint`` right before the first risky (first-compile)
    device launch.

    ``durable_dir=`` makes the journal crash-durable: rounds append to
    a segmented WAL (``loro_tpu/persist/``), checkpoints land on a
    retention ladder and rotate/prune the WAL segments;
    ``persist.recover_server(durable_dir)`` reopens after a crash with
    bounded replay (docs/PERSISTENCE.md).
    """

    # wall clock for the WAL round stamps (replication-lag attribution);
    # a class-level reference so tests can inject a fake
    _wall = staticmethod(_time.time)

    def __init__(self, family: str, n_docs: int, mesh=None,
                 auto_grow: bool = True, supervisor=None,
                 host_fallback: bool = True, auto_checkpoint: bool = True,
                 durable_dir: Optional[str] = None,
                 durable_fsync=True,
                 fsync_window: int = 8,
                 mirror_anchor=True,
                 hot_slots: Optional[int] = None,
                 **caps):
        if family not in _FAMILIES:
            raise ValueError(f"unknown family {family!r} (one of {sorted(_FAMILIES)})")
        if hot_slots is not None:
            # tiered residency (parallel/residency.py, docs/RESIDENCY.md):
            # the device batch holds only the hot set; warm/cold tiers
            # live on the anchor+journal plane, so both are required —
            # and the anchor must be DEEP (history-complete) because a
            # revive re-exports the doc's full history for the landing
            from ..errors import ResidencyError

            if not host_fallback:
                raise ResidencyError(
                    "tiered residency (hot_slots=) needs host_fallback="
                    "True — the warm/cold tiers are the mirror-anchor + "
                    "journal plane"
                )
            if not mirror_anchor:
                raise ResidencyError(
                    "tiered residency (hot_slots=) needs a mirror anchor"
                )
            mirror_anchor = "deep"
            caps = dict(caps)
            caps["hot_slots"] = int(hot_slots)
        self.family = family
        self.batch = self._build_batch(family, n_docs, mesh, auto_grow, caps)
        self.n_docs = n_docs
        # acks[di][replica] = newest epoch that replica confirmed
        self.acks: List[Dict[str, int]] = [dict() for _ in range(n_docs)]
        self._compacted_at: List[int] = [0] * n_docs
        durable = None
        if durable_dir is not None:
            from ..errors import PersistError
            from ..persist import DurableLog, WalMeta

            durable = DurableLog(durable_dir, fsync=durable_fsync)
            try:
                if durable.in_use():
                    raise PersistError(
                        f"{durable_dir}: directory already holds journaled "
                        "rounds or checkpoints — use persist.recover_server()"
                        "/open_server() instead of constructing a fresh "
                        "server over them"
                    )
                durable.ensure_meta(WalMeta(
                    family=family, n_docs=n_docs, caps=dict(caps),
                    auto_grow=auto_grow, host_fallback=host_fallback,
                    fsync_mode=durable.fsync_mode,
                    deep_anchor=(mirror_anchor == "deep"),
                ))
            except BaseException:
                durable.close()  # never leak the active segment handle
                raise
        anchor = None
        if host_fallback and mirror_anchor:
            from ..persist import MirrorAnchor

            # mirror_anchor="deep" folds full snapshots (history kept)
            # instead of StateOnly blobs — the sharded fleet passes it
            # so live doc migration can re-export history (SHARDING.md)
            anchor = MirrorAnchor(family, n_docs,
                                  deep=(mirror_anchor == "deep"))
        self._init_resilience(
            mesh=mesh, auto_grow=auto_grow, caps=dict(caps),
            supervisor=supervisor, host_fallback=host_fallback,
            auto_checkpoint=auto_checkpoint, history_complete=True,
            anchor=anchor, durable=durable, fsync_window=fsync_window,
        )
        self._bind_batch(self.batch)

    # -- batch construction (tiered-aware; parallel/residency.py) -------
    @staticmethod
    def _build_batch(family: str, n_docs: int, mesh, auto_grow, caps):
        """One construction point for the device batch: a ``hot_slots``
        entry in ``caps`` builds a TieredBatch (doc-space window over a
        hot-set device batch) instead of the plain family batch — the
        same caps dict rides the WAL meta and v3 checkpoints, so cold
        recovery and restore rebuild the same shape."""
        hs = (caps or {}).get("hot_slots")
        if hs:
            from .residency import TieredBatch

            return TieredBatch(family, n_docs, hs, mesh, auto_grow, caps)
        return _FAMILIES[family][1](n_docs, mesh, auto_grow, caps)

    @staticmethod
    def _import_batch(family: str, data: bytes, caps, mesh):
        if (caps or {}).get("hot_slots"):
            from .residency import TieredBatch

            return TieredBatch.import_state(data, mesh=mesh)
        return _FAMILIES[family][0].import_state(data, mesh=mesh)

    def _bind_batch(self, batch) -> None:
        """Attach a back-reference on batches that need the server's
        anchor/journal plane (TieredBatch warm/cold mirrors)."""
        b = getattr(batch, "bind", None)
        if b is not None:
            b(self)

    @property
    def residency(self):
        """The ResidencyManager when this server is tiered
        (``hot_slots=``), else None — tier queries, ``report()`` and
        the demotion policy hang off it (docs/RESIDENCY.md)."""
        return getattr(self.batch, "mgr", None)

    def _init_resilience(self, mesh, auto_grow, caps, supervisor,
                         host_fallback, auto_checkpoint,
                         history_complete, anchor=None, durable=None,
                         replay_base=None, ckpt_epoch=0,
                         fsync_window: int = 8) -> None:
        self._mesh = mesh
        self._auto_grow = auto_grow
        self._caps = caps
        self._supervisor = supervisor
        self._host_fallback = host_fallback
        # journal of (epoch, frozen_updates, cid) rounds; the tail
        # since the last checkpoint once one exists (checkpoint() folds
        # older rounds into the mirror anchor and drops them).  With no
        # anchor the journal must be complete since birth to seed a
        # host mirror — a restore()d pre-v3 server has neither, so its
        # degradation surfaces typed instead.
        self._history: List[tuple] = []
        self._history_complete = history_complete
        # shallow-snapshot mirror anchor (persist.MirrorAnchor): the
        # host-mirror base at the last checkpoint epoch
        self._anchor = anchor
        # durable journal (persist.DurableLog) when durable_dir= given
        self._durable = durable
        self._durable_closed = False
        # group commit (docs/PERSISTENCE.md): in "group" fsync mode the
        # WAL defers fsyncs; the server syncs every `fsync_window`
        # journaled rounds and tracks the acked-epoch watermark — the
        # newest epoch a crash is guaranteed not to lose.  The
        # watermark advances to the newest JOURNALED epoch (not
        # self.epoch, which a concurrently-staging pipeline group may
        # already have pushed past what is on disk).
        self._fsync_window = max(1, int(fsync_window))
        self._unsynced_rounds = 0
        self._journaled_epoch = 0
        self._durable_epoch = 0
        # attached PipelinedIngest executor (parallel/pipeline.py):
        # close()/checkpoint() drain it so no staged round is stranded
        self._pipeline = None
        # epoch-commit subscribers (loro_tpu/sync fan-out): called with
        # each newly VISIBLE epoch, on whichever thread committed it
        self._epoch_subs: List = []
        # bounded recover(): batch bytes to re-seed from (the last
        # checkpoint blob) + the visible epoch it covers
        self._replay_base: Optional[bytes] = replay_base
        self._ckpt_epoch = ckpt_epoch
        self.last_recovery = None
        self._degraded = False
        self._host = None
        self._epoch_base = 0
        self._host_rounds = 0
        # visible epoch = batch-internal epoch + offset: a degrade/
        # recover cycle may replay fewer internal epochs than clients
        # already acked (the failed round can commit on device but land
        # in the journal only once), so the offset keeps the VISIBLE
        # epoch monotone across recovery
        self._epoch_offset = 0
        self._cid = None
        self._auto_ckpt_pending = auto_checkpoint
        self.last_checkpoint: Optional[bytes] = None
        self.last_poison_docs: List[int] = []

    @property
    def degraded(self) -> bool:
        return self._degraded

    def _sup(self):
        return self._supervisor if self._supervisor is not None else get_supervisor()

    # -- sync rounds ---------------------------------------------------
    def ingest(self, per_doc_updates: Sequence, cid=None) -> int:
        """Feed one sync round (per-doc update payloads via the native
        path when bytes, else change lists; None = no update) and
        return the epoch clients must ack once they integrate the
        round's fan-out.

        Entries are normalized PER DOC (ADVICE r5 finding 1): a round
        mixing bytes payloads and Change lists decodes the bytes
        entries host-side instead of mis-routing the change lists
        through the payload path (where a TypeError escaped the
        per-doc fallback)."""
        if getattr(self, "_durable_closed", False):
            from ..errors import PersistError

            raise PersistError(
                "durable server is closed — a round applied now could "
                "never be journaled; reopen via persist.recover_server()"
            )
        batch = self.batch
        self.last_poison_docs = []
        per_doc_updates, use_payloads, n_updated = self._normalize_round(
            per_doc_updates, batch
        )
        if self.family not in ("map", "counter") and cid is None:
            # API misuse, not a poison round: surface it before the
            # isolation machinery can misread it as per-doc poison
            raise ValueError(f"{self.family} ingest needs the container id")
        if cid is not None:
            self._cid = cid
        self._tick_round_counters(use_payloads, n_updated)
        if self._degraded:
            # decode EVERYTHING first (per-doc poison -> skip, typed),
            # then apply: a poison doc never half-applies a mirror round
            per_doc_updates = self._decode_bytes_entries(per_doc_updates)
            with obs.histogram(
                "server.epoch_seconds", "ingest wall time per sync round"
            ).time(family=self.family):
                self._host.apply(per_doc_updates, cid)
            self._host_rounds += 1
            self._record_round(per_doc_updates, cid)
            obs.counter("server.degraded_rounds_total").inc(family=self.family)
            return self.epoch
        sup = self._sup()
        if self._auto_ckpt_pending:
            # the FIRST device append compiles the scatter kernels — the
            # riskiest launch of a server's life (a wedge here loses the
            # epoch).  Snapshot first so the round is recoverable via
            # checkpoint()/restore().  The checkpoint itself reads
            # device state, so it is guarded too: a failure HERE is
            # already a device failure and takes the degradation path.
            self._auto_ckpt_pending = False
            try:
                self.last_checkpoint = sup.guard(
                    self.checkpoint, label=f"server.checkpoint.{self.family}"
                )
            except DeviceFailure as e:
                return self._degrade_round(per_doc_updates, cid, e)
            obs.counter("server.auto_checkpoints_total").inc(family=self.family)
        try:
            with obs.histogram(
                "server.epoch_seconds", "ingest wall time per sync round"
            ).time(family=self.family):
                sup.launch(
                    lambda: self._append(batch, per_doc_updates, cid, use_payloads),
                    label=f"server.ingest.{self.family}",
                    retry=False,  # appends donate buffers: never re-run
                    drain=self._drain_fetch,
                )
        except DeviceFailure as e:
            return self._degrade_round(per_doc_updates, cid, e)
        except _DATA_ERRORS:
            # data error (poison payload / bad change list): the
            # columnar walk raises BEFORE any device commit, so
            # re-attempting per doc is safe — isolate the offender
            self._ingest_isolated(per_doc_updates, cid, sup)
            return self.epoch
        except Exception:
            # host-side config/logic error (e.g. capacity exceeded with
            # auto_grow=False): surface it loudly, don't misread it as
            # poison or degrade on it
            obs.counter("server.errors_total").inc(family=self.family)
            raise
        self._record_round(per_doc_updates, cid)
        return self.epoch

    def _normalize_round(self, per_doc_updates, batch):
        """Fault-mangle + route one round (shared by ingest and
        ingest_coalesced): returns ``(updates, use_payloads,
        n_updated)``.  Bytes entries decode host-side when the round is
        mixed or the family lacks a native payload path; an entry that
        won't decode is poison for THAT doc only — skipped with a
        typed record (``last_poison_docs``), never an uncaught error."""
        per_doc_updates = [
            faultinject.mangle("poison_doc", u, doc=di) if u is not None else None
            for di, u in enumerate(per_doc_updates)
        ]
        n_updated = sum(1 for u in per_doc_updates if u is not None)
        obs.gauge("server.queue_depth").set(n_updated, family=self.family)
        has_bytes = any(isinstance(u, (bytes, bytearray))
                        for u in per_doc_updates if u is not None)
        has_changes = any(u is not None and not isinstance(u, (bytes, bytearray))
                          for u in per_doc_updates)
        if has_bytes and (has_changes or not hasattr(batch, "append_payloads")):
            # mixed round, or a family without a native payload path
            # (counter): decode bytes entries host-side per doc
            reason = "mixed_round" if has_changes else "no_payload_path"
            n_decoded = sum(
                1 for u in per_doc_updates if isinstance(u, (bytes, bytearray))
            )
            obs.counter("server.ingest_fallback_total").inc(
                n_decoded, family=self.family, reason=reason
            )
            per_doc_updates = self._decode_bytes_entries(per_doc_updates)
            use_payloads = False
        else:
            use_payloads = has_bytes
        return per_doc_updates, use_payloads, n_updated

    def _tick_round_counters(self, use_payloads: bool, n_updated: int) -> None:
        route = "payloads" if use_payloads else "changes"
        obs.counter("server.ingest_rounds_total").inc(
            family=self.family, route=route
        )
        obs.counter("server.ingest_docs_total").inc(n_updated, family=self.family)

    def _append(self, batch, updates, cid, use_payloads: bool) -> None:
        if self.family in ("map", "counter"):
            if use_payloads:
                batch.append_payloads(updates)
            else:
                batch.append_changes(updates)
        else:
            if cid is None:
                raise ValueError(
                    f"{self.family} ingest needs the container id"
                )
            if use_payloads:
                batch.append_payloads(updates, cid)
            else:
                batch.append_changes(updates, cid)

    def _decode_bytes_entries(self, updates):
        """Bytes entries -> Change lists, per doc.  An entry that will
        not decode is poison for that doc only: skipped (None) with a
        typed record + counter, never an uncaught decode error."""
        from ..codec.binary import decode_changes

        out = list(updates)
        for di, u in enumerate(out):
            if isinstance(u, (bytes, bytearray)):
                try:
                    out[di] = decode_changes(bytes(u))
                except _DATA_ERRORS:
                    out[di] = None
                    self.last_poison_docs.append(di)
                    obs.counter("server.poison_docs_total").inc(family=self.family)
        return out

    def _record_round(self, updates, cid, epoch: Optional[int] = None) -> None:
        """Journal one APPLIED round (stamped with the round's visible
        epoch — coalesced ingest passes each round's epoch explicitly,
        since the batch clock has already advanced past it by journal
        time).  Change-list entries are FROZEN as encoded bytes: the
        live Change objects are aliased with the producing doc's oplog,
        which extends them in place on later commits (change RLE) —
        journaling the objects themselves would double-apply those ops
        on replay.  Bytes entries are immutable already and stored
        as-is.  With ``durable_dir`` the round also lands in the WAL
        before this method returns (fsync'd per round, or deferred to
        the group-commit window in ``durable_fsync="group"`` mode —
        ``durable_epoch`` is the watermark a crash cannot lose)."""
        if epoch is None:
            epoch = self.epoch
        self._notify_epoch(epoch)
        if not (self._host_fallback or self._durable is not None):
            return
        from ..codec.binary import encode_changes

        frozen = [
            u if u is None or isinstance(u, (bytes, bytearray))
            else bytes(encode_changes(list(u)))
            for u in updates
        ]
        # in-memory journal FIRST: the round is already on the device,
        # and the mirror/recover() paths must see it even if the
        # durable append below fails
        if self._host_fallback:
            self._history.append((epoch, frozen, cid))
            if not self._degraded:
                # tiered residency: a journaled round's device work is
                # committed, so its docs become eviction-eligible
                nj = getattr(self.batch, "note_journaled", None)
                if nj is not None:
                    nj()
        if self._durable is not None:
            # fail-stop durability: a failed append means served state
            # has diverged from the WAL — continuing to journal would
            # make every later recovery silently wrong.  Detach the log
            # and surface typed; the in-memory paths stay consistent,
            # the operator recovers durability from the last checkpoint.
            try:
                # request-tracing stamps: the ambient trace id of the
                # committing thread (the pipeline/fan-in set it from
                # the round-leading push) and the leader wall clock —
                # a follower turns the stamp into measured apply lag
                self._durable.append_round(
                    epoch, cid, frozen,
                    trace=tracing.current(),
                    stamp_us=int(self._wall() * 1e6),
                )
            except BaseException as e:
                from ..errors import FencedLeader, PersistError

                log, self._durable = self._durable, None
                self._durable_closed = True  # later ingests raise typed
                try:
                    log.close()
                except Exception:  # tpulint: disable=LT-EXC(best-effort WAL close while the typed fail-stop PersistError is already in flight)
                    pass
                obs.counter("server.errors_total").inc(family=self.family)
                if isinstance(e, FencedLeader):
                    # replication fencing (docs/REPLICATION.md): the
                    # fence fires BEFORE any bytes land, so the WAL is
                    # intact — surface the deposition itself, not a
                    # disk-failure wrap; journaling stays detached
                    # (fail-stop) either way.
                    raise
                raise PersistError(
                    f"durable journal append failed at epoch {epoch} — "
                    "the WAL no longer matches served state; journaling "
                    "is DETACHED (fail-stop), recover durability from "
                    f"{log.dir!r}: {type(e).__name__}: {e}"
                ) from e
            self._journaled_epoch = max(self._journaled_epoch, epoch)
            if self._durable.fsync_mode == "group":
                self._unsynced_rounds += 1
                if self._unsynced_rounds >= self._fsync_window:
                    self.flush_durable()
            else:
                # per-round fsync: the round is already on disk
                self._durable_epoch = epoch
            obs.gauge(
                "persist.checkpoint_age_rounds",
                "journaled rounds since the last checkpoint",
            ).set(epoch - self._ckpt_epoch, family=self.family)

    def flush_durable(self) -> int:
        """Group-commit flush point: fsync every journaled-but-unsynced
        WAL append (the WAL's own pending count includes control
        records the per-round window never sees) and advance the
        ``durable_epoch`` watermark to the newest JOURNALED epoch —
        never ``self.epoch``, which a concurrently-staging pipeline
        group may already have pushed past what is on disk.  Returns
        appends covered (0 when nothing was pending or the server is
        not durable).  Fail-stop like the append path: a failed fsync
        detaches the journal typed."""
        if self._durable is None:
            return 0
        try:
            n = self._durable.sync()
        except BaseException as e:
            from ..errors import PersistError

            log, self._durable = self._durable, None
            self._durable_closed = True
            try:
                log.close()
            except Exception:  # tpulint: disable=LT-EXC(best-effort WAL close while the typed fail-stop PersistError is already in flight)
                pass
            obs.counter("server.errors_total").inc(family=self.family)
            raise PersistError(
                f"durable group-commit fsync failed — journaling is "
                f"DETACHED (fail-stop), recover durability from "
                f"{log.dir!r}: {type(e).__name__}: {e}"
            ) from e
        self._unsynced_rounds = 0
        self._durable_epoch = max(self._durable_epoch, self._journaled_epoch)
        return n

    @property
    def durable_epoch(self) -> int:
        """The acked-epoch watermark: the newest visible epoch whose
        journal record is known fsync'd.  A crash loses at most rounds
        after it (group mode); equals the newest journaled epoch in
        per-round mode.  0 for non-durable servers."""
        return self._durable_epoch

    def _replay_round(self, batch, updates, cid) -> None:
        """Re-apply a journaled round to `batch` with the same routing
        rule ingest used (all-bytes + payload path -> payloads; mixed
        or no payload path -> decode host-side).  Journaled bytes were
        applied once already, so they are known-decodable."""
        from ..codec.binary import decode_changes

        has_bytes = any(isinstance(u, (bytes, bytearray))
                        for u in updates if u is not None)
        has_changes = any(u is not None and not isinstance(u, (bytes, bytearray))
                          for u in updates)
        if has_bytes and (has_changes or not hasattr(batch, "append_payloads")):
            updates = [
                decode_changes(bytes(u)) if isinstance(u, (bytes, bytearray)) else u
                for u in updates
            ]
            has_bytes = False
        self._append(batch, updates, cid, has_bytes)

    def _drain_fetch(self) -> None:
        """Tiny host fetch that drains the async device queue (the
        honest sync — block_until_ready lies under the axon tunnel):
        fetch the smallest device array the batch holds."""
        import jax
        import numpy as np
        from contextlib import nullcontext

        dev = getattr(self.batch, "device_batch", self.batch)
        # under the device lock: a tiered eviction (release_doc) DONATES
        # the old column buffers — collecting a leaf here and fetching
        # it after the donation would read a deleted buffer.  The lock
        # spans collect+fetch so the snapshot stays coherent.
        lk = getattr(dev, "_dev_lock", None)
        with (lk if lk is not None else nullcontext()):
            leaves = []
            for v in dev.__dict__.values():
                for leaf in jax.tree_util.tree_leaves(v):
                    if isinstance(leaf, jax.Array):
                        leaves.append(leaf)
            if leaves:
                np.asarray(min(leaves, key=lambda a: a.size))

    # -- coalesced sync rounds ----------------------------------------
    def ingest_coalesced(self, rounds: Sequence[Sequence], cid=None) -> List[int]:
        """Apply several pending sync rounds as ONE coalesced device
        group (docs/RESILIENCE.md "round coalescing"): every round's
        host work — routing, order maintenance, id maps, epoch clock —
        runs per round exactly as serial ``ingest`` would (the final
        state is byte-for-byte identical), but the device scatters/
        folds of the whole group ship as one launch, amortizing the
        dispatch + tunnel-RTT floor across the group.

        Journal records, poison isolation, host-mirror degradation and
        ack bookkeeping stay PER ROUND: returns one visible epoch per
        round, in order, for clients to ack.  With
        ``durable_fsync="group"`` the group's journal records share one
        fsync and the epochs are returned only after it — an acked
        round is never lost to a crash (``durable_epoch``).

        ``ingest_stage``/``ingest_commit`` are the two-phase form the
        pipeline executor uses to overlap group N's device commit with
        group N+1's host staging; this method is simply stage+commit
        back-to-back."""
        rounds = [list(r) for r in rounds]
        if not rounds:
            return []
        if self._degraded or len(rounds) == 1:
            # host mirror rounds have no launch to amortize; a solo
            # round IS the serial path
            return [self.ingest(r, cid) for r in rounds]
        hs = getattr(self.batch, "hot_slots", None)
        if hs is not None:
            # tiered residency: a group's distinct docs co-reside in
            # device slots, so chunk the group to the hot budget (each
            # chunk commits — and journals — before the next stages,
            # so consecutive chunks may reuse the whole budget)
            out: List[int] = []
            chunk: List[list] = []
            docs_seen: set = set()
            for r in rounds:
                nxt = {di for di, u in enumerate(r) if u is not None}
                if chunk and len(docs_seen | nxt) > hs:
                    out.extend(self.ingest_commit(self.ingest_stage(chunk, cid)))
                    chunk, docs_seen = [], set()
                chunk.append(r)
                docs_seen |= nxt
            out.extend(self.ingest_commit(self.ingest_stage(chunk, cid)))
            return out
        return self.ingest_commit(self.ingest_stage(rounds, cid))

    def ingest_stage(self, rounds: Sequence[Sequence], cid=None):
        """Phase 1 of a coalesced group: normalize + HOST-stage every
        round (order maintenance, id maps, per-round epoch stamps) with
        the device work deferred, and return an opaque handle for
        ``ingest_commit``.  Touches no device arrays (modulo a rare
        capacity grow, which the batch's device lock serializes against
        an in-flight commit), so it may run while the PREVIOUS group's
        commit is still on the device — the host/device overlap of
        docs/RESILIENCE.md."""
        rounds = [list(r) for r in rounds]
        if getattr(self, "_durable_closed", False):
            from ..errors import PersistError

            raise PersistError(
                "durable server is closed — a round applied now could "
                "never be journaled; reopen via persist.recover_server()"
            )
        if self.family not in ("map", "counter") and cid is None:
            raise ValueError(f"{self.family} ingest needs the container id")
        h = _StagedGroup(rounds, cid)
        if not rounds:
            h.mode = "done"
            return h
        if self._degraded:
            h.mode = "serial"  # commit routes through degraded ingest
            return h
        batch = self.batch
        self.last_poison_docs = []
        for r in rounds:
            ups, use_pl, n_upd = self._normalize_round(r, batch)
            h.staged.append((ups, use_pl))
            self._tick_round_counters(use_pl, n_upd)
        if cid is not None:
            self._cid = cid
        sup = self._sup()
        if self._auto_ckpt_pending:
            # same contract as serial ingest: snapshot before the first
            # risky (first-compile) launch of the server's life.  Only
            # ever runs before the FIRST group, so no commit can be in
            # flight behind it.
            self._auto_ckpt_pending = False
            try:
                self.last_checkpoint = sup.guard(
                    self.checkpoint, label=f"server.checkpoint.{self.family}"
                )
            except DeviceFailure as e:
                h.mode = "done"
                h.epochs = self._degrade_rounds(
                    [s[0] for s in h.staged], cid, e
                )
                return h
            obs.counter("server.auto_checkpoints_total").inc(family=self.family)
        batch.begin_coalesce()
        try:
            for i, (ups, use_pl) in enumerate(h.staged):
                try:
                    self._append(batch, ups, cid, use_pl)
                except _DATA_ERRORS:
                    # poison round: staging stops here; commit isolates
                    # it per doc and runs the tail serially
                    h.error_index = i
                    break
                h.epochs.append(self.epoch)
        except BaseException:
            # host config/logic error (capacity with auto_grow=False,
            # API misuse): ship the staged prefix so host and device
            # agree, journal it, then surface loudly — same contract as
            # serial ingest
            batch.flush_coalesce()
            for j, ep in enumerate(h.epochs):
                self._record_round(h.staged[j][0], cid, epoch=ep)
            self.flush_durable()
            obs.counter("server.errors_total").inc(family=self.family)
            raise
        h.pending = batch.detach_coalesce()
        return h

    def ingest_commit(self, h) -> List[int]:
        """Phase 2 of a coalesced group: ship the staged device work as
        one supervised launch, journal each round with its stage-time
        epoch, and fsync the group-commit window.  Returns the
        per-round ack epochs.  A DeviceFailure here degrades with the
        WHOLE group (none of it is journaled before this method), so
        staged work replays in order on the host mirror — never lost,
        never double-applied."""
        if h.mode == "done":
            return h.epochs
        if h.mode == "serial":
            # server was degraded at stage time: plain serial ingest
            # (host mirror application, journaled per round).  The
            # group-end fsync still applies: a pipeline epoch future
            # must never resolve before its journal record is durable.
            out = [self.ingest(r, h.cid) for r in h.rounds]
            self.flush_durable()
            return out
        cid = h.cid
        sup = self._sup()
        batch = self.batch
        if self._degraded:
            # a previous group's commit degraded the server AFTER this
            # group host-staged into the now-discarded device batch:
            # re-apply the normalized rounds on the mirror (the mirror
            # seeded from the journal, which holds none of them)
            out: List[int] = []
            for ups, _pl in h.staged:
                obs.counter("server.degraded_rounds_total").inc(family=self.family)
                ups = self._decode_bytes_entries(ups)
                self._host.apply(ups, cid)
                self._host_rounds += 1
                self._record_round(ups, cid)
                out.append(self.epoch)
            self.flush_durable()
            return out
        obs.counter("pipeline.groups_total").inc(family=self.family)
        obs.histogram(
            "pipeline.coalesce_group_rounds", "rounds per coalesced group",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        ).observe(len(h.staged))
        try:
            with obs.histogram(
                "server.epoch_seconds", "ingest wall time per sync round"
            ).time(family=self.family):
                sup.launch(
                    lambda: batch.commit_detached(h.pending),
                    label=f"server.ingest.{self.family}",
                    retry=False,  # scatters donate buffers: never re-run
                    drain=self._drain_fetch,
                )
        except DeviceFailure as e:
            return self._degrade_rounds([s[0] for s in h.staged], cid, e)
        epochs = list(h.epochs)
        # journal per round (each with ITS stage-time epoch)
        for (ups, _pl), ep in zip(h.staged, epochs):
            self._record_round(ups, cid, epoch=ep)
        if h.error_index is not None:
            # the poison round + unstaged tail: isolate per doc, then
            # run the remainder serially (another device failure there
            # degrades with the remaining rounds)
            i = h.error_index
            self._ingest_isolated(h.staged[i][0], cid, sup)
            epochs.append(self.epoch)
            i += 1
            while i < len(h.staged):
                ups, use_pl = h.staged[i]
                try:
                    sup.launch(
                        lambda ups=ups, up=use_pl: self._append(
                            batch, ups, cid, up
                        ),
                        label=f"server.ingest.{self.family}",
                        retry=False,
                        drain=self._drain_fetch,
                    )
                except DeviceFailure as e:
                    return epochs + self._degrade_rounds(
                        [s[0] for s in h.staged[i:]], cid, e
                    )
                except _DATA_ERRORS:
                    self._ingest_isolated(ups, cid, sup)
                else:
                    self._record_round(ups, cid)
                epochs.append(self.epoch)
                i += 1
        # one group-commit sync point: every returned epoch is durable
        self.flush_durable()
        return epochs

    # -- per-doc error isolation --------------------------------------
    def _ingest_isolated(self, updates, cid, sup) -> None:
        """Re-apply a failed round one doc at a time: good docs commit,
        bytes entries that misparse get one host-decode fallback, and
        a doc that still fails is poison — skipped with a typed record
        (``last_poison_docs`` + the server.poison_docs_total counter),
        never an uncaught exception for the whole round."""
        from ..codec.binary import decode_changes

        obs.counter("server.isolation_rounds_total").inc(family=self.family)
        for di, u in enumerate(updates):
            if u is None:
                continue
            one = [None] * len(updates)
            one[di] = u
            use_payloads = isinstance(u, (bytes, bytearray)) and hasattr(
                self.batch, "append_payloads"
            )
            try:
                sup.launch(
                    lambda one=one, up=use_payloads: self._append(
                        self.batch, one, cid, up
                    ),
                    label=f"server.ingest.{self.family}",
                    retry=False,
                    drain=self._drain_fetch,
                )
                # each per-doc append bumps batch.epoch once, so it is
                # journaled as its OWN round — recovery replay then
                # reproduces the same epoch numbering clients acked
                self._record_round(one, cid)
                continue
            except DeviceFailure:
                raise  # double fault: device died mid-isolation — typed
            except _DATA_ERRORS:
                pass
            if isinstance(u, (bytes, bytearray)):
                # host-decode fallback for THIS doc only (extends the
                # mixed-round fallback to per-doc poison isolation)
                try:
                    chs = decode_changes(bytes(u))
                    one[di] = chs
                    sup.launch(
                        lambda one=one: self._append(self.batch, one, cid, False),
                        label=f"server.ingest.{self.family}",
                        retry=False,
                        drain=self._drain_fetch,
                    )
                    self._record_round(one, cid)
                    obs.counter("server.ingest_fallback_total").inc(
                        family=self.family, reason="doc_isolated"
                    )
                    continue
                except DeviceFailure:
                    raise
                except _DATA_ERRORS:
                    pass
            self.last_poison_docs.append(di)
            obs.counter("server.poison_docs_total").inc(family=self.family)

    # -- graceful degradation -----------------------------------------
    def _degrade_round(self, updates, cid, cause: DeviceFailure) -> int:
        """Supervisor declared the device dead mid-epoch: re-run the
        epoch on the host engine (anchor seed / journal replay + this
        round) and stay degraded until ``recover()``."""
        return self._degrade_rounds([updates], cid, cause)[-1]

    def _degrade_rounds(self, rounds_updates, cid,
                        cause: DeviceFailure) -> List[int]:
        """Group form of ``_degrade_round`` (coalesced ingest): seed
        the host mirror once — anchor / journal replay, which holds
        NOTHING of the failed group — then apply and journal every
        group round in order, so staged work replays exactly once.
        Returns one visible epoch per round."""
        anchored = self._anchor is not None
        if not (self._host_fallback and (self._history_complete or anchored)):
            obs.counter("server.errors_total").inc(family=self.family)
            raise cause
        self._sup().note_degradation(f"server.{self.family}")
        obs.gauge("server.degraded").set(1, family=self.family)
        # base = the VISIBLE epoch (batch.epoch may already include
        # rounds of the failed group that committed before the drain
        # raised — the offset keeps visible epochs monotone)
        self._epoch_base = self.epoch
        host = self.seed_mirror_engine()
        self._host = host
        self._degraded = True
        self._host_rounds = 0
        out: List[int] = []
        for updates in rounds_updates:
            obs.counter("server.degraded_rounds_total").inc(family=self.family)
            # the failed rounds' bytes never committed anywhere, so
            # they are NOT known-decodable: poison-skip per doc
            updates = self._decode_bytes_entries(updates)
            host.apply(updates, cid)
            self._host_rounds += 1
            self._record_round(updates, cid)
            out.append(self.epoch)
        self.flush_durable()
        return out

    def _seed_mirror(self):
        """Host mirror base: anchor-seeded docs when a mirror anchor
        exists (state at the last checkpoint, history trimmed below
        it), else fresh docs (the journal is then complete since
        birth)."""
        if self._anchor is not None:
            return self._anchor.seed_engine()
        from ..resilience.hostpath import HostEngine

        return HostEngine(self.family, self.n_docs)

    def seed_mirror_engine(self):
        """A ``hostpath.HostEngine`` at the server's current APPLIED
        state: the mirror-anchor seed plus the journal tail.  The one
        replay rule both consumers share — the degradation mirror
        (``_degrade_rounds``) and the sync front-end's delta-export
        oracle (``loro_tpu/sync``).  Requires ``host_fallback`` (the
        journal/anchor machinery); callers that may hold a pre-v3
        restore check ``_history_complete``/``_anchor`` first."""
        rh = getattr(self.batch, "rehydrate_anchor", None)
        if rh is not None:
            # tiered residency: cold docs' blobs come back first — the
            # mirror engine must hold EVERY doc, whatever its tier
            rh()
        host = self._seed_mirror()
        floor = self._anchor.epoch if self._anchor is not None else 0
        for _e, ups, c in self._history:
            if _e > floor:
                host.apply(ups, c)
        if self._cid is not None:
            host._cid = self._cid
        return host

    # -- epoch-commit subscription (loro_tpu/sync fan-out) -------------
    def subscribe_epochs(self, cb) -> "callable":
        """Register ``cb(epoch)`` to run for every newly VISIBLE epoch
        (device commit, coalesced group member, isolated per-doc round,
        or degraded host-mirror round alike).  Fires on the committing
        thread, after the round is applied but before pipeline epoch
        futures resolve — a subscriber observes a commit no later than
        the client that pushed it.  Commit-visibility semantics, not
        durability: in ``durable_fsync="group"`` mode the epoch may not
        be fsync'd yet (gate on ``durable_epoch`` for that).  Recovery
        replay (``_replay_journal_tail``) does NOT re-fire — those
        epochs were announced in their original life.  Returns an
        unsubscribe callable."""
        self._epoch_subs.append(cb)
        return lambda: self._epoch_subs.remove(cb)

    def _notify_epoch(self, epoch: int) -> None:
        flight.record("server.epoch", family=self.family, epoch=epoch,
                      trace=tracing.current())
        for cb in list(self._epoch_subs):
            try:
                cb(epoch)
            except Exception:  # tpulint: disable=LT-EXC(subscriber isolation: a broken epoch subscriber must never poison ingest; counted below)
                obs.counter(
                    "server.epoch_sub_errors_total",
                    "epoch-commit subscriber callbacks that raised",
                ).inc(family=self.family)

    def attach_durable(self, log) -> None:
        """Adopt a ``persist.DurableLog`` (recover_server re-attaches
        the reopened directory so future rounds keep journaling).
        Every replayed round came FROM disk, so the durable watermark
        starts at the recovered epoch."""
        self._durable = log
        self._durable_closed = False
        self._unsynced_rounds = 0
        self._journaled_epoch = self.epoch
        self._durable_epoch = self.epoch

    @property
    def pipeline_doc_budget(self) -> Optional[int]:
        """Max DISTINCT docs a coalesced group may touch (None = no
        bound).  Tiered servers (hot_slots=) bound it to half the hot
        budget: a group's docs must co-reside in device slots — their
        merged scatter references the slots, so none is evictable until
        the group commits and journals — and the staging group overlaps
        the in-flight one, so two groups' worth must fit.  A single
        round touching more docs than hot_slots still fails typed
        (ResidencyError) whatever the grouping."""
        hs = getattr(self.batch, "hot_slots", None)
        if hs is None:
            return None
        return max(1, hs // 2)

    def pipeline(self, cid=None, coalesce: int = 4, depth: int = 2):
        """Attach a ``PipelinedIngest`` executor (parallel/pipeline.py):
        submitted rounds stage on the host while the device group in
        flight drains, and consecutive staged rounds coalesce into one
        launch.  ``close()``/``checkpoint()`` drain it automatically."""
        from .pipeline import PipelinedIngest

        if self._pipeline is not None and not self._pipeline.closed:
            raise RuntimeError(
                "server already has a live pipeline — close() it first"
            )
        self._pipeline = PipelinedIngest(
            self, cid=cid, coalesce=coalesce, depth=depth
        )
        return self._pipeline

    def _drain_pipeline(self) -> None:
        """Flush the attached pipeline (no-op from the pipeline's own
        worker thread — e.g. the auto-checkpoint a worker ingest
        triggers — and when no pipeline is attached)."""
        if self._pipeline is not None and not self._pipeline.closed:
            self._pipeline.flush()

    def close(self) -> None:
        """Drain the attached pipeline, fsync any pending group-commit
        window, and release the durable log (flush + close the active
        WAL segment) so ``persist.recover_server``/``open_server`` can
        reopen the directory.  The server stays READABLE, but further
        ``ingest()`` raises a typed PersistError — applying a round the
        closed WAL can't journal would silently diverge served state
        from recovery."""
        try:
            if self._pipeline is not None and not self._pipeline.closed:
                self._pipeline.close()
        finally:
            # the durable teardown must run even when the pipeline
            # drain re-raises a worker error: a WAL handle left open
            # would make the directory refuse a later recover_server
            if self._durable is not None:
                self.flush_durable()
                self._durable.close()
                self._durable = None
                self._durable_closed = True

    def _replay_journal_tail(self, rounds) -> None:
        """Apply recovered WAL rounds (``(epoch, cid, frozen)``) to the
        batch and re-seed the in-memory journal tail — recovery-only
        (persist.recover_server); appends route through the supervisor
        but are NOT re-journaled (the WAL already holds them)."""
        sup = self._sup()
        last_epoch = self._ckpt_epoch
        nj = getattr(self.batch, "note_journaled", None)
        for epoch, cid, ups in rounds:
            sup.launch(
                lambda ups=ups, cid=cid: self._replay_round(self.batch, list(ups), cid),
                label=f"server.recover.{self.family}",
                retry=False,
                drain=self._drain_fetch,
            )
            if cid is not None:
                self._cid = cid
            if self._host_fallback:
                self._history.append((epoch, list(ups), cid))
            if nj is not None:
                # replayed rounds come FROM the WAL: journaled by
                # definition, so tiered eviction stays possible while
                # the replay revives the docs it touches
                nj()
            last_epoch = epoch
        # visible epochs must continue exactly where the WAL left off
        self._epoch_offset = max(
            0, last_epoch - getattr(self.batch, "epoch", 0)
        )

    def recover(self, mesh=None) -> bool:
        """Rebuild the device batch — from the last checkpoint's batch
        state plus the journal tail when a checkpoint exists (bounded
        replay), else a fresh batch plus the full journal — and switch
        reads back to the device.  Replay launches pass ``retry=False``
        on purpose: a transiently-failed append may have half-mutated
        the new batch's order engines / donated buffers, so the only
        safe unit of retry is this whole method (the failed batch is
        discarded — call ``recover()`` again).  Returns True on
        success; stays degraded and returns False if the device is
        still failing."""
        if not self._degraded:
            return True
        if self._caps is None and self._replay_base is None:
            raise ResilienceError(
                "cannot recover a restore()d pre-v3 server (no construction "
                "caps in the checkpoint); build a fresh server and "
                "restore() a v3 checkpoint into it"
            )
        sup = self._sup()
        try:
            if self._replay_base is not None:
                # bounded replay: re-seed the batch from the last
                # checkpoint's device state, then replay only the
                # journal tail (rounds after the checkpoint epoch)
                from ..storage import MemKvStore

                kv = MemKvStore()
                kv.import_all(self._replay_base)
                batch = sup.guard(
                    lambda: self._import_batch(
                        self.family, kv.get(b"batch"), self._caps,
                        mesh if mesh is not None else self._mesh,
                    ),
                    label=f"server.recover.{self.family}",
                )
                tail = [r for r in self._history if r[0] > self._ckpt_epoch]
            else:
                batch = self._build_batch(
                    self.family, self.n_docs,
                    mesh if mesh is not None else self._mesh,
                    self._auto_grow, self._caps,
                )
                tail = self._history
            # bind BEFORE replay: a tiered batch builds its revive
            # mirrors from this server's anchor + journal.  The journal
            # is rebuilt INCREMENTALLY alongside the replay (same shape
            # as persist's _replay_journal_tail): a tiered revive mid-
            # replay must see only the rounds already replayed — a full
            # journal would land FUTURE ops in the revive payload and
            # the remaining replay would then duplicate them on device.
            self._bind_batch(batch)
            nj = getattr(batch, "note_journaled", None)
            full_hist = self._history
            self._history = (
                [r for r in full_hist if r[0] <= self._ckpt_epoch]
                if self._replay_base is not None else []
            )
            try:
                for _e, ups, c in tail:
                    sup.launch(
                        lambda ups=ups, c=c: self._replay_round(batch, ups, c),
                        label=f"server.recover.{self.family}",
                        retry=False,
                    )
                    self._history.append((_e, ups, c))
                    if nj is not None:
                        nj()  # journal rounds are journaled by definition
            except BaseException:
                # stay degraded with the journal intact: the degraded
                # mirror (and a later recover() retry) needs it whole
                self._history = full_hist
                raise
        except DeviceFailure:
            obs.counter("server.recovery_failures_total").inc(family=self.family)
            return False
        prev_visible = self.epoch
        self.batch = batch
        self._degraded = False
        self._host = None
        self._host_rounds = 0
        # epochs clients acked must stay reachable: never regress the
        # visible epoch below what the degraded server handed out
        self._epoch_offset = max(
            0, prev_visible - getattr(batch, "epoch", 0)
        )
        obs.counter("server.recoveries_total").inc(family=self.family)
        obs.gauge("server.degraded").set(0, family=self.family)
        return True

    # -- reads (device batch, or the host mirror when degraded) --------
    def _read(self, name: str, *args, **kw):
        target = self._host if self._degraded else self.batch
        return getattr(target, name)(*args, **kw)

    def texts(self) -> List[str]:
        return self._read("texts")

    def richtexts(self) -> List[list]:
        return self._read("richtexts")

    def values(self) -> List[list]:
        return self._read("values")

    def value_maps(self):
        return self._read("value_maps")

    def root_value_maps(self, name: str):
        return self._read("root_value_maps", name)

    def parent_maps(self) -> List[dict]:
        return self._read("parent_maps")

    def children_maps(self) -> List[dict]:
        return self._read("children_maps")

    def value_lists(self) -> List[list]:
        return self._read("value_lists")

    @property
    def epoch(self) -> int:
        if self._degraded:
            return self._epoch_base + self._host_rounds
        return getattr(self.batch, "epoch", 0) + self._epoch_offset

    # -- acknowledgment bookkeeping -----------------------------------
    def register_replica(self, di: int, replica: str) -> None:
        """A doc's replica set must be registered before its acks count
        — an unregistered replica set means 'unknown readers', which
        pins the doc's stability floor at 0 (never compact)."""
        self.acks[di].setdefault(replica, 0)

    def ack(self, di: int, replica: str, epoch: int) -> None:
        """Record that `replica` integrated everything the server sent
        up to `epoch` (monotone; stale acks are ignored).  The replica
        must have been registered: silently admitting an unknown name
        would let a PARTIAL replica set define the stability floor and
        reclaim rows an unregistered reader still references."""
        if replica not in self.acks[di]:
            raise ValueError(
                f"doc {di}: ack from unregistered replica {replica!r} — "
                "call register_replica first (the full replica set "
                "defines the compaction floor)"
            )
        if epoch > self.acks[di][replica]:
            self.acks[di][replica] = epoch

    def drop_replica(self, di: int, replica: str) -> None:
        """Forget a departed replica so it stops pinning the floor.
        Only do this once the replica is PERMANENTLY gone — a returning
        replica that missed deletes may reference reclaimed rows."""
        self.acks[di].pop(replica, None)

    def stable_epoch(self, di: int) -> int:
        """The doc's compaction floor: the newest epoch every
        registered replica has acked (0 = no floor)."""
        a = self.acks[di]
        return min(a.values()) if a else 0

    # -- lifecycle -----------------------------------------------------
    def compact(self) -> int:
        """Reclaim what the ack floors allow (no-op for map/counter —
        their resident state is already a fold — and while degraded:
        the host mirror holds no device rows to reclaim).  Returns rows
        reclaimed."""
        self._drain_pipeline()  # never compact under a staged group
        if self.family not in _COMPACTABLE or self._degraded:
            return 0
        floors: List[Optional[int]] = []
        for di in range(self.n_docs):
            # acks live on the VISIBLE epoch scale; the batch compares
            # floors against its INTERNAL epochs — translate, clamping
            # at 0 (a too-new floor could reclaim a tombstone a replica
            # still references)
            e = max(0, self.stable_epoch(di) - self._epoch_offset)
            # skip docs whose floor hasn't advanced since the last pass
            floors.append(e if e > self._compacted_at[di] else None)
        if all(f is None for f in floors):
            return 0
        with obs.histogram("server.compact_seconds").time(family=self.family):
            n = self.batch.compact(floors)
        obs.counter("server.compact_rows_reclaimed_total").inc(
            n, family=self.family
        )
        for di, f in enumerate(floors):
            if f is not None:
                self._compacted_at[di] = f
        return n

    # -- checkpoint/resume --------------------------------------------
    def checkpoint(self) -> bytes:
        """Batch state + ack floors (+ v3: construction caps and the
        mirror anchor) as one LTKV store.  Also the journal bound:
        the anchor folds every journaled round in, the in-memory
        journal drops to rounds AFTER this epoch, and with
        ``durable_dir`` the blob lands on the checkpoint ladder while
        the WAL rotates and prunes covered segments.  Unavailable
        while degraded (the device state is gone — ``recover()``
        first, or restore the pre-failure ``last_checkpoint``).  An
        attached pipeline is DRAINED first: a checkpoint must cover
        every submitted round, never split a staged group."""
        self._drain_pipeline()
        if self._degraded:
            raise ResilienceError(
                "cannot checkpoint a degraded server (device state lost); "
                "recover() first or restore() the last_checkpoint"
            )
        from ..codec.binary import Writer
        from ..storage import MemKvStore

        rh = getattr(self.batch, "rehydrate_anchor", None)
        if rh is not None:
            # tiered residency: cold docs' blobs come back into the
            # anchor first — the rung this checkpoint writes must carry
            # EVERY doc (it becomes the cold tier's new backing rung)
            rh()
        if self._anchor is not None:
            # fold the journal tail into the shallow-snapshot anchor
            # BEFORE trimming: the mirror oracle re-anchors here
            self._anchor.advance(self._history, self._cid)
        kv = MemKvStore()
        meta = Writer()
        meta.u8(3)  # server-state version (v3: + caps/flags/anchor)
        meta.str_(self.family)
        meta.varint(self.n_docs)
        meta.varint(len(self._compacted_at))
        for e in self._compacted_at:
            meta.varint(e)
        # acks are visible-scale; the batch state is internal-scale —
        # the offset must survive restore or floors skew (see epoch)
        meta.varint(self._epoch_offset)
        # v3: construction caps + lifecycle flags, so a restore()d
        # server can degrade (anchor) and recover() (caps)
        flags = (
            (1 if self._auto_grow else 0)
            | (2 if self._host_fallback else 0)
            | (4 if self._anchor is not None else 0)
        )
        meta.u8(flags)
        from ..persist.wal import write_caps

        write_caps(meta, self._caps or {})
        kv.set(b"server", bytes(meta.buf))
        w = Writer()
        w.varint(len(self.acks))
        for a in self.acks:
            w.varint(len(a))
            for rep, e in a.items():
                w.str_(rep)
                w.varint(e)
        kv.set(b"acks", bytes(w.buf))
        kv.set(b"batch", self.batch.export_state())
        if self._anchor is not None:
            kv.set(b"anchor", self._anchor.encode())
        blob = kv.export_all()
        # re-anchor recovery + bound the journal (satellite: journal
        # length stays O(rounds since checkpoint)).  last_checkpoint
        # stays the auto-checkpoint blob (the documented pre-first-
        # launch restore point); _replay_base is the recovery anchor.
        self._replay_base = blob
        self._ckpt_epoch = self.epoch
        if self._anchor is not None:
            # trim ONLY when the anchor holds the folded history: a
            # mirror_anchor=False server's host mirror still needs the
            # journal from birth (recover() is bounded either way — it
            # filters the tail against _ckpt_epoch)
            self._history = [r for r in self._history if r[0] > self._ckpt_epoch]
        ckpt_name = None
        if self._durable is not None:
            ckpt_name = self._durable.record_checkpoint(self._ckpt_epoch, blob)
            # the rotation inside record_checkpoint fsyncs any pending
            # group-commit tail: everything JOURNALED is now durable
            # (self.epoch may already include concurrently-staged
            # rounds that are not — the pipeline was drained above,
            # but stay on the journaled clock for consistency)
            self._unsynced_rounds = 0
            self._durable_epoch = max(
                self._durable_epoch, self._journaled_epoch
            )
            obs.gauge(
                "persist.checkpoint_age_rounds",
                "journaled rounds since the last checkpoint",
            ).set(0, family=self.family)
        ac = getattr(self.batch, "after_checkpoint", None)
        if ac is not None:
            # tiered residency: re-back the cold tier on the fresh rung
            # (and re-drop its blobs), run the warm-budget demotions,
            # refresh residency.json
            ac(ckpt_name)
        return blob

    @classmethod
    def restore(cls, data: bytes, mesh=None) -> "ResidentServer":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, acks_b, batch_b = kv.get(b"server"), kv.get(b"acks"), kv.get(b"batch")
        if meta_b is None or acks_b is None or batch_b is None:
            raise DecodeError("ResidentServer state: missing sections")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > 3:
                raise DecodeError(f"ResidentServer state v{version} too new")
            family = r.str_()
            n_docs = r.varint()
            n_comp = r.varint()
            compacted_at = [r.varint() for _ in range(n_comp)]
            epoch_offset = r.varint() if version >= 2 else 0
            # v3: construction caps + lifecycle flags (v1/v2 blobs keep
            # the old semantics: no caps -> no in-place recover, no
            # anchor -> typed failure instead of degradation)
            auto_grow, host_fallback, has_anchor, caps = True, False, False, None
            if version >= 3:
                from ..persist.wal import read_caps

                flags = r.u8()
                auto_grow = bool(flags & 1)
                host_fallback = bool(flags & 2)
                has_anchor = bool(flags & 4)
                caps = read_caps(r)
            if family not in _FAMILIES or n_comp != n_docs:
                raise DecodeError("ResidentServer state: malformed meta")
            r = Reader(acks_b)
            n_acks = r.varint()
            if n_acks != n_docs:
                raise DecodeError("ResidentServer state: ack table width")
            acks: List[Dict[str, int]] = []
            for _ in range(n_acks):
                a: Dict[str, int] = {}
                for _ in range(r.varint()):
                    rep = r.str_()
                    a[rep] = r.varint()
                acks.append(a)
        except (IndexError, ValueError, UnicodeDecodeError) as e:
            raise DecodeError(f"ResidentServer state: malformed ({e})") from None
        anchor = None
        if has_anchor:
            from ..persist import MirrorAnchor

            anchor_b = kv.get(b"anchor")
            if anchor_b is None:
                raise DecodeError("ResidentServer state: anchor flag without section")
            anchor = MirrorAnchor.decode(anchor_b)
            if anchor.family != family or anchor.n_docs != n_docs:
                raise DecodeError("ResidentServer state: anchor shape mismatch")
        srv = cls.__new__(cls)
        srv.family = family
        srv.n_docs = n_docs
        srv.acks = acks
        srv._compacted_at = compacted_at
        srv.batch = cls._import_batch(family, batch_b, caps, mesh)
        if srv.batch.n_docs < n_docs:
            raise DecodeError(
                "ResidentServer state: batch narrower than the ack table"
            )
        # a v3 restore carries everything the resilience machinery
        # needs: caps (in-place recover()), the mirror anchor (host
        # degradation without birth history — the journal resumes from
        # the restore point) and the blob itself as the bounded-replay
        # base.  Pre-v3 blobs restore with host_fallback OFF and a
        # later device failure surfaces as a typed DeviceFailure.
        srv._init_resilience(
            mesh=mesh, auto_grow=auto_grow, caps=caps, supervisor=None,
            host_fallback=host_fallback and anchor is not None,
            auto_checkpoint=False, history_complete=False,
            anchor=anchor, replay_base=data,
        )
        srv._bind_batch(srv.batch)
        srv._epoch_offset = epoch_offset
        srv.last_checkpoint = data
        srv._ckpt_epoch = srv.epoch
        if anchor is not None and anchor.cid is not None:
            srv._cid = anchor.cid
        return srv
