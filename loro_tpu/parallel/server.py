"""Batteries-included resident sync server: one device batch + the
ack bookkeeping that makes its lifecycle (grow/compact) safe to use.

The resident batches expose a precise but easy-to-misuse contract:
``compact(stable_epochs)`` may only receive epochs that EVERY replica
of a doc has acknowledged integrating — passing a too-new epoch can
reclaim a tombstone some replica still references (see
DeviceDocBatch.compact).  This wrapper owns that bookkeeping:

- ``ingest(per_doc_updates)`` feeds a sync round into the batch and
  returns the epoch to hand to clients with the round's fan-out;
- ``ack(di, replica, epoch)`` records a replica's acknowledgment;
- ``compact()`` reclaims with each doc's stability floor =
  min over its registered replicas' acked epochs (docs with no
  registered replicas never compact — safe default);
- ``checkpoint()/restore()`` round-trip batch + acks through LTKV
  bytes, so a restarted server resumes with its compaction floors.

Reference analog: the two-round sync loop of the reference's README
(crates/loro/README) plus its shallow-snapshot floor
(crates/loro-internal/src/encoding/shallow_snapshot.rs:16-40), packaged
server-side at fleet scale.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..obs import metrics as obs
from .fleet import (
    DeviceCounterBatch,
    DeviceDocBatch,
    DeviceMapBatch,
    DeviceMovableBatch,
    DeviceTreeBatch,
)

# ONE table per family: (batch class for restore, constructor) — both
# checkpoint/restore and __init__ resolve from it, so they cannot drift
_FAMILIES = {
    "text": (DeviceDocBatch, lambda n, mesh, auto_grow, kw: DeviceDocBatch(
        n, kw.get("capacity", 1 << 14), mesh=mesh, auto_grow=auto_grow
    )),
    "list": (DeviceDocBatch, lambda n, mesh, auto_grow, kw: DeviceDocBatch(
        n, kw.get("capacity", 1 << 14), mesh=mesh, as_text=False,
        auto_grow=auto_grow,
    )),
    "map": (DeviceMapBatch, lambda n, mesh, auto_grow, kw: DeviceMapBatch(
        n, kw.get("slot_capacity", 1 << 10), mesh=mesh, auto_grow=auto_grow
    )),
    "tree": (DeviceTreeBatch, lambda n, mesh, auto_grow, kw: DeviceTreeBatch(
        n, kw.get("move_capacity", 1 << 12), kw.get("node_capacity", 1 << 10),
        mesh=mesh, auto_grow=auto_grow,
    )),
    "movable": (DeviceMovableBatch, lambda n, mesh, auto_grow, kw: DeviceMovableBatch(
        n, kw.get("capacity", 1 << 13), kw.get("elem_capacity", 1 << 10),
        mesh=mesh, auto_grow=auto_grow,
    )),
    "counter": (DeviceCounterBatch, lambda n, mesh, auto_grow, kw: DeviceCounterBatch(
        n, kw.get("slot_capacity", 1 << 6), mesh=mesh, auto_grow=auto_grow
    )),
}
_COMPACTABLE = ("text", "list", "tree", "movable")


class ResidentServer:
    """One resident device batch + per-doc replica-ack bookkeeping.

    ``family``: "text" | "list" | "map" | "tree" | "movable" |
    "counter".  Capacity knobs pass through (capacity, slot_capacity,
    move_capacity, node_capacity, elem_capacity).  The underlying batch
    is ``self.batch`` — every read API (texts/richtexts/values/
    value_lists/parent_maps/...) is used directly on it.
    """

    def __init__(self, family: str, n_docs: int, mesh=None,
                 auto_grow: bool = True, **caps):
        if family not in _FAMILIES:
            raise ValueError(f"unknown family {family!r} (one of {sorted(_FAMILIES)})")
        self.family = family
        self.batch = _FAMILIES[family][1](n_docs, mesh, auto_grow, caps)
        self.n_docs = n_docs
        # acks[di][replica] = newest epoch that replica confirmed
        self.acks: List[Dict[str, int]] = [dict() for _ in range(n_docs)]
        self._compacted_at: List[int] = [0] * n_docs

    # -- sync rounds ---------------------------------------------------
    def ingest(self, per_doc_updates: Sequence, cid=None) -> int:
        """Feed one sync round (per-doc update payloads via the native
        path when bytes, else change lists; None = no update) and
        return the epoch clients must ack once they integrate the
        round's fan-out.

        Entries are normalized PER DOC (ADVICE r5 finding 1): a round
        mixing bytes payloads and Change lists decodes the bytes
        entries host-side instead of mis-routing the change lists
        through the payload path (where a TypeError escaped the
        per-doc fallback)."""
        batch = self.batch
        per_doc_updates = list(per_doc_updates)
        n_updated = sum(1 for u in per_doc_updates if u is not None)
        obs.gauge("server.queue_depth").set(n_updated, family=self.family)
        has_bytes = any(isinstance(u, (bytes, bytearray))
                        for u in per_doc_updates if u is not None)
        has_changes = any(u is not None and not isinstance(u, (bytes, bytearray))
                          for u in per_doc_updates)
        if has_bytes and (has_changes or not hasattr(batch, "append_payloads")):
            # mixed round, or a family without a native payload path
            # (counter): decode bytes entries host-side per doc
            from ..codec.binary import decode_changes

            reason = "mixed_round" if has_changes else "no_payload_path"
            n_decoded = sum(
                1 for u in per_doc_updates if isinstance(u, (bytes, bytearray))
            )
            obs.counter("server.ingest_fallback_total").inc(
                n_decoded, family=self.family, reason=reason
            )
            per_doc_updates = [
                decode_changes(u) if isinstance(u, (bytes, bytearray)) else u
                for u in per_doc_updates
            ]
            use_payloads = False
        else:
            use_payloads = has_bytes
        route = "payloads" if use_payloads else "changes"
        obs.counter("server.ingest_rounds_total").inc(
            family=self.family, route=route
        )
        obs.counter("server.ingest_docs_total").inc(n_updated, family=self.family)
        try:
            with obs.histogram(
                "server.epoch_seconds", "ingest wall time per sync round"
            ).time(family=self.family):
                if self.family in ("map", "counter"):
                    if use_payloads:
                        batch.append_payloads(per_doc_updates)
                    else:
                        batch.append_changes(per_doc_updates)
                else:
                    if cid is None:
                        raise ValueError(
                            f"{self.family} ingest needs the container id"
                        )
                    if use_payloads:
                        batch.append_payloads(per_doc_updates, cid)
                    else:
                        batch.append_changes(per_doc_updates, cid)
        except Exception:
            obs.counter("server.errors_total").inc(family=self.family)
            raise
        return self.epoch

    @property
    def epoch(self) -> int:
        return getattr(self.batch, "epoch", 0)

    # -- acknowledgment bookkeeping -----------------------------------
    def register_replica(self, di: int, replica: str) -> None:
        """A doc's replica set must be registered before its acks count
        — an unregistered replica set means 'unknown readers', which
        pins the doc's stability floor at 0 (never compact)."""
        self.acks[di].setdefault(replica, 0)

    def ack(self, di: int, replica: str, epoch: int) -> None:
        """Record that `replica` integrated everything the server sent
        up to `epoch` (monotone; stale acks are ignored).  The replica
        must have been registered: silently admitting an unknown name
        would let a PARTIAL replica set define the stability floor and
        reclaim rows an unregistered reader still references."""
        if replica not in self.acks[di]:
            raise ValueError(
                f"doc {di}: ack from unregistered replica {replica!r} — "
                "call register_replica first (the full replica set "
                "defines the compaction floor)"
            )
        if epoch > self.acks[di][replica]:
            self.acks[di][replica] = epoch

    def drop_replica(self, di: int, replica: str) -> None:
        """Forget a departed replica so it stops pinning the floor.
        Only do this once the replica is PERMANENTLY gone — a returning
        replica that missed deletes may reference reclaimed rows."""
        self.acks[di].pop(replica, None)

    def stable_epoch(self, di: int) -> int:
        """The doc's compaction floor: the newest epoch every
        registered replica has acked (0 = no floor)."""
        a = self.acks[di]
        return min(a.values()) if a else 0

    # -- lifecycle -----------------------------------------------------
    def compact(self) -> int:
        """Reclaim what the ack floors allow (no-op for map/counter —
        their resident state is already a fold).  Returns rows
        reclaimed."""
        if self.family not in _COMPACTABLE:
            return 0
        floors: List[Optional[int]] = []
        for di in range(self.n_docs):
            e = self.stable_epoch(di)
            # skip docs whose floor hasn't advanced since the last pass
            floors.append(e if e > self._compacted_at[di] else None)
        if all(f is None for f in floors):
            return 0
        with obs.histogram("server.compact_seconds").time(family=self.family):
            n = self.batch.compact(floors)
        obs.counter("server.compact_rows_reclaimed_total").inc(
            n, family=self.family
        )
        for di, f in enumerate(floors):
            if f is not None:
                self._compacted_at[di] = f
        return n

    # -- checkpoint/resume --------------------------------------------
    def checkpoint(self) -> bytes:
        """Batch state + ack floors as one LTKV store."""
        from ..codec.binary import Writer
        from ..storage import MemKvStore

        kv = MemKvStore()
        meta = Writer()
        meta.u8(1)  # server-state version
        meta.str_(self.family)
        meta.varint(self.n_docs)
        meta.varint(len(self._compacted_at))
        for e in self._compacted_at:
            meta.varint(e)
        kv.set(b"server", bytes(meta.buf))
        w = Writer()
        w.varint(len(self.acks))
        for a in self.acks:
            w.varint(len(a))
            for rep, e in a.items():
                w.str_(rep)
                w.varint(e)
        kv.set(b"acks", bytes(w.buf))
        kv.set(b"batch", self.batch.export_state())
        return kv.export_all()

    @classmethod
    def restore(cls, data: bytes, mesh=None) -> "ResidentServer":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, acks_b, batch_b = kv.get(b"server"), kv.get(b"acks"), kv.get(b"batch")
        if meta_b is None or acks_b is None or batch_b is None:
            raise DecodeError("ResidentServer state: missing sections")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > 1:
                raise DecodeError(f"ResidentServer state v{version} too new")
            family = r.str_()
            n_docs = r.varint()
            n_comp = r.varint()
            compacted_at = [r.varint() for _ in range(n_comp)]
            if family not in _FAMILIES or n_comp != n_docs:
                raise DecodeError("ResidentServer state: malformed meta")
            r = Reader(acks_b)
            n_acks = r.varint()
            if n_acks != n_docs:
                raise DecodeError("ResidentServer state: ack table width")
            acks: List[Dict[str, int]] = []
            for _ in range(n_acks):
                a: Dict[str, int] = {}
                for _ in range(r.varint()):
                    rep = r.str_()
                    a[rep] = r.varint()
                acks.append(a)
        except (IndexError, ValueError, UnicodeDecodeError) as e:
            raise DecodeError(f"ResidentServer state: malformed ({e})") from None
        srv = cls.__new__(cls)
        srv.family = family
        srv.n_docs = n_docs
        srv.acks = acks
        srv._compacted_at = compacted_at
        srv.batch = _FAMILIES[family][0].import_state(batch_b, mesh=mesh)
        if srv.batch.n_docs < n_docs:
            raise DecodeError(
                "ResidentServer state: batch narrower than the ack table"
            )
        return srv
