"""Device mesh + sharding layout for fleet merges.

The distributed axis of a CRDT fleet is document-batch parallelism
(SURVEY.md §2.4): docs are independent, so the mesh shards the doc axis
("docs") across chips over ICI and across hosts over DCN.  A second
axis ("ops") is available for intra-doc parallelism of very large
imports (sharded sorts/scans); by default it is size 1 — XLA's sorts
already saturate a chip for the op counts a single doc produces.

No NCCL/MPI analog is needed: merges are embarrassingly parallel per
doc; the only collectives are the result gathers XLA inserts when the
caller asks for replicated output.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOC_AXIS = "docs"
OP_AXIS = "ops"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, op_parallel: int = 1) -> Mesh:
    """1D (docs) or 2D (docs, ops) mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % op_parallel == 0, f"{n} devices not divisible by op_parallel={op_parallel}"
    arr = np.array(devices).reshape(n // op_parallel, op_parallel)
    return Mesh(arr, (DOC_AXIS, OP_AXIS))


def doc_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (doc) axis; replicate the rest."""
    return NamedSharding(mesh, P(DOC_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_doc_batch(mesh: Mesh, tree):
    """Place a pytree of [D, ...] arrays with the doc axis sharded."""
    sh = doc_sharding(mesh)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def shard_meshes(mesh: Mesh, n_shards: int):
    """Carve a ("docs",) / ("docs", "ops") mesh into ``n_shards``
    contiguous doc-axis slices, one sub-mesh per shard (axis names
    preserved, so per-shard batches still shard "ops" when present).
    The sharded resident fleet places each shard's device batch on its
    own sub-mesh; raises typed ConfigError when the doc axis does not
    divide evenly (a ragged carve would skew per-shard capacity)."""
    from ..errors import ConfigError

    devs = np.asarray(mesh.devices)
    rows = devs.shape[0]
    if not isinstance(n_shards, int) or isinstance(n_shards, bool) \
            or n_shards < 1:
        raise ConfigError("shards", n_shards, "positive integer")
    if rows % n_shards:
        raise ConfigError(
            "shards", n_shards,
            f"a divisor of the mesh doc axis ({rows} device row(s))",
        )
    k = rows // n_shards
    return [
        Mesh(devs[s * k:(s + 1) * k], mesh.axis_names)
        for s in range(n_shards)
    ]


def make_global_mesh(op_parallel: int = 1) -> Mesh:
    """Multi-host fleet mesh: all devices across all processes.

    The DCN story for a CRDT fleet is simple because documents are
    independent (SURVEY.md §2.4): shard the doc axis over every chip of
    every host; per-host ingest feeds its local shard (jax makes arrays
    from per-host shards via make_array_from_process_local_data), and
    NO cross-host collectives run during a merge — DCN only carries the
    control plane and any cross-host doc rebalancing.  Call
    jax.distributed.initialize() before this in each host process.
    """
    return make_mesh(jax.devices(), op_parallel=op_parallel)
