"""Device mesh + sharding layout for fleet merges.

The distributed axis of a CRDT fleet is document-batch parallelism
(SURVEY.md §2.4): docs are independent, so the mesh shards the doc axis
("docs") across chips over ICI and across hosts over DCN.  A second
axis ("ops") is available for intra-doc parallelism of very large
imports (sharded sorts/scans); by default it is size 1 — XLA's sorts
already saturate a chip for the op counts a single doc produces.

No NCCL/MPI analog is needed: merges are embarrassingly parallel per
doc; the only collectives are the result gathers XLA inserts when the
caller asks for replicated output.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOC_AXIS = "docs"
OP_AXIS = "ops"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, op_parallel: int = 1) -> Mesh:
    """1D (docs) or 2D (docs, ops) mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % op_parallel == 0, f"{n} devices not divisible by op_parallel={op_parallel}"
    arr = np.array(devices).reshape(n // op_parallel, op_parallel)
    return Mesh(arr, (DOC_AXIS, OP_AXIS))


def doc_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (doc) axis; replicate the rest."""
    return NamedSharding(mesh, P(DOC_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_doc_batch(mesh: Mesh, tree):
    """Place a pytree of [D, ...] arrays with the doc axis sharded."""
    sh = doc_sharding(mesh)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def make_global_mesh(op_parallel: int = 1) -> Mesh:
    """Multi-host fleet mesh: all devices across all processes.

    The DCN story for a CRDT fleet is simple because documents are
    independent (SURVEY.md §2.4): shard the doc axis over every chip of
    every host; per-host ingest feeds its local shard (jax makes arrays
    from per-host shards via make_array_from_process_local_data), and
    NO cross-host collectives run during a merge — DCN only carries the
    control plane and any cross-host doc rebalancing.  Call
    jax.distributed.initialize() before this in each host process.
    """
    return make_mesh(jax.devices(), op_parallel=op_parallel)
