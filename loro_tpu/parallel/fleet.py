"""Fleet merge engine: reconcile batches of documents in one XLA launch.

The north-star path (BASELINE.json): a server holds thousands of docs;
incoming update blobs are decoded host-side into columnar element
tables (ops/columnar.py), the doc axis is sharded over the device mesh,
and one jit launch resolves every document's final sequence order /
LWW winners.  This replaces the reference's per-doc sequential
`OpLog::import -> DiffCalculator` replay (loro.rs:568 -> diff_calc.rs)
with data-parallel kernels.

Shapes are bucket-padded (pad_bucket) so the jit cache stays small
across varying doc sizes.
"""
from __future__ import annotations

import functools
import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.change import Change
from ..core.ids import ContainerID
from ..errors import DeviceFailure
from ..obs import metrics as obs
from ..analysis.lockwitness import named_rlock
from ..resilience import get_supervisor
from ..resilience.faultinject import register_site

register_site(
    "export_launch", "batched delta-export selection launch (fleet "
    "export_select thunk, inside the supervisor): transient retries, "
    "terminal -> DeviceFailure degrades ONLY that window")
from ..utils import tracing
from ..ops.columnar import MapExtract, SeqExtract, extract_seq_container
from ..ops.fugue_batch import SeqColumns, materialize_content_batch, pad_bucket
from ..ops.lww import MapOpCols, lww_merge_doc
from .mesh import DOC_AXIS, OP_AXIS, doc_sharding, make_mesh, replicated


@dataclass
class TextMergeResult:
    texts: List[str]


def _mesh_pad(mesh, d: int) -> int:
    """Doc count padded up to a multiple of the mesh's doc dimension."""
    dm = mesh.shape[DOC_AXIS]
    return ((d + dm - 1) // dm) * dm


def _obs_merge(family: str, docs: int, real_rows: int, padded_rows: int,
               shape: Tuple[int, ...]) -> None:
    """One accounting point per device merge launch (docs/OBSERVABILITY
    .md): real vs padded rows quantify pad_bucket waste, the shape set
    cardinality proxies the jit cache size."""
    obs.counter("fleet.merge_calls_total").inc(family=family)
    obs.counter("fleet.docs_merged_total").inc(docs, family=family)
    obs.counter("fleet.ops_merged_total").inc(real_rows, family=family)
    obs.counter("fleet.pad_waste_rows_total").inc(
        max(0, padded_rows - real_rows), family=family
    )
    obs.counter("fleet.device_launches_total").inc(family=family)
    obs.unique("fleet.padded_shapes_distinct").add((family,) + tuple(shape))


def _obs_fallback(kind: str) -> None:
    """Host-fallback hits: forced Python engines (LORO_PY_ORDER /
    LORO_PY_IDMAP or missing native lib) and per-payload decode
    fallbacks."""
    obs.counter("fleet.host_fallback_total").inc(kind=kind)


def _sup_launch(label: str, thunk):
    """Route one merge launch through the process DeviceSupervisor:
    bounded retry on transient UNAVAILABLE errors, typed DeviceFailure
    on anything terminal, in-flight accounting (docs/RESILIENCE.md).
    Fleet merge thunks are pure (fresh device_put inputs, no donated
    buffers) so retry is safe."""
    return get_supervisor().launch(thunk, label=label)


def _sup_fetch(label: str, value):
    """Supervised host fetch: the merge's sync point (drains the
    in-flight queue through it)."""
    return get_supervisor().fetch(value, label=label)


def _host_degrade(family: str, docs_changes, cid=None):
    """Graceful degradation: re-run a failed device merge on the host
    ``models/`` engine (byte-identical by the differential-fuzz
    contract).  One obs counter per degraded merge."""
    from ..resilience import hostpath

    get_supervisor().note_degradation(f"fleet.{family}")
    obs.counter("fleet.degraded_merges_total").inc(family=family)
    return hostpath.host_merge_changes(family, docs_changes, cid)


def _batch_export_select(batch, family: str, index, requests, sup=None):
    """Shared read-plane selection entry (docs/SYNC.md "Read plane"):
    ONE supervised launch answers a window of ``(doc, frontier)`` pull
    requests against the change-span index (ops/export_batch.py).
    Runs under the batch device lock — selection never mutates batch
    state, but the supervisor's drain fetch must not interleave with a
    buffer-donating grow/evict on the same device queue.  The
    ``export_launch`` fault site fires inside the supervised thunk, so
    an armed failure classifies exactly like a real device error
    (DeviceFailure -> the read batcher degrades that window to the
    oracle)."""
    from ..resilience import faultinject

    sup = sup if sup is not None else get_supervisor()

    def thunk():
        faultinject.check("export_launch")
        return index.select(requests)

    with batch._dev_lock:
        # selection is a pure read of the index grid: retry-safe
        return sup.launch(thunk, label=f"fleet.export.{family}")


def _empty_seq_np(n: int):
    """All-invalid numpy SeqColumns of n rows (doc-axis padding filler)."""
    import numpy as _np

    from ..ops.fugue_batch import SeqColumns, pad_seq_columns

    return pad_seq_columns(
        SeqColumns(
            parent=_np.zeros(0, _np.int32),
            side=_np.zeros(0, _np.int32),
            peer=_np.zeros(0, _np.int32),
            counter=_np.zeros(0, _np.int32),
            deleted=_np.zeros(0, bool),
            content=_np.zeros(0, _np.int32),
            valid=_np.zeros(0, bool),
        ),
        n,
    )


class Fleet:
    """Batched merge front-end bound to a device mesh."""

    def __init__(self, mesh=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self._text_fn = None

    # ------------------------------------------------------------------
    # text / list sequence merge
    # ------------------------------------------------------------------
    def _build_text_fn(self):
        mesh = self.mesh
        in_sh = NamedSharding(mesh, P(DOC_AXIS))
        out_sh = NamedSharding(mesh, P(DOC_AXIS))

        @functools.partial(
            jax.jit,
            in_shardings=(SeqColumns(*([in_sh] * 7)),),
            out_shardings=(out_sh, out_sh),
        )
        def run(cols: SeqColumns):
            return materialize_content_batch(cols)

        return run

    def merge_text_docs(
        self, extracts: Sequence[SeqExtract], pad_docs: Optional[int] = None
    ) -> TextMergeResult:
        """Resolve final text for a batch of documents (one launch).
        Documents are padded to a common bucketed element count and the
        doc axis is padded to a multiple of the mesh's doc dimension."""
        if self._text_fn is None:
            self._text_fn = self._build_text_fn()
        tracing.instant("fleet.merge_text_docs", docs=len(extracts))
        n = pad_bucket(max(e.n for e in extracts))
        d = len(extracts)
        d_pad = pad_docs or _mesh_pad(self.mesh, d)
        _obs_merge("text", d, sum(e.n for e in extracts), n * d_pad, (n, d_pad))
        cols_np = [e.to_seq_columns(pad_to=n) for e in extracts]
        empty = SeqColumns(
            parent=np.full(n, -1, np.int32),
            side=np.zeros(n, np.int32),
            peer=np.zeros(n, np.int32),
            counter=np.zeros(n, np.int32),
            deleted=np.ones(n, bool),
            content=np.full(n, -1, np.int32),
            valid=np.zeros(n, bool),
        )
        cols_np += [empty] * (d_pad - d)
        batched = SeqColumns(
            *[np.stack([getattr(c, f) for c in cols_np]) for f in SeqColumns._fields]
        )
        sh = doc_sharding(self.mesh)
        # the upload is supervised too: a dead tunnel raises
        # synchronously at device_put, and that must be a typed
        # DeviceFailure for the degradation handlers, not a raw crash
        batched = _sup_launch(
            "fleet.text",
            lambda: SeqColumns(*[jax.device_put(a, sh) for a in batched]),
        )
        codes, counts = _sup_launch("fleet.text", lambda: self._text_fn(batched))
        codes = _sup_fetch("fleet.text", codes)
        counts = _sup_fetch("fleet.text", counts)
        texts = [
            "".join(map(chr, codes[i, : counts[i]])) for i in range(d)
        ]
        return TextMergeResult(texts)

    def merge_text_changes(
        self, docs_changes: Sequence[Sequence[Change]], cid: ContainerID
    ) -> TextMergeResult:
        """Convenience: decode + merge each doc's change list.  On a
        supervisor-declared device failure the merge transparently
        re-runs on the host engine (same bytes out, typed counters)."""
        extracts = [extract_seq_container(chs, cid) for chs in docs_changes]
        try:
            return self.merge_text_docs(extracts)
        except DeviceFailure:
            return TextMergeResult(_host_degrade("text", docs_changes, cid))

    def merge_text_payloads(
        self, payloads: Sequence[bytes], cid: ContainerID
    ) -> TextMergeResult:
        """Full ingest pipeline: binary update payloads -> native C++
        wire->SoA decode -> one sharded device launch.  This is the
        server-side bulk-sync path the north star describes: the decode
        stage never materializes Python op objects.

        Payloads are envelope-stripped bytes; integrity (CRC) is the
        envelope layer's job (LoroDoc._parse_envelope) — a corrupted
        payload here decodes to garbage-but-safe output, never a crash.
        """
        from ..codec.binary import decode_changes
        from ..ops.columnar import extract_seq_from_payload

        extracts = []
        for p in payloads:
            try:
                ex = extract_seq_from_payload(p, cid)
            except ValueError:
                # native path can't resolve (e.g. incremental payload
                # referencing elements outside it): python fallback
                ex = None
            if ex is None:
                _obs_fallback("payload_extract")
                try:
                    ex = extract_seq_container(decode_changes(p), cid)
                except KeyError as e:
                    raise ValueError(
                        "payload is not self-contained (references elements "
                        f"outside it: {e}); one-shot fleet merges need full-"
                        "history payloads — use DeviceDocBatch for deltas"
                    ) from e
            extracts.append(ex)
        try:
            return self.merge_text_docs(extracts)
        except DeviceFailure:
            return TextMergeResult(
                _host_degrade("text", [decode_changes(p) for p in payloads], cid)
            )

    # ------------------------------------------------------------------
    # rich text merge
    # ------------------------------------------------------------------
    def merge_richtext_changes(self, docs_changes: Sequence[Sequence[Change]], cid) -> List[list]:
        """Batched rich-text merge: per-doc change lists -> Quill-style
        segment lists with resolved styles (one vmapped launch)."""
        from ..ops.fugue_batch import ChainColumns, pad_bucket
        from ..ops.richtext_batch import (
            RichtextChainCols,
            extract_richtext_chain,
            pad_richtext_chain_cols,
            richtext_chain_merge_batch,
        )

        extracts = [extract_richtext_chain(chs, cid) for chs in docs_changes]
        n = pad_bucket(max(1, max(c.chain.chain_id.shape[0] for c, _, _ in extracts)))
        cpad = pad_bucket(max(1, max(c.chain.c_parent.shape[0] for c, _, _ in extracts)))
        p = pad_bucket(max(1, max(c.pair_start.shape[0] for c, _, _ in extracts)), floor=16)
        n_keys = pad_bucket(max(1, max(len(k) for _, k, _ in extracts)), floor=4)
        d = len(extracts)
        d_pad = _mesh_pad(self.mesh, d)
        _obs_merge(
            "richtext",
            d,
            sum(c.chain.chain_id.shape[0] for c, _, _ in extracts),
            n * d_pad,
            (n, cpad, p, n_keys, d_pad),
        )

        padded = [
            pad_richtext_chain_cols(c, pad_n=n, pad_c=cpad, pad_p=p)
            for c, _, _ in extracts
        ]
        if len(padded) < d_pad:  # doc-axis pad: one shared all-pad doc
            empty = pad_richtext_chain_cols(
                RichtextChainCols(
                    chain=ChainColumns(
                        c_parent=np.zeros(0, np.int32),
                        c_side=np.zeros(0, np.int32),
                        c_valid=np.zeros(0, bool),
                        head_row=np.zeros(0, np.int32),
                        chain_id=np.zeros(0, np.int32),
                        deleted=np.zeros(0, bool),
                        content=np.zeros(0, np.int32),
                        valid=np.zeros(0, bool),
                    ),
                    pair_start=np.zeros(0, np.int32),
                    pair_end=np.zeros(0, np.int32),
                    pair_key=np.zeros(0, np.int32),
                    pair_value=np.zeros(0, np.int32),
                    pair_lamport=np.zeros(0, np.int32),
                    pair_peer=np.zeros(0, np.int32),
                    pair_valid=np.zeros(0, bool),
                ),
                pad_n=n,
                pad_c=cpad,
                pad_p=p,
            )
            padded.extend([empty] * (d_pad - len(padded)))
        sh = doc_sharding(self.mesh)

        def upload():
            return RichtextChainCols(
                chain=ChainColumns(
                    *[
                        jax.device_put(np.stack([getattr(q.chain, f) for q in padded]), sh)
                        for f in ChainColumns._fields
                    ]
                ),
                **{
                    f: jax.device_put(np.stack([getattr(q, f) for q in padded]), sh)
                    for f in RichtextChainCols._fields
                    if f != "chain"
                },
            )

        try:
            cols = _sup_launch("fleet.richtext", upload)
            codes, counts, bounds, win = _sup_launch(
                "fleet.richtext", lambda: richtext_chain_merge_batch(cols, n_keys)
            )
            codes = _sup_fetch("fleet.richtext", codes)
            counts = _sup_fetch("fleet.richtext", counts)
            bounds = _sup_fetch("fleet.richtext", bounds)
            win = _sup_fetch("fleet.richtext", win)
        except DeviceFailure:
            return _host_degrade("richtext", docs_changes, cid)
        results = []
        for i, (_, keys, values) in enumerate(extracts):
            text = "".join(map(chr, codes[i, : counts[i]]))
            segs: List[dict] = []
            for r in range(bounds.shape[1] - 1):
                lo, hi = int(bounds[i, r]), int(bounds[i, r + 1])
                if lo >= hi:
                    continue
                attrs = {}
                for ki in range(len(keys)):
                    vi = int(win[i, r, ki])
                    if vi >= 0:
                        attrs[keys[ki]] = values[vi]
                seg: dict = {"insert": text[lo:hi]}
                if attrs:
                    seg["attributes"] = attrs
                if segs and segs[-1].get("attributes") == seg.get("attributes"):
                    segs[-1]["insert"] += seg["insert"]
                else:
                    segs.append(seg)
            results.append(segs)
        return results

    # ------------------------------------------------------------------
    # movable list merge
    # ------------------------------------------------------------------
    def merge_movable_changes(self, docs_changes: Sequence[Sequence[Change]], cid) -> List[list]:
        """Batched movable-list merge: per-doc change lists -> final
        value lists (one vmapped launch)."""
        from ..ops.movable_batch import extract_movable

        try:
            return self._merge_movable_extracted(
                [extract_movable(chs, cid) for chs in docs_changes]
            )
        except DeviceFailure:
            return _host_degrade("movable", docs_changes, cid)

    def merge_movable_payloads(self, payloads: Sequence[bytes], cid) -> List[list]:
        """Native ingest: envelope-stripped update payloads -> C++
        movable explode -> one launch.  Values decode lazily (winners
        only); unresolvable payloads fall back to the Python decoder."""
        from ..codec.binary import decode_changes
        from ..ops.movable_batch import extract_movable, extract_movable_from_payload

        extracts = []
        for p in payloads:
            try:
                ex = extract_movable_from_payload(p, cid)
            except ValueError:
                ex = None
            if ex is None:
                _obs_fallback("payload_extract")
                try:
                    ex = extract_movable(decode_changes(p), cid)
                except KeyError as e:
                    raise ValueError(
                        "payload is not self-contained (references elements "
                        f"outside it: {e}); one-shot fleet merges need full-"
                        "history payloads — use DeviceDocBatch for deltas"
                    ) from e
            extracts.append(ex)
        try:
            return self._merge_movable_extracted(extracts)
        except DeviceFailure:
            return _host_degrade(
                "movable", [decode_changes(p) for p in payloads], cid
            )

    def _merge_movable_extracted(self, extracts) -> List[list]:
        import jax.numpy as jnp

        from ..ops.fugue_batch import SeqColumns, pad_bucket, pad_seq_columns
        from ..ops.movable_batch import (
            LazyPayloadValue,
            MovableCols,
            movable_merge_batch,
        )
        s = pad_bucket(max(1, max(c.seq.parent.shape[0] for c, _, _ in extracts)))
        k = pad_bucket(max(1, max(c.set_elem.shape[0] for c, _, _ in extracts)), floor=16)
        n_elems = pad_bucket(max(1, max(len(e) for _, e, _ in extracts)), floor=16)
        d = len(extracts)
        d_pad = _mesh_pad(self.mesh, d)
        _obs_merge(
            "movable",
            d,
            sum(c.seq.parent.shape[0] + c.set_elem.shape[0] for c, _, _ in extracts),
            (s + k) * d_pad,
            (s, k, n_elems, d_pad),
        )

        def padk(a, fill, dtype):
            out = np.full(k, fill, dtype)
            out[: a.shape[0]] = a
            return out

        def pads(a, fill, dtype):
            out = np.full(s, fill, dtype)
            out[: a.shape[0]] = a
            return out

        seq_stack = []
        lam, se, sl, sp, sv, svd = [], [], [], [], [], []
        for c, _, _ in extracts:
            seq_stack.append(pad_seq_columns(c.seq, s))
            lam.append(pads(c.lamport, 0, np.int32))
            se.append(padk(c.set_elem, 0, np.int32))
            sl.append(padk(c.set_lamport, 0, np.int32))
            sp.append(padk(c.set_peer, 0, np.int32))
            sv.append(padk(c.set_value, 0, np.int32))
            svd.append(padk(c.set_valid, False, bool))
        empty_seq = _empty_seq_np(s)
        while len(seq_stack) < d_pad:
            seq_stack.append(empty_seq)
            lam.append(np.zeros(s, np.int32))
            se.append(np.zeros(k, np.int32))
            sl.append(np.zeros(k, np.int32))
            sp.append(np.zeros(k, np.int32))
            sv.append(np.zeros(k, np.int32))
            svd.append(np.zeros(k, bool))
        sh = doc_sharding(self.mesh)
        cols = _sup_launch("fleet.movable", lambda: MovableCols(
            seq=SeqColumns(
                *[
                    jax.device_put(np.stack([getattr(q, f) for q in seq_stack]), sh)
                    for f in SeqColumns._fields
                ]
            ),
            lamport=jax.device_put(np.stack(lam), sh),
            set_elem=jax.device_put(np.stack(se), sh),
            set_lamport=jax.device_put(np.stack(sl), sh),
            set_peer=jax.device_put(np.stack(sp), sh),
            set_value=jax.device_put(np.stack(sv), sh),
            set_valid=jax.device_put(np.stack(svd), sh),
        ))
        out, counts = _sup_launch(
            "fleet.movable", lambda: movable_merge_batch(cols, n_elems)
        )
        out = _sup_fetch("fleet.movable", out)
        counts = _sup_fetch("fleet.movable", counts)
        results = []
        for i, (_, _, values) in enumerate(extracts):
            idxs = out[i, : counts[i]]
            row = []
            for j in idxs:
                v = values[j] if j >= 0 else None
                if isinstance(v, LazyPayloadValue):
                    v = v.get()  # winners only ever decode
                row.append(v)
            results.append(row)
        return results

    # ------------------------------------------------------------------
    # tree merge
    # ------------------------------------------------------------------
    def merge_tree_changes(self, docs_changes: Sequence[Sequence[Change]], cid) -> List[dict]:
        """Batched movable-tree merge: per-doc change lists -> parent
        maps {TreeID: parent TreeID | None} of alive nodes."""
        from ..ops.tree_batch import extract_tree_ops

        try:
            return self._merge_tree_extracted(
                [extract_tree_ops(chs, cid) for chs in docs_changes]
            )
        except DeviceFailure:
            return _host_degrade("tree", docs_changes, cid)

    def merge_tree_payloads(self, payloads: Sequence[bytes], cid) -> List[dict]:
        """Native ingest: envelope-stripped update payloads -> C++ tree
        explode -> one launch (no per-op Python objects).  Falls back to
        the Python decoder per payload on unresolvable input."""
        from ..codec.binary import decode_changes
        from ..ops.tree_batch import extract_tree_from_payload, extract_tree_ops

        extracted = []
        for p in payloads:
            try:
                ex = extract_tree_from_payload(p, cid)
            except ValueError:
                ex = None
            if ex is None:
                # tree ops carry no intra-payload row references, so the
                # Python fallback is total
                _obs_fallback("payload_extract")
                ex = extract_tree_ops(decode_changes(p), cid)
            extracted.append(ex)
        try:
            return self._merge_tree_extracted(extracted)
        except DeviceFailure:
            return _host_degrade("tree", [decode_changes(p) for p in payloads], cid)

    def _merge_tree_extracted(self, extracted) -> List[dict]:
        import jax.numpy as jnp

        from ..ops.fugue_batch import pad_bucket
        from ..ops.tree_batch import (
            ABSENT,
            ROOT,
            TRASH,
            TreeOpCols,
            is_deleted_batch,
            pad_tree_cols,
            tree_merge_batch,
        )

        m = pad_bucket(max(1, max(c.target.shape[0] for c, _, _ in extracted)), floor=16)
        n = max(1, max(len(nodes) for _, nodes, _ in extracted))
        d = len(extracted)
        d_pad = _mesh_pad(self.mesh, d)
        _obs_merge(
            "tree", d, sum(c.target.shape[0] for c, _, _ in extracted),
            m * d_pad, (m, n, d_pad),
        )
        padded = [pad_tree_cols(c, m) for c, _, _ in extracted]
        empty = TreeOpCols(
            target=np.zeros(m, np.int32), parent=np.full(m, ROOT, np.int32), valid=np.zeros(m, bool)
        )
        padded += [empty] * (d_pad - d)
        sh = doc_sharding(self.mesh)
        cols = _sup_launch("fleet.tree", lambda: TreeOpCols(
            *[jax.device_put(np.stack([getattr(c, f) for c in padded]), sh) for f in TreeOpCols._fields]
        ))
        parents, eff = _sup_launch(
            "fleet.tree", lambda: tree_merge_batch(cols, n)
        )
        deleted = _sup_fetch(
            "fleet.tree", _sup_launch("fleet.tree", lambda: is_deleted_batch(parents))
        )
        parents = _sup_fetch("fleet.tree", parents)
        eff = _sup_fetch("fleet.tree", eff)
        out = []
        for i, (c, nodes, row_pos) in enumerate(extracted):
            res = {}
            for j, tid in enumerate(nodes):
                p = int(parents[i, j])
                if p == ABSENT or deleted[i, j]:
                    continue
                res[tid] = None if p == ROOT else nodes[p]
            out.append(res)
        return out

    def merge_tree_children(self, docs_changes: Sequence[Sequence[Change]], cid) -> List[dict]:
        """Like merge_tree_changes but returns ordered children maps
        {parent|None: [child TreeIDs in (fractional-index, move-key)
        order]} — the full materialized tree shape."""
        from ..ops.fugue_batch import pad_bucket
        from ..ops.tree_batch import (
            ABSENT,
            ROOT,
            TreeOpCols,
            extract_tree_ops,
            is_deleted_batch,
            pad_tree_cols,
            positions_of,
            tree_merge_batch,
        )

        extracted = [extract_tree_ops(chs, cid) for chs in docs_changes]
        m = pad_bucket(max(1, max(c.target.shape[0] for c, _, _ in extracted)), floor=16)
        n = max(1, max(len(nodes) for _, nodes, _ in extracted))
        d = len(extracted)
        d_pad = _mesh_pad(self.mesh, d)
        # distinct family: the children materialization runs extra
        # kernels, so its shapes must not alias _merge_tree_extracted's
        # in the jit-cache proxy
        _obs_merge(
            "tree_children", d, sum(c.target.shape[0] for c, _, _ in extracted),
            m * d_pad, (m, n, d_pad),
        )
        padded = [pad_tree_cols(c, m) for c, _, _ in extracted]
        empty = TreeOpCols(
            target=np.zeros(m, np.int32), parent=np.full(m, ROOT, np.int32), valid=np.zeros(m, bool)
        )
        padded += [empty] * (d_pad - d)
        sh = doc_sharding(self.mesh)
        try:
            cols = _sup_launch("fleet.tree_children", lambda: TreeOpCols(
                *[jax.device_put(np.stack([getattr(c, f) for c in padded]), sh) for f in TreeOpCols._fields]
            ))
            parents, eff = _sup_launch(
                "fleet.tree_children", lambda: tree_merge_batch(cols, n)
            )
            deleted = _sup_fetch(
                "fleet.tree_children",
                _sup_launch("fleet.tree_children", lambda: is_deleted_batch(parents)),
            )
            parents = _sup_fetch("fleet.tree_children", parents)
            eff = _sup_fetch("fleet.tree_children", eff)
        except DeviceFailure:
            return _host_degrade("tree_children", docs_changes, cid)
        out = []
        for i, (c, nodes, row_pos) in enumerate(extracted):
            n_rows = c.target.shape[0]
            e_i = eff[i, :n_rows]
            pos = positions_of(c, row_pos, e_i)
            # sibling tiebreak = the winning move's key; rows are sorted
            # by (lamport, peer, counter) so the row index is that order
            last_eff_row: Dict[int, int] = {}
            for j in range(n_rows):
                if e_i[j]:
                    last_eff_row[int(c.target[j])] = j
            kids: Dict = {}
            for j, tid in enumerate(nodes):
                p = int(parents[i, j])
                if p == ABSENT or deleted[i, j]:
                    continue
                parent_t = None if p == ROOT else nodes[p]
                kids.setdefault(parent_t, []).append(
                    (pos.get(j) or b"", last_eff_row.get(j, 0), tid)
                )
            out.append(
                {k: [t for _, _, t in sorted(v, key=lambda x: (x[0], x[1]))] for k, v in kids.items()}
            )
        return out

    # ------------------------------------------------------------------
    # counter merge
    # ------------------------------------------------------------------
    def merge_counter_changes(self, docs_changes: Sequence[Sequence[Change]]) -> List[Dict]:
        """Batched counter merge: per-doc change lists -> {container:
        sum} (order-independent segment sums, one launch)."""
        from ..core.change import CounterIncr
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import counter_merge_batch

        rows_per_doc = []
        cids_per_doc = []
        for changes in docs_changes:
            rows = []
            cid_of: Dict[ContainerID, int] = {}
            cids: List[ContainerID] = []
            for ch in changes:
                for op in ch.ops:
                    if not isinstance(op.content, CounterIncr):
                        continue
                    if op.container not in cid_of:
                        cid_of[op.container] = len(cids)
                        cids.append(op.container)
                    rows.append((cid_of[op.container], op.content.delta))
            rows_per_doc.append(rows)
            cids_per_doc.append(cids)
        m = pad_bucket(max(1, max(len(r) for r in rows_per_doc)), floor=16)
        s = max(1, max(len(c) for c in cids_per_doc))
        d = len(docs_changes)
        d_pad = _mesh_pad(self.mesh, d)
        _obs_merge(
            "counter", d, sum(len(r) for r in rows_per_doc),
            m * d_pad, (m, s, d_pad),
        )
        slot = np.zeros((d_pad, m), np.int32)
        delta = np.zeros((d_pad, m), np.float32)
        valid = np.zeros((d_pad, m), bool)
        for di, rows in enumerate(rows_per_doc):
            for j, (s_, dv) in enumerate(rows):
                slot[di, j] = s_
                delta[di, j] = dv
                valid[di, j] = True
        sh = doc_sharding(self.mesh)
        try:
            sums = _sup_fetch(
                "fleet.counter",
                _sup_launch(
                    "fleet.counter",
                    lambda: counter_merge_batch(
                        jax.device_put(slot, sh), jax.device_put(delta, sh),
                        jax.device_put(valid, sh), s,
                    ),
                ),
            )
        except DeviceFailure:
            return _host_degrade("counter", docs_changes)
        return [
            {cid: float(sums[di, j]) for j, cid in enumerate(cids_per_doc[di])}
            for di in range(d)
        ]

    # ------------------------------------------------------------------
    # LWW map merge
    # ------------------------------------------------------------------
    def _batch_map_cols(self, extracts: Sequence[MapExtract], m: int) -> MapOpCols:
        """Stack per-doc MapExtract rows into padded [D, M] columns."""
        d_pad = _mesh_pad(self.mesh, len(extracts))

        def col(rows_list, fill, dtype):
            out = np.full((d_pad, m), fill, dtype)
            for i, r in enumerate(rows_list):
                out[i, : len(r)] = r
            return out

        return MapOpCols(
            slot=col([e.slot for e in extracts], 0, np.int32),
            lamport=col([e.lamport for e in extracts], 0, np.int32),
            peer=col([e.peer for e in extracts], 0, np.int32),
            value_idx=col([e.value_idx for e in extracts], 0, np.int32),
            valid=col([e.valid for e in extracts], False, bool),
        )

    def merge_map_docs(self, extracts: Sequence[MapExtract]) -> List[Dict[str, object]]:
        """Resolve LWW winners for a batch of docs; returns per-doc
        {key: value} for root map containers."""
        m = pad_bucket(max(1, max(len(e.slot) for e in extracts)))
        s = max(1, max(len(e.slots) for e in extracts))
        d_pad = _mesh_pad(self.mesh, len(extracts))
        _obs_merge(
            "map", len(extracts), sum(len(e.slot) for e in extracts),
            m * d_pad, (m, s, d_pad),
        )
        batched = self._batch_map_cols(extracts, m)
        sh = doc_sharding(self.mesh)
        batched = _sup_launch("fleet.map", lambda: MapOpCols(
            *[jax.device_put(np.asarray(a), sh) for a in batched]
        ))
        fn = _lww_batch_fn(self.mesh, s)
        vi, _, _ = _sup_launch("fleet.map", lambda: fn(batched))
        return self._map_winner_values(_sup_fetch("fleet.map", vi), extracts)

    def _map_winner_values(self, vi: np.ndarray, extracts) -> List[Dict[str, object]]:
        out: List[Dict[str, object]] = []
        for i, e in enumerate(extracts):
            got: Dict[str, object] = {}
            for si, (cid, key) in enumerate(e.slots):
                idx = int(vi[i, si])
                if idx >= 0:
                    got[key] = e.values[idx]
            out.append(got)
        return out

    def merge_map_docs_sharded(self, extracts: Sequence[MapExtract]) -> List[Dict[str, object]]:
        """Op-axis-sharded LWW merge for very large imports (SURVEY.md
        §2.4 "sp"): op rows shard over the mesh's ops axis; per-shard
        scatter-max partials combine with pmax collectives.  Requires a
        Fleet built on a 2D mesh (make_mesh(op_parallel=k)).  Same
        output contract as merge_map_docs."""
        op_dim = self.mesh.shape[OP_AXIS]
        if op_dim <= 1:
            return self.merge_map_docs(extracts)
        m = pad_bucket(max(1, max(len(e.slot) for e in extracts)))
        m = ((m + op_dim - 1) // op_dim) * op_dim  # divisible by the op axis
        s = max(1, max(len(e.slots) for e in extracts))
        d_pad = _mesh_pad(self.mesh, len(extracts))
        _obs_merge(
            "map_sharded", len(extracts), sum(len(e.slot) for e in extracts),
            m * d_pad, (m, s, d_pad, op_dim),
        )
        batched = self._batch_map_cols(extracts, m)
        sh = NamedSharding(self.mesh, P(DOC_AXIS, OP_AXIS))
        batched = _sup_launch("fleet.map_sharded", lambda: MapOpCols(
            *[jax.device_put(np.asarray(a), sh) for a in batched]
        ))
        fn = _lww_sharded_fn(self.mesh, s)
        vi, _, _ = _sup_launch("fleet.map_sharded", lambda: fn(batched))
        return self._map_winner_values(_sup_fetch("fleet.map_sharded", vi), extracts)


def _pad_axis1(arrays: Dict[str, "jax.Array"], new_n: int, fills: Dict[str, object], sh) -> Dict[str, "jax.Array"]:
    """Re-pad (d, n) device arrays to (d, new_n) with per-field fills —
    the repack half of the resident grow path.  Host round trip: growth
    is rare (power-of-two buckets) and the simple path is shape-safe."""
    out = {}
    for f, a in arrays.items():
        h = np.asarray(a)
        nh = np.full((h.shape[0], new_n), fills[f], h.dtype)
        nh[:, : h.shape[1]] = h
        out[f] = jax.device_put(nh, sh)
    return out


def _grow_target(required: int, current: int) -> int:
    """Next power-of-two-style bucket >= required, at least 2x current
    (avoids repeated small regrows)."""
    from ..ops.fugue_batch import pad_bucket

    return pad_bucket(required, floor=max(16, 2 * current))


def _lww_fills(value_fill: int) -> Dict[str, object]:
    """Fill values for LwwResident columns — ONE table shared by the
    grow()/import paths of the map and movable batches so they cannot
    drift from each other (the value fill is the only per-use field)."""
    from ..ops.lww import NEG

    return dict(lamport=int(NEG), peer_hi=0, peer_lo=0, value=value_fill)


def _resolve_row(overlay, idmap, key, di, what):
    """Overlay-then-idmap row lookup that raises a typed, actionable
    error for unknown ids (shared by every resident ingest walk)."""
    r = overlay.get(key)
    if r is not None:
        return r
    try:
        return idmap[key]
    except KeyError:
        from ..errors import LoroError

        raise LoroError(
            f"doc {di}: {what} references unknown element {key} — resident "
            "batches need every doc's FULL history from its first epoch "
            "(feed the base import before deltas)"
        ) from None


class DeviceDocBatch:
    """Device-resident document batch with incremental ingest.

    SURVEY.md §7 step 9: "state lives on device for bulk workloads".
    The element tables stay on device between syncs; each `append` ships
    only the new rows/tombstones, and `texts()` re-resolves order in one
    launch.  Uses the row-order-free kernel (SeqColumnsU) because
    appended rows land in the buffer tail, not in (peer, counter) order.
    """

    def __init__(self, n_docs: int, capacity: int, mesh=None, as_text: bool = True,
                 auto_grow: bool = False):
        """as_text=False holds List containers: contents become per-doc
        value ordinals (host keeps the value stores) and values() is the
        materializer instead of texts().  auto_grow=True repacks the
        batch to the next capacity bucket instead of raising when an
        append overflows (long-lived server lifecycle)."""
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_docs = n_docs
        d_mesh = self.mesh.shape[DOC_AXIS]
        self.d = ((n_docs + d_mesh - 1) // d_mesh) * d_mesh  # mesh-padded
        n_docs = self.d
        self.cap = capacity
        self.as_text = as_text
        self.auto_grow = auto_grow
        self._c_pad = 256  # chain budget (doubles on overflow)
        self.counts = np.zeros(n_docs, np.int64)  # used rows per doc
        # ingest epochs date rows + tombstones for compaction: a
        # tombstone may be reclaimed once every replica has acked the
        # epoch that ingested its delete; row dates let layered batches
        # (DeviceMovableBatch) date supersessions by their winner's
        # ingest epoch (see compact())
        self.epoch = 0
        self.tomb_epoch = np.full((n_docs, capacity), -1, np.int64)
        self.row_epoch = np.full((n_docs, capacity), -1, np.int64)
        # host-side id -> row resolution per doc (C++ hash map when the
        # native lib is available; batch stage/lookup/commit contract —
        # see parallel/idmap.py)
        from .idmap import make_idmap

        self.id2row = [make_idmap() for _ in range(n_docs)]
        self.value_store: List[List] = [[] for _ in range(n_docs)]
        # richtext: per-doc style-anchor metadata ((peer, ctr) -> dict)
        # + device-row backmap so delete tombstones deactivate pairs
        self.anchor_meta: List[Dict[Tuple[int, int], dict]] = [dict() for _ in range(n_docs)]
        self.anchor_by_row: List[Dict[int, Tuple[int, int]]] = [dict() for _ in range(n_docs)]
        # incremental order: per-doc host ShadowOrder assigns standing
        # 64-bit order keys in O(delta); materialization sorts by key
        # instead of re-ranking the table (VERDICT round-1 item 4).
        # The C++ engine (native/codec.cpp loro_order_*) is used when
        # available — bit-identical keys; LORO_PY_ORDER=1 forces the
        # Python engine (the differential oracle).
        self.order = [self._fresh_order() for _ in range(n_docs)]
        from ..ops.fugue_batch import SeqColumnsU

        sh = doc_sharding(self.mesh)
        z = lambda dt, fill: jax.device_put(
            np.full((n_docs, capacity), fill, dt), sh
        )
        self.cols = SeqColumnsU(
            parent=z(np.int32, -1),
            side=z(np.int32, 0),
            peer_hi=z(np.uint32, 0),
            peer_lo=z(np.uint32, 0),
            counter=z(np.int32, 0),
            deleted=z(bool, True),
            content=z(np.int32, -1),
            valid=z(bool, False),
        )
        self.key_hi = z(np.uint32, 0xFFFFFFFF)
        self.key_lo = z(np.uint32, 0xFFFFFFFF)
        # coalesced-ingest accumulator (None = every append launches its
        # own device scatter; see begin_coalesce)
        self._defer: Optional[_DeferredSeqDevice] = None
        # serializes device-array writers: a detached commit (pipeline
        # commit thread) vs a grow() triggered by the NEXT group's host
        # staging — the only two that can ever overlap
        self._dev_lock = named_rlock("fleet.dev")

    # column fill values shared by __init__, grow() and compact() —
    # one table so the three cannot drift
    _COL_FILLS = dict(
        parent=-1, side=0, peer_hi=0, peer_lo=0, counter=0,
        deleted=True, content=-1, valid=False,
    )

    # -- round coalescing ----------------------------------------------
    # Contract (docs/RESILIENCE.md "round coalescing"): between
    # begin_coalesce() and flush_coalesce(), every append commits its
    # HOST state per round exactly as before — epoch clock, row/tomb
    # epoch stamps, order engines, id maps, counts — so the final state
    # is byte-for-byte what the serial path produces; only the DEVICE
    # block scatters/tombstone launches accumulate, and flush ships
    # them as ONE scatter (+ one tombstone launch) for the whole group.
    # Reads between begin and flush would see stale device columns:
    # the caller (ResidentServer.ingest_coalesced) never reads inside a
    # group.
    def begin_coalesce(self) -> None:
        if self._defer is not None:
            raise RuntimeError("coalesce group already open")
        self._defer = _DeferredSeqDevice(self.counts.copy())

    def detach_coalesce(self):
        """Close the group and hand back its pending device work for a
        later ``commit_detached`` — the two-phase form the pipeline
        executor uses to overlap group N's device commit with group
        N+1's host staging.  Everything the commit needs from host
        state is SNAPSHOTTED here (renumbered key rows, group-start
        offsets), so the commit thread never reads order engines / id
        maps / epoch arrays the next group is already mutating."""
        d, self._defer = self._defer, None
        if d is not None and d.renumbered:
            d.key_snap = {
                di: np.asarray(self.order[di].all_keys(), np.int64).copy()
                for di in sorted(d.renumbered)
            }
        return d

    def commit_detached(self, d) -> None:
        """Ship a detached group's blocks as one merged scatter + one
        tombstone launch.  Per-doc row segments across rounds are
        contiguous (appends only ever extend the tail), so the merged
        block is each doc's concatenated segments at its group-start
        offset.  Never grows: a grow here would race the next group's
        host staging (epoch arrays repack) — a merged window that
        outgrew capacity by bucket rounding falls back to per-round
        scatters, each already validated at stage time."""
        from ..ops.fugue_batch import pad_bucket

        if d is None:
            return
        with self._dev_lock:
            if d.rounds:
                total = np.zeros(self.d, np.int64)
                for _blk, _kh, _kl, n_new in d.rounds:
                    total += np.asarray(n_new, np.int64)
                width = pad_bucket(int(total.max()), floor=16)
                need = max(
                    (int(d.base0[di]) + width
                     for di in range(self.d) if total[di]),
                    default=0,
                )
                if need > self.cap:
                    off = d.base0.astype(np.int64).copy()
                    for blk, kh, kl, n_new in d.rounds:
                        self._device_commit_block(
                            blk, kh, kl, off.astype(np.int32), n_new,
                            renumbered=(),
                        )
                        off += np.asarray(n_new, np.int64)
                    if d.renumbered:
                        self._upload_renumbered_keys(
                            sorted(d.renumbered), d.key_snap
                        )
                else:
                    blk_shape = (self.d, width)
                    blk = {
                        f: np.full(blk_shape, fill,
                                   dtype=d.rounds[0][0][f].dtype)
                        for f, fill in self._COL_FILLS.items()
                    }
                    khc = np.full(blk_shape, 0xFFFFFFFF, np.uint32)
                    klc = np.full(blk_shape, 0xFFFFFFFF, np.uint32)
                    pos = np.zeros(self.d, np.int64)
                    for rblk, rkh, rkl, n_new in d.rounds:
                        for di, k in enumerate(n_new):
                            if not k:
                                continue
                            p = int(pos[di])
                            for f in blk:
                                blk[f][di, p : p + k] = rblk[f][di, :k]
                            khc[di, p : p + k] = rkh[di, :k]
                            klc[di, p : p + k] = rkl[di, :k]
                            pos[di] += k
                    self._device_commit_block(
                        blk, khc, klc, d.base0.astype(np.int32), total,
                        sorted(d.renumbered), d.key_snap,
                    )
                obs.counter("pipeline.coalesced_rounds_total").inc(
                    len(d.rounds), family="text" if self.as_text else "list"
                )
            elif d.renumbered:
                # delete-only / no-op rounds can still renumber docs
                self._upload_renumbered_keys(sorted(d.renumbered), d.key_snap)
            if d.del_d:
                self._device_mark_deleted(
                    np.concatenate(d.del_d), np.concatenate(d.del_r)
                )

    def flush_coalesce(self) -> None:
        """Synchronous close-and-commit of the open group."""
        self.commit_detached(self.detach_coalesce())

    def _device_commit_block(self, blk, key_blk_hi, key_blk_lo, offsets,
                             n_new, renumbered, key_snap=None) -> None:
        """The device tail of an append: one block scatter (+ whole-row
        key re-uploads for renumbered docs).  Shared by the immediate
        path and commit_detached."""
        width = blk["valid"].shape[1]
        obs.counter("fleet.pad_waste_rows_total").inc(
            int(self.d * width - int(np.sum(n_new))), family="resident_seq"
        )
        obs.counter("fleet.device_launches_total").inc(family="resident_seq")
        obs.unique("fleet.padded_shapes_distinct").add(
            ("resident_seq", self.d, width, self.cap)
        )
        with self._dev_lock:
            sh = doc_sharding(self.mesh)
            blk_dev = {f: jax.device_put(v, sh) for f, v in blk.items()}
            blk_dev["key_hi"] = jax.device_put(key_blk_hi, sh)
            blk_dev["key_lo"] = jax.device_put(key_blk_lo, sh)
            packed = _scatter_rows(
                (self.cols, self.key_hi, self.key_lo),
                blk_dev,
                jax.device_put(
                    np.asarray(offsets, np.int32), replicated(self.mesh)
                ),
            )
            self.cols, self.key_hi, self.key_lo = packed
            if renumbered:
                self._upload_renumbered_keys(list(renumbered), key_snap)

    def _upload_renumbered_keys(self, renumbered, key_snap=None) -> None:
        """Renumbered docs: re-upload whole key rows in ONE jitted
        scatter (the per-doc eager .at[di].set dispatch was ~half of
        warm epoch time — r5 profile).  Fixed [cap]-wide rows + bucket-
        padded doc count bound retraces; pad entries repeat doc
        renumbered[0]'s row (idempotent writes).  ``key_snap`` (doc ->
        key array) is the detach-time snapshot a pipelined commit uses
        — the live engines belong to the group being staged."""
        from ..ops.fugue_batch import pad_bucket

        from .order_maintenance import split_keys

        nb = pad_bucket(len(renumbered), floor=4)
        kh_rows = np.empty((nb, self.cap), np.uint32)
        kl_rows = np.empty((nb, self.cap), np.uint32)
        d_idx = np.empty(nb, np.int32)
        for i in range(nb):
            di = renumbered[i] if i < len(renumbered) else renumbered[0]
            d_idx[i] = di
            if i < len(renumbered):
                keys = (
                    key_snap[di] if key_snap is not None
                    else self.order[di].all_keys()
                )
                kh, kl = split_keys(np.asarray(keys, np.int64))
                kh_rows[i, : len(kh)] = kh
                kl_rows[i, : len(kl)] = kl
                kh_rows[i, len(kh):] = 0xFFFFFFFF
                kl_rows[i, len(kl):] = 0xFFFFFFFF
            else:
                kh_rows[i] = kh_rows[0]
                kl_rows[i] = kl_rows[0]
        with self._dev_lock:
            self.key_hi, self.key_lo = _set_key_rows(
                (self.key_hi, self.key_lo),
                jnp.asarray(d_idx),
                jnp.asarray(kh_rows),
                jnp.asarray(kl_rows),
            )

    def _device_mark_deleted(self, d_all: np.ndarray, r_all: np.ndarray) -> None:
        """The device tail of mark_deleted (padded tombstone scatter)."""
        from ..ops.fugue_batch import pad_bucket

        n = len(d_all)
        k = pad_bucket(n, floor=16)
        d_idx = np.empty(k, np.int32)
        r_idx = np.empty(k, np.int32)
        d_idx[:n], r_idx[:n] = d_all, r_all
        d_idx[n:], r_idx[n:] = d_all[0], r_all[0]
        with self._dev_lock:
            deleted = _set_deleted(
                self.cols.deleted, jnp.asarray(d_idx), jnp.asarray(r_idx)
            )
            self.cols = self.cols._replace(deleted=deleted)

    # ------------------------------------------------------------------
    def grow(self, new_capacity: int) -> None:
        """Repack the resident columns to a larger row capacity (device
        re-pad; order engines, id maps, counts and host metadata are
        capacity-independent).  Part of the resident lifecycle: a
        long-lived server grows instead of dying at the initial bucket
        (r4 verdict #6).  Reference analog: the reference re-allocates
        its tracker arenas as docs grow (crates/loro-internal/src/
        container/richtext/tracker.rs)."""
        if new_capacity <= self.cap:
            return
        # under the device lock: a pipelined commit in flight is
        # scattering into the SAME buffers this repack replaces
        with self._dev_lock:
            sh = doc_sharding(self.mesh)
            cols = _pad_axis1(
                {f: getattr(self.cols, f) for f in self.cols._fields},
                new_capacity, self._COL_FILLS, sh,
            )
            from ..ops.fugue_batch import SeqColumnsU

            self.cols = SeqColumnsU(**cols)
            keys = _pad_axis1(
                {"key_hi": self.key_hi, "key_lo": self.key_lo},
                new_capacity,
                {"key_hi": 0xFFFFFFFF, "key_lo": 0xFFFFFFFF},
                sh,
            )
            self.key_hi, self.key_lo = keys["key_hi"], keys["key_lo"]
            for name in ("tomb_epoch", "row_epoch"):
                ne = np.full((self.d, new_capacity), -1, np.int64)
                ne[:, : self.cap] = getattr(self, name)
                setattr(self, name, ne)
            self.cap = new_capacity

    def release_doc(self, di: int) -> None:
        """Reset doc ``di`` to a never-used slot (tiered-residency
        eviction, parallel/residency.py): every host structure back to
        its construction value, every device row back to its fill.  The
        CALLER owns the safety argument — the doc's state must already
        be preserved elsewhere (deep mirror anchor + journal) and no
        staged/in-flight device work may reference the doc (the
        residency manager only releases journal-stable docs).  Inside
        an open coalesce group the deferred base offset for the doc
        resets too, so a later round in the same group can land a new
        doc at row 0."""
        from .idmap import make_idmap

        self.counts[di] = 0
        self.tomb_epoch[di, :] = -1
        self.row_epoch[di, :] = -1
        self.id2row[di] = make_idmap()
        self.value_store[di] = []
        self.anchor_meta[di] = {}
        self.anchor_by_row[di] = {}
        self.order[di] = self._fresh_order()
        if self._defer is not None:
            self._defer.base0[di] = 0
            self._defer.renumbered.discard(di)
        with self._dev_lock:
            fields = list(self.cols._fields)
            arrays = tuple(getattr(self.cols, f) for f in fields) + (
                self.key_hi, self.key_lo,
            )
            fills = tuple(self._COL_FILLS[f] for f in fields) + (
                0xFFFFFFFF, 0xFFFFFFFF,
            )
            out = _release_rows(arrays, jnp.int32(di), fills)
            from ..ops.fugue_batch import SeqColumnsU

            self.cols = SeqColumnsU(**dict(zip(fields, out[: len(fields)])))
            self.key_hi, self.key_lo = out[len(fields):]
        obs.counter("fleet.doc_releases_total").inc(
            family="text" if self.as_text else "list"
        )

    def compact(
        self,
        stable_epochs: Sequence[Optional[int]],
        extra_protect: Optional[Sequence[Optional[np.ndarray]]] = None,
        extra_dead: Optional[Sequence[Optional[np.ndarray]]] = None,
        return_remaps: bool = False,
    ):
        """Reclaim causally-stable tombstones (resident lifecycle, r4
        verdict #6; the reference analog is the shallow-snapshot floor,
        crates/loro-internal/src/encoding/shallow_snapshot.rs:16-40).

        ``extra_protect[di]`` (optional row arrays) marks rows a caller
        layers external references onto (DeviceMovableBatch's winning
        slot rows); ``extra_dead[di]`` marks rows the caller asserts
        are invisible AND stably so (superseded movable slots whose
        winner's ingest epoch every replica acked) — they join the
        droppable set under the same protection/subtree rules;
        ``return_remaps=True`` additionally returns {di: old-row ->
        new-row int array, -1 = dropped} so such callers can rewrite
        their references.

        ``stable_epochs[di]`` is the newest ingest epoch (``self.epoch``
        after an append) that EVERY replica of doc di has acknowledged
        integrating; None skips the doc.  A tombstone whose delete was
        ingested at epoch <= that is invisible at every replica, so no
        future op can treat it as visible.  Three keep-rules still
        apply, because Fugue ops CAN reference invisible rows:

        - attach-target protection: a future insert at the gap after a
          visible row `a` with R-children parents (side=L) on `a`'s
          total-order SUCCESSOR, tombstone or not; an insert at
          position 0 parents on the total-order FIRST row; and the
          anchor-aware expand walk (models/handlers._placement_with_
          expand) can end on the LAST tombstone of an invisible window,
          so every tombstone whose immediate successor is non-deleted
          is targetable too — all three classes stay (mirrors
          models/seq_crdt.placement_for_visible_pos + the expand walk);
        - live subtrees: a row with a surviving child stays (children's
          placements reference the parent chain) — EXCEPT a run-interior
          tombstone whose single live R-child is its run continuation,
          which drops by promoting that child into its place (safe: the
          only siblings the child could re-order against are same-peer
          counters inside the collapsed interval — the dropped chain
          itself; future same-peer ops carry higher counters);
        - undated tombstones (imported from pre-epoch checkpoints)
          never drop.

        Rebuilds the order engine, id map, anchors and device columns
        for compacted docs; returns rows reclaimed.  O(table) host pass
        — a rare maintenance op, not the hot path."""
        from .idmap import make_idmap
        from .order_maintenance import split_keys

        if len(stable_epochs) > self.d:
            raise ValueError(
                f"compact: {len(stable_epochs)} stable_epochs for a "
                f"{self.d}-doc batch"
            )
        stable_epochs = list(stable_epochs) + [None] * (self.d - len(stable_epochs))
        host = None  # fetched lazily on the first doc that compacts
        key_hi = key_lo = None
        reclaimed = 0
        remaps: Dict[int, np.ndarray] = {}
        for di, stable_e in enumerate(stable_epochs):
            if stable_e is None or not int(self.counts[di]):
                continue
            if host is None:
                host = {f: np.asarray(getattr(self.cols, f)).copy()
                        for f in self.cols._fields}
                key_hi = np.asarray(self.key_hi).copy()
                key_lo = np.asarray(self.key_lo).copy()
            k = int(self.counts[di])
            peer = (host["peer_hi"][di, :k].astype(np.uint64) << np.uint64(32)) | \
                host["peer_lo"][di, :k].astype(np.uint64)
            ctr = host["counter"][di, :k].astype(np.int64)
            parent = host["parent"][di, :k].astype(np.int64)
            deleted = host["deleted"][di, :k]
            side = host["side"][di, :k].astype(np.int64)
            te = self.tomb_epoch[di, :k]
            dead = deleted.copy()  # invisible rows: tombstones + caller's
            if extra_dead is not None and extra_dead[di] is not None:
                rows_d = np.asarray(extra_dead[di], np.int64)
                dead[rows_d[rows_d < k]] = True
            # attach-target protection from the standing total order
            order = np.lexsort((key_lo[di, :k], key_hi[di, :k]))
            protected = np.zeros(k, bool)
            protected[order[0]] = True  # global first (position-0 inserts)
            succ_of = np.full(k, -1, np.int64)
            succ_of[order[:-1]] = order[1:]
            has_r = np.zeros(k, bool)
            rmask = side == 1
            has_r[parent[rmask][parent[rmask] >= 0]] = True
            tgt = np.flatnonzero((~dead) & has_r & (succ_of >= 0))
            protected[succ_of[tgt]] = True
            if self.as_text:
                # expand-walk targets (TEXT only — style anchors can
                # appear at any future time and the anchor-aware walk
                # steps over tombstones, attaching to the LAST one of an
                # invisible window; list containers never grow anchors,
                # so their isolated slot tombstones stay reclaimable):
                # the last tombstone before any non-deleted row...
                nd_succ = np.flatnonzero(
                    (succ_of >= 0) & dead & ~dead[np.clip(succ_of, 0, k - 1)]
                )
                protected[nd_succ] = True
                # ...and the end-of-document window's final tombstone,
                # which has no successor
                protected[order[-1]] = True
            # anchor rows never drop, live OR dead: a dead END anchor
            # with a live start means "style runs to EOF" (richtexts'
            # dead-end-never-pops rule) — dropping the row would discard
            # its metadata and silently deactivate the style
            if self.anchor_by_row[di]:
                rows_a = np.fromiter(
                    self.anchor_by_row[di], np.int64, len(self.anchor_by_row[di])
                )
                protected[rows_a[rows_a < k]] = True
            if extra_protect is not None and extra_protect[di] is not None:
                rows_x = np.asarray(extra_protect[di], np.int64)
                protected[rows_x[rows_x < k]] = True
            stable_dead = deleted & (te >= 0) & (te <= int(stable_e))
            if extra_dead is not None and extra_dead[di] is not None:
                # caller-asserted stability: superseded rows join as-is
                stable_dead |= dead & ~deleted
            stable_dead &= ~protected
            # Reverse pass (children have higher indices than parents):
            # a stable tombstone drops when it anchors no live subtree —
            # either no live children at all (dead subtree), or exactly
            # one live R-child that is its run continuation, which then
            # PROMOTES into its place (chain collapse).  Promotion is
            # sibling-sort-safe: the promoted child keeps its identity
            # (peer, ctr); the only siblings it could re-order against
            # are same-peer rows with counters inside the collapsed
            # (T.ctr, C.ctr] interval — all of which are the dropped
            # chain rows themselves, and future same-peer ops always
            # carry higher counters.
            dparent = parent.copy()
            dside = side.copy()
            prom = ctr.copy()  # promoted placement counter (check only)
            live_l = np.zeros(k, np.int64)
            live_r = np.zeros(k, np.int64)
            only_r = np.full(k, -1, np.int64)  # valid when live_r == 1
            keep = np.zeros(k, bool)

            def credit(child: int, p: int, s: int) -> None:
                if p < 0:
                    return
                if s == 1:
                    live_r[p] += 1
                    only_r[p] = child if live_r[p] == 1 else -1
                else:
                    live_l[p] += 1

            for r in range(k - 1, -1, -1):
                if stable_dead[r] and live_l[r] == 0:
                    if live_r[r] == 0:
                        continue  # whole subtree dead: drop
                    if live_r[r] == 1:
                        c = int(only_r[r])
                        if peer[c] == peer[r] and prom[c] == ctr[r] + 1:
                            dparent[c] = parent[r]
                            dside[c] = side[r]
                            prom[c] = ctr[r]
                            credit(c, int(parent[r]), int(side[r]))
                            continue  # r drops, c takes its place
                keep[r] = True
                credit(r, int(dparent[r]), int(dside[r]))
            n_keep = int(keep.sum())
            if n_keep == k:
                continue
            reclaimed += k - n_keep
            old_rows = np.flatnonzero(keep)
            remap = np.full(k, -1, np.int64)
            remap[old_rows] = np.arange(n_keep)
            remaps[di] = remap
            new_parent = dparent[old_rows]
            pos = new_parent >= 0
            new_parent[pos] = remap[new_parent[pos]]
            new_side = dside[old_rows]
            # rebuild columns for this doc (tail restored to fills)
            for f in self.cols._fields:
                row = host[f][di]
                vals = row[:k][old_rows].copy()
                row[:] = self._COL_FILLS[f]
                row[:n_keep] = vals
            # list batches: drop stranded values and rewrite the content
            # ordinals over survivors (an empty store with content rows
            # is the externally-indexed movable-slot use — those
            # ordinals are NOT ours to rewrite, and there is no store
            # to shrink)
            if not self.as_text and self.value_store[di]:
                cvals = host["content"][di, :n_keep].astype(np.int64)
                uniq = np.unique(cvals[cvals >= 0])
                vmap = np.full(len(self.value_store[di]), -1, np.int64)
                vmap[uniq] = np.arange(len(uniq))
                host["content"][di, :n_keep] = np.where(
                    cvals >= 0, vmap[np.clip(cvals, 0, None)], cvals
                ).astype(host["content"].dtype)
                self.value_store[di] = [
                    self.value_store[di][int(o)] for o in uniq
                ]
            host["parent"][di, :n_keep] = new_parent
            host["side"][di, :n_keep] = new_side  # promoted rows inherit
            te_new = te[old_rows].copy()
            self.tomb_epoch[di, :] = -1
            self.tomb_epoch[di, :n_keep] = te_new
            re_new = self.row_epoch[di, :k][old_rows]
            self.row_epoch[di, :] = -1
            self.row_epoch[di, :n_keep] = re_new
            # rebuild the order engine + standing keys by replay
            self.order[di] = self._fresh_order()
            keys = self.order[di].append_arrays(
                new_parent.astype(np.int32),
                host["side"][di, :n_keep],
                peer[old_rows],
                ctr[old_rows],
                0,
            )
            if keys is None:
                keys = self.order[di].all_keys()
            kh, kl = split_keys(np.asarray(keys, np.int64))
            key_hi[di] = 0xFFFFFFFF
            key_lo[di] = 0xFFFFFFFF
            key_hi[di, :n_keep] = kh
            key_lo[di, :n_keep] = kl
            # rebuild the id map over survivors only
            m = make_idmap()
            m.insert_arrays(
                peer[old_rows], ctr[old_rows], np.arange(n_keep, dtype=np.int32)
            )
            self.id2row[di] = m
            # anchors: drop dead rows' metadata, remap the survivors
            if self.anchor_meta[di]:
                new_meta = {}
                for pc, a in self.anchor_meta[di].items():
                    nr = remap[a["row"]] if a["row"] < k else -1
                    if nr >= 0:
                        new_meta[pc] = dict(a, row=int(nr))
                self.anchor_meta[di] = new_meta
                self.anchor_by_row[di] = {a["row"]: pc for pc, a in new_meta.items()}
            self.counts[di] = n_keep
        if host is not None and reclaimed:
            from ..ops.fugue_batch import SeqColumnsU

            sh = doc_sharding(self.mesh)
            self.cols = SeqColumnsU(
                **{f: jax.device_put(v, sh) for f, v in host.items()}
            )
            self.key_hi = jax.device_put(key_hi, sh)
            self.key_lo = jax.device_put(key_lo, sh)
        return (reclaimed, remaps) if return_remaps else reclaimed

    def _fresh_order(self):
        """A new order engine of the configured kind (compaction
        rebuild)."""
        import os as _os

        if _os.environ.get("LORO_PY_ORDER", "0") not in ("1", "true", "yes"):
            from ..native import native_order

            nat = native_order()
            if nat is not None:
                return nat
        from .order_maintenance import ShadowOrder

        _obs_fallback("order")
        return ShadowOrder()

    def append_changes(self, per_doc_changes: Sequence[Optional[Sequence[Change]]], cid) -> None:
        """Incremental ingest: each doc's new causally-ordered changes
        (None = no update).  Inserts (chars AND style anchors — anchors
        are real Fugue nodes other inserts may parent on) become new
        rows; deletes tombstone rows from any epoch.  All validation and
        id-map staging happens before any state mutates, so a capacity
        error leaves the batch untouched.  One device scatter per call."""
        per_doc_changes = list(per_doc_changes) + [None] * (self.d - len(per_doc_changes))
        rows_per_doc: List[List[Tuple[int, int, int, int, int]]] = []
        overlays: List[Dict[Tuple[int, int], int]] = []
        anchor_stages: List[Dict[Tuple[int, int], dict]] = []
        value_stages: List[list] = []
        del_pairs: List[Tuple[int, int]] = []
        for di, changes in enumerate(per_doc_changes):
            rows: List[Tuple[int, int, int, int, int]] = []
            overlay: Dict[Tuple[int, int], int] = {}
            stage: Dict[Tuple[int, int], dict] = {}
            vstage: list = []
            rows_per_doc.append(rows)
            overlays.append(overlay)
            anchor_stages.append(stage)
            value_stages.append(vstage)
            if changes:
                self._python_rows(di, changes, cid, rows, overlay, del_pairs, stage, vstage)
        self._commit_rows(rows_per_doc, overlays, del_pairs, anchor_stages, value_stages)

    def _python_rows(self, di, changes, cid, rows, overlay, del_pairs, anchor_stage, value_stage) -> None:
        """Pure-Python op walk producing (parent,side,counter,content,
        peer) rows + delete pairs + staged anchor metadata for one doc
        (also the fallback for the native delta path)."""
        from ..core.change import SeqDelete, SeqInsert, StyleAnchor
        from ..oplog.oplog import _RunCont

        base = int(self.counts[di])
        idmap = self.id2row[di]
        n_vals = len(self.value_store[di])

        def resolve(key):
            return _resolve_row(overlay, idmap, key, di, "op parent")

        for ch in changes:
            for op in ch.ops:
                if op.container != cid:
                    continue
                c = op.content
                if isinstance(c, SeqInsert):
                    body = [c.content] if isinstance(c.content, StyleAnchor) else c.content
                    for j in range(len(body)):
                        if j == 0:
                            if isinstance(c.parent, _RunCont):
                                prow = resolve((ch.peer, op.counter - 1))
                            elif c.parent is None:
                                prow = -1
                            else:
                                prow = resolve((c.parent.peer, c.parent.counter))
                            side = int(c.side)
                        else:
                            prow = base + len(rows) - 1
                            side = 1
                        row = base + len(rows)
                        overlay[(ch.peer, op.counter + j)] = row
                        if isinstance(body[j], StyleAnchor):
                            content = -1
                            a = body[j]
                            anchor_stage[(ch.peer, op.counter + j)] = {
                                "row": row,
                                "key": a.key,
                                "value": a.value,
                                "lamport": ch.lamport + (op.counter + j - ch.ctr_start),
                                "peer": ch.peer,
                                "start": a.is_start,
                                "deleted": False,
                            }
                        elif self.as_text:
                            content = ord(body[j])
                        else:
                            content = n_vals + len(value_stage)
                            value_stage.append(body[j])
                        rows.append((prow, side, op.counter + j, content, ch.peer))
                elif isinstance(c, SeqDelete):
                    # deletes tolerate unknown targets (same as the
                    # native paths): a missing target means the insert
                    # is missing too, which the parent resolution flags
                    for sp in c.spans:
                        for ctr in range(sp.start, sp.end):
                            row_d = overlay.get((sp.peer, ctr))
                            if row_d is None:
                                row_d = idmap.get((sp.peer, ctr))
                            if row_d is not None:
                                del_pairs.append((di, row_d))

    def _commit_rows(self, rows_per_doc, overlays, del_pairs, anchor_stages=None, value_stages=None) -> None:
        """Shared tail: validate capacity, commit staged id maps +
        anchor metadata, block-scatter new rows, tombstone deletes
        (append_changes and append_payloads both end here).  Per-doc
        entries are either tuple lists (Python walks) or column dicts
        (the native fast path, ids staged in the idmap: overlays[di] is
        None and commit/abort goes through the map's staging)."""
        from ..ops.fugue_batch import pad_bucket

        def n_of(r) -> int:
            return len(r["parent"]) if isinstance(r, dict) else len(r)

        n_new = [n_of(r) for r in rows_per_doc]
        max_new = pad_bucket(max(n_new, default=0), floor=16) if any(n_new) else 0
        # validate BEFORE mutating: the scatter window is max_new wide,
        # so every updated doc needs base + max_new <= capacity
        # (dynamic_update_slice would silently clamp otherwise)
        required = max(
            (int(self.counts[di]) + max_new for di, k in enumerate(n_new) if k),
            default=0,
        )
        if required > self.cap:
            if self.auto_grow:
                self.grow(_grow_target(required, self.cap))
            else:
                for dj, ov in enumerate(overlays):
                    if ov is None:
                        self.id2row[dj].abort()
                raise RuntimeError(
                    f"DeviceDocBatch capacity exceeded: a doc needs "
                    f"{required} rows > {self.cap} (pass auto_grow=True "
                    "or call grow())"
                )
        self.epoch += 1  # post-validation: dates this append's rows
        # commit staged id maps + anchor metadata
        for di, overlay in enumerate(overlays):
            if overlay is None:
                self.id2row[di].commit()
            elif overlay:
                self.id2row[di].update(overlay)
        for di, stage in enumerate(anchor_stages or ()):
            if stage:
                self.anchor_meta[di].update(stage)
                self.anchor_by_row[di].update(
                    {a["row"]: pc for pc, a in stage.items()}
                )
        for di, vs in enumerate(value_stages or ()):
            if vs:
                self.value_store[di].extend(vs)
        if max_new:
            from .order_maintenance import split_keys

            obs.counter("fleet.resident_rows_total").inc(
                sum(n_new), family="text" if self.as_text else "list"
            )
            blk_shape = (self.d, max_new)
            blk = {
                "parent": np.full(blk_shape, -1, np.int32),
                "side": np.zeros(blk_shape, np.int32),
                "peer_hi": np.zeros(blk_shape, np.uint32),
                "peer_lo": np.zeros(blk_shape, np.uint32),
                "counter": np.zeros(blk_shape, np.int32),
                "deleted": np.ones(blk_shape, bool),
                "content": np.full(blk_shape, -1, np.int32),
                "valid": np.zeros(blk_shape, bool),
            }
            key_blk_hi = np.full(blk_shape, 0xFFFFFFFF, np.uint32)
            key_blk_lo = np.full(blk_shape, 0xFFFFFFFF, np.uint32)
            offsets = np.zeros(self.d, np.int32)
            renumbered: List[int] = []

            def _ingest_doc(di: int) -> bool:
                """Per-doc host work (block fill + order append): writes
                touch doc-disjoint slices/state only, and the native
                order engine's ctypes call releases the GIL, so docs
                shard across threads.  Returns True when the doc's keys
                were renumbered (caller re-uploads the whole key row)."""
                rows = rows_per_doc[di]
                base = int(self.counts[di])
                if isinstance(rows, dict):
                    k = len(rows["parent"])
                    parent, side_a = rows["parent"], rows["side"]
                    ctr_a, content_a = rows["counter"], rows["content"]
                    pu = rows["peer"]
                else:
                    k = len(rows)
                    arr = np.asarray(
                        [(r[0], r[1], r[2], r[3]) for r in rows], np.int64
                    )
                    pu = np.asarray([r[4] for r in rows], np.uint64)
                    parent, side_a = arr[:, 0], arr[:, 1]
                    ctr_a, content_a = arr[:, 2], arr[:, 3]
                blk["parent"][di, :k] = parent
                blk["side"][di, :k] = side_a
                blk["peer_hi"][di, :k] = (pu >> np.uint64(32)).astype(np.uint32)
                blk["peer_lo"][di, :k] = (pu & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                blk["counter"][di, :k] = ctr_a
                blk["deleted"][di, :k] = False
                blk["content"][di, :k] = content_a
                blk["valid"][di, :k] = True
                self.row_epoch[di, base : base + k] = self.epoch
                keys = self.order[di].append_arrays(
                    parent, side_a, pu, ctr_a, base
                )
                renum = keys is None
                if not renum:
                    kh, kl = split_keys(np.asarray(keys, np.int64))
                    key_blk_hi[di, :k] = kh
                    key_blk_lo[di, :k] = kl
                offsets[di] = base
                self.counts[di] += k
                return renum

            active = [di for di, k in enumerate(n_new) if k]
            # thread fan-out only pays when the order engine is the
            # native one (ctypes releases the GIL); the Python
            # ShadowOrder fallback would serialize through the GIL and
            # eat pool-spawn overhead on the hot path
            from ..native import NativeShadowOrder

            native_engine = bool(self.order) and isinstance(
                self.order[0], NativeShadowOrder
            )
            n_threads = min(
                int(os.environ.get("LORO_ORDER_THREADS") or (os.cpu_count() or 1))
                if native_engine
                else 1,
                max(1, len(active)),
            )
            if n_threads > 1:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=n_threads) as pool:
                    for di, renum in zip(active, pool.map(_ingest_doc, active)):
                        if renum:
                            renumbered.append(di)
            else:
                for di in active:
                    if _ingest_doc(di):
                        renumbered.append(di)
            if self._defer is not None:
                # coalesced group: stash the block; flush_coalesce ships
                # every round's segments in one merged scatter
                self._defer.rounds.append(
                    (blk, key_blk_hi, key_blk_lo, list(n_new))
                )
                self._defer.renumbered.update(renumbered)
            else:
                self._device_commit_block(
                    blk, key_blk_hi, key_blk_lo, offsets, n_new, renumbered
                )
        self.mark_deleted(del_pairs)

    def append_payloads(self, per_doc_payloads: Sequence[Optional[bytes]], cid) -> None:
        """Incremental NATIVE ingest: envelope-stripped binary payloads
        -> C++ delta explode (cross-epoch parents/deletes resolved
        through the per-doc id maps) -> one block scatter.  Falls back
        to append_changes via the Python decoder per payload when the
        native library is unavailable."""
        from ..codec.binary import decode_changes, read_tables
        from ..native import (
            available,
            decode_value_at,
            explode_seq_anchor_meta,
            explode_seq_delta_payload,
        )

        if not available() or not self.as_text:
            # no native lib, or a value batch (the native explode only
            # understands text payloads): python decode per payload
            if not available():
                _obs_fallback("payload_decode")
            self.append_changes(
                [decode_changes(p) if p else None for p in per_doc_payloads], cid
            )
            return
        per_doc_payloads = list(per_doc_payloads) + [None] * (self.d - len(per_doc_payloads))
        try:
            self._append_payloads_staged(per_doc_payloads, cid)
        except BaseException:
            # ANY escaping error must roll back native-staged ids: the
            # C++ maps are long-lived, and a later commit would publish
            # phantom (peer, ctr) -> row mappings for rows that were
            # never scattered (post-commit aborts are no-ops)
            for di in range(self.d):
                self.id2row[di].abort()
            raise

    def _append_payloads_staged(self, per_doc_payloads, cid) -> None:
        from ..codec.binary import decode_changes, read_tables
        from ..native import (
            decode_value_at,
            explode_seq_anchor_meta,
            explode_seq_delta_payload,
        )

        rows_per_doc: List[list] = []
        overlays: List[Dict[Tuple[int, int], int]] = []
        anchor_stages: List[Dict[Tuple[int, int], dict]] = []
        value_stages: List[list] = []
        del_pairs: List[Tuple[int, int]] = []
        for di, payload in enumerate(per_doc_payloads):
            rows: list = []
            overlay: Dict[Tuple[int, int], int] = {}
            stage: Dict[Tuple[int, int], dict] = {}
            vstage: list = []
            rows_per_doc.append(rows)
            overlays.append(overlay)
            anchor_stages.append(stage)
            value_stages.append(vstage)
            if not payload:
                continue
            n_dels_start = len(del_pairs)
            try:
                peers_wire, _keys, cids, _r = read_tables(payload)
                try:
                    target = cids.index(cid)
                except ValueError:
                    continue  # no ops for this container
                out = explode_seq_delta_payload(payload, target)
                anchor_cols = None
                if (np.asarray(out["content"]) == -1).any():
                    # style anchors: fetch their metadata natively (same
                    # row numbering as the main explode) so richtexts()
                    # keeps its pair table without the python walk
                    anchor_cols = explode_seq_anchor_meta(payload, target)
                base = int(self.counts[di])
                idmap = self.id2row[di]
                # columnar end-to-end: the id registrations ride the
                # native map's staging (committed in _commit_rows), ext
                # parents and delete spans resolve in TWO batch lookups
                # — no per-row Python dict/tuple traffic (r4 verdict #5)
                peers_np = np.asarray(peers_wire, np.uint64)
                peer_u64 = peers_np[out["peer_idx"]]
                ctr64 = out["counter"].astype(np.int64)
                idmap.stage_base(peer_u64, ctr64, base)
                prow_arr = np.where(
                    out["parent"] >= 0, base + out["parent"], out["parent"]
                ).astype(np.int32)
                ext_rows = np.flatnonzero(out["parent"] == -2)
                if len(ext_rows):
                    res = idmap.lookup(
                        peers_np[out["ext_peer_idx"][ext_rows]],
                        out["ext_counter"][ext_rows],
                    )
                    if (res < 0).any():
                        raise KeyError("unresolved cross-epoch parent")
                    prow_arr[ext_rows] = res
                rows_per_doc[di] = {
                    "parent": prow_arr,
                    "side": out["side"],
                    "counter": out["counter"],
                    "content": out["content"],
                    "peer": peer_u64,
                }
                overlays[di] = None  # marker: ids staged in the idmap
                if anchor_cols is not None:
                    for ai in range(len(anchor_cols["row"])):
                        rrow = int(anchor_cols["row"][ai])
                        a_peer = int(peer_u64[rrow])
                        stage[(a_peer, int(out["counter"][rrow]))] = {
                            "row": base + rrow,
                            "key": _keys[int(anchor_cols["key_idx"][ai])],
                            "value": decode_value_at(
                                payload, int(anchor_cols["voffset"][ai]), cids
                            ),
                            "lamport": int(anchor_cols["lamport"][ai]),
                            "peer": a_peer,
                            "start": bool(anchor_cols["flags"][ai] & 1),
                            "deleted": False,
                        }
                lens = (out["del_end"] - out["del_start"]).astype(np.int64)
                tot = int(lens.sum())
                if tot:
                    dp = np.repeat(peers_np[out["del_peer_idx"]], lens)
                    offs = np.repeat(np.cumsum(lens) - lens, lens)
                    dctr = np.arange(tot, dtype=np.int64) - offs + np.repeat(
                        out["del_start"], lens
                    )
                    drows = idmap.lookup(dp, dctr)
                    # deletes tolerate unknown targets (as the walks do)
                    drows = drows[drows >= 0]
                    if len(drows):
                        del_pairs.append((di, drows))
            except (KeyError, ValueError):
                # unresolvable refs or malformed input for the native
                # path: python fallback for this payload only
                _obs_fallback("payload_decode")
                self.id2row[di].abort()
                rows.clear()
                rows_per_doc[di] = rows
                overlay.clear()
                overlays[di] = overlay
                stage.clear()
                vstage.clear()
                del del_pairs[n_dels_start:]
                self._python_rows(
                    di, decode_changes(payload), cid, rows, overlay, del_pairs,
                    stage, vstage,
                )
        self._commit_rows(rows_per_doc, overlays, del_pairs, anchor_stages, value_stages)

    def mark_deleted(self, pairs) -> None:
        """Tombstone (doc, rows) entries (delete ops referencing earlier
        appends).  Each entry is (doc, row) or (doc, row_ndarray) — the
        columnar ingest path ships whole per-doc delete chunks.  Padded
        to buckets (idempotent repeats of the first pair) to bound
        retraces.

        Advances the epoch clock and dates the new tombstones with the
        fresh epoch — including direct public calls, so an out-of-band
        delete can never be stamped with an epoch replicas already
        acked (which would let compact() reclaim a never-propagated
        delete).  Runs after all ingest validation, so a failed append
        leaves the clock untouched."""
        from ..ops.fugue_batch import pad_bucket

        if not pairs:
            return
        self.epoch += 1
        d_parts: List[np.ndarray] = []
        r_parts: List[np.ndarray] = []
        for di, row in pairs:  # deactivate style pairs whose anchor died
            abr = self.anchor_by_row[di]
            if isinstance(row, np.ndarray):
                if abr:  # anchors are rare; skip the loop when none
                    for rr in row.tolist():
                        pc = abr.get(rr)
                        if pc is not None:
                            self.anchor_meta[di][pc]["deleted"] = True
                d_parts.append(np.full(len(row), di, np.int32))
                r_parts.append(row.astype(np.int32))
            else:
                pc = abr.get(row)
                if pc is not None:
                    self.anchor_meta[di][pc]["deleted"] = True
                d_parts.append(np.full(1, di, np.int32))
                r_parts.append(np.full(1, row, np.int32))
        d_all = np.concatenate(d_parts)
        r_all = np.concatenate(r_parts)
        n = len(d_all)
        if not n:
            return
        # date the tombstones: compact() may reclaim them once every
        # replica has acked this epoch
        self.tomb_epoch[d_all, r_all] = self.epoch
        if self._defer is not None:
            # coalesced group: tombstones launch once at flush (after
            # the merged row scatter, which only writes NEW rows — it
            # cannot resurrect a row an earlier round tombstoned)
            self._defer.del_d.append(d_all)
            self._defer.del_r.append(r_all)
            return
        self._device_mark_deleted(d_all, r_all)

    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection for the sync pull path: one
        launch per request window (see ``_batch_export_select``)."""
        return _batch_export_select(self, "seq", index, requests, sup)

    def resolve_row(self, doc: int, peer: int, counter: int) -> Optional[int]:
        return self.id2row[doc].get((peer, counter))

    def _materialize(self, use_solver: bool = False):
        """(codes, counts) for the whole batch in one launch.

        Default path: sort by the standing ShadowOrder keys — the
        per-sync order work already happened incrementally on ingest
        (O(delta)); the launch is one multi-key sort, no rank solve.
        use_solver=True runs the full chain-contracted rank solve
        instead (bulk path; also the differential check in tests)."""
        from ..ops.fugue_batch import chain_merge_docs_u, materialize_by_key

        obs.counter("fleet.device_launches_total").inc(family="resident_materialize")
        if not use_solver:
            codes, counts = materialize_by_key(self.cols, self.key_hi, self.key_lo)
            return np.asarray(codes), np.asarray(counts)
        while True:
            codes, counts, n_chains = chain_merge_docs_u(self.cols, self._c_pad)
            max_chains = int(np.asarray(n_chains).max()) if self.d else 0
            if max_chains <= self._c_pad:
                break
            while self._c_pad < max_chains:
                self._c_pad *= 2
        return np.asarray(codes), np.asarray(counts)

    def texts(self, use_solver: bool = False) -> List[str]:
        """Materialize every doc (one launch)."""
        codes, counts = self._materialize(use_solver)
        return ["".join(map(chr, codes[i, : counts[i]])) for i in range(self.n_docs)]

    def values(self, use_solver: bool = False) -> List[list]:
        """Materialize value lists (as_text=False batches)."""
        from ..errors import DecodeError

        assert not self.as_text, "values() is for as_text=False batches"
        codes, counts = self._materialize(use_solver)
        out = []
        for i in range(self.n_docs):
            store = self.value_store[i]
            row = []
            for j in codes[i, : counts[i]]:
                if not 0 <= j < len(store):
                    raise DecodeError(
                        "resident batch: content ordinal outside the value store "
                        "(corrupt restored state?)"
                    )
                row.append(store[j])
            out.append(row)
        return out

    # -- checkpoint/resume (fleet-scale; SURVEY §5) --------------------
    STATE_VERSION = 2  # v2: + ingest epoch in meta, tomb-epoch columns
    # serialized row columns (valid is derivable from counts): ONE
    # schema shared by export and import so they cannot drift
    _STATE_SCHEMA = (
        ("parent", np.int32),
        ("side", np.int32),
        ("peer_hi", np.uint32),
        ("peer_lo", np.uint32),
        ("counter", np.int32),
        ("deleted", np.uint8),
        ("content", np.int32),
    )

    def export_state(self) -> bytes:
        """Serialize the resident batch into an LTKV store (storage/kv
        SSTable): per-doc committed row columns, value stores, anchor
        metadata.  id2row and the order engine are NOT serialized —
        both rebuild deterministically from the row table on import
        (keys are re-assigned by replay; any valid assignment orders
        identically).  One server restart = export_state -> bytes ->
        import_state."""
        from ..codec.binary import Writer, _Dicts, _write_value
        from ..storage import MemKvStore

        cols = {f: np.asarray(getattr(self.cols, f)) for f, _ in self._STATE_SCHEMA}
        kv = MemKvStore()
        d = _Dicts()
        meta = Writer()
        meta.u8(self.STATE_VERSION)
        meta.varint(self.n_docs)
        meta.varint(self.d)  # exporter's mesh-padded width
        meta.varint(self.cap)
        meta.u8(1 if self.as_text else 0)
        meta.varint(self._c_pad)
        for di in range(self.d):
            meta.varint(int(self.counts[di]))
        meta.varint(self.epoch)  # v2: compaction epoch clock
        meta.u8(1 if self.auto_grow else 0)  # v2: lifecycle flag
        kv.set(b"meta", bytes(meta.buf))
        for di in range(self.d):
            k = int(self.counts[di])
            w = Writer()
            for f, dt in self._STATE_SCHEMA:
                w.bytes_(cols[f][di, :k].astype(dt).tobytes())
            kv.set(b"doc/%08d/rows" % di, bytes(w.buf))
            if k:
                # v2: tombstone + row ingest epochs (compaction dating)
                kv.set(
                    b"doc/%08d/tombepoch" % di,
                    self.tomb_epoch[di, :k].astype(np.int64).tobytes(),
                )
                kv.set(
                    b"doc/%08d/rowepoch" % di,
                    self.row_epoch[di, :k].astype(np.int64).tobytes(),
                )
            w = Writer()
            _state_write_values(w, d, self.value_store[di])
            kv.set(b"doc/%08d/values" % di, bytes(w.buf))
            w = Writer()
            w.varint(len(self.anchor_meta[di]))
            for (peer, ctr), a in self.anchor_meta[di].items():
                w.varint(d.peer(peer))
                w.zigzag(ctr)
                w.varint(a["row"])
                w.str_(a["key"])
                if a["value"] is None:
                    w.u8(0)
                else:
                    w.u8(1)
                    _write_value(w, d, a["value"])
                w.varint(a["lamport"])
                w.u8((1 if a["start"] else 0) | (2 if a["deleted"] else 0))
            kv.set(b"doc/%08d/anchors" % di, bytes(w.buf))
        kv.set(b"dicts", _state_dicts_blob(d))
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "DeviceDocBatch":
        """Restore a resident batch from export_state bytes: upload the
        row table, rebuild id maps + the incremental order engine by
        deterministic replay, re-derive standing keys."""
        from ..codec.binary import Reader, _read_value
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b = kv.get(b"meta")
        if meta_b is None:
            raise DecodeError("DeviceDocBatch state: missing meta")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"DeviceDocBatch state v{version} too new")
            n_docs = r.varint()
            d_saved = r.varint()  # exporter's mesh-padded width
            cap = r.varint()
            as_text = r.u8() == 1
            c_pad = r.varint()
            if c_pad <= 0:  # the chain-budget doubling loop needs > 0
                raise DecodeError("DeviceDocBatch state: bad chain budget")
            counts = [r.varint() for _ in range(d_saved)]
            epoch = r.varint() if version >= 2 else 0
            auto_grow = (r.u8() == 1) if version >= 2 else False
        except (IndexError, ValueError, struct.error) as e:
            raise DecodeError(f"DeviceDocBatch state: malformed meta ({e})") from None
        _state_sane_sizes("DeviceDocBatch", d_saved, capacity=cap)
        if not 0 < n_docs <= d_saved:
            raise DecodeError("DeviceDocBatch state: implausible n_docs")
        batch = cls(n_docs, cap, mesh=mesh, as_text=as_text, auto_grow=auto_grow)
        batch._c_pad = c_pad
        batch.epoch = epoch
        # mesh-pad docs beyond the importer's width must be empty (they
        # only ever receive None updates on the export side)
        for di in range(batch.d, d_saved):
            if counts[di]:
                raise DecodeError(
                    "DeviceDocBatch state: exporter pad doc carries rows but "
                    "importer mesh is narrower"
                )
        dicts_b = kv.get(b"dicts")
        if dicts_b is None:
            raise DecodeError("DeviceDocBatch state: missing dicts")
        peers, cids = _state_read_dicts(dicts_b)
        host = {
            f: np.asarray(getattr(batch.cols, f)).copy() for f in batch.cols._fields
        }
        key_hi = np.asarray(batch.key_hi).copy()
        key_lo = np.asarray(batch.key_lo).copy()
        from .order_maintenance import split_keys

        for di in range(min(batch.d, d_saved)):
            k = counts[di]
            if k > cap:
                raise DecodeError("DeviceDocBatch state: count exceeds capacity")
            rows_b = kv.get(b"doc/%08d/rows" % di)
            if k and rows_b is None:
                raise DecodeError(f"DeviceDocBatch state: missing rows for doc {di}")
            if rows_b is not None:
                r = Reader(rows_b)
                arrs = {}
                try:
                    for f, dt in cls._STATE_SCHEMA:
                        buf = np.frombuffer(r.bytes_(), dt)
                        if len(buf) != k:
                            raise DecodeError("DeviceDocBatch state: row column length")
                        arrs[f] = buf
                except (IndexError, ValueError) as e:
                    raise DecodeError(
                        f"DeviceDocBatch state: malformed rows ({e})"
                    ) from None
                for f in arrs:
                    tgt = host[f]
                    tgt[di, :k] = arrs[f].astype(tgt.dtype)
                host["valid"][di, :k] = True
                batch.counts[di] = k
                for key, attr in (
                    (b"doc/%08d/tombepoch" % di, "tomb_epoch"),
                    (b"doc/%08d/rowepoch" % di, "row_epoch"),
                ):
                    e_b = kv.get(key)
                    if e_b is not None:
                        ecol = np.frombuffer(e_b, np.int64)
                        if len(ecol) != k:
                            raise DecodeError(
                                "DeviceDocBatch state: epoch column length"
                            )
                        getattr(batch, attr)[di, :k] = ecol
                peer_full = (arrs["peer_hi"].astype(np.uint64) << np.uint64(32)) | arrs[
                    "peer_lo"
                ].astype(np.uint64)
                ctr = arrs["counter"]
                batch.id2row[di].insert_arrays(
                    peer_full, ctr.astype(np.int64), np.arange(k, dtype=np.int32)
                )
                # deterministic order-engine rebuild by replay
                if k:
                    keys = batch.order[di].append_arrays(
                        arrs["parent"], arrs["side"], peer_full,
                        ctr.astype(np.int64), 0,
                    )
                    if keys is None:
                        keys = batch.order[di].all_keys()
                    kh, kl = split_keys(np.asarray(keys, np.int64))
                    key_hi[di, :k] = kh
                    key_lo[di, :k] = kl
            try:
                vals_b = kv.get(b"doc/%08d/values" % di)
                if vals_b is not None:
                    batch.value_store[di] = _state_read_values(vals_b, cids)
                if k:
                    c_col = host["content"][di, :k].astype(np.int64)
                    if as_text:
                        if c_col.min() < -1 or c_col.max() >= 0x110000:
                            raise DecodeError("DeviceDocBatch state: content code")
                    elif batch.value_store[di] and (
                        c_col.min() < -1
                        or c_col.max() >= len(batch.value_store[di])
                    ):
                        # (an empty store with content rows is the
                        # externally-indexed nested use — DeviceMovable-
                        # Batch slots; values() re-checks at read time)
                        raise DecodeError("DeviceDocBatch state: value ordinal")
                anch_b = kv.get(b"doc/%08d/anchors" % di)
                if anch_b is not None:
                    r = Reader(anch_b)
                    meta_d: Dict[Tuple[int, int], dict] = {}
                    for _ in range(r.varint()):
                        pi = r.varint()
                        if pi >= len(peers):
                            raise DecodeError("DeviceDocBatch state: anchor peer index")
                        peer = peers[pi]
                        ctr_ = r.zigzag()
                        row = r.varint()
                        if row >= k:
                            # an out-of-range anchor row would silently
                            # clip into wrong style positions in
                            # richtexts(); reject like value ordinals
                            raise DecodeError(
                                "DeviceDocBatch state: anchor row out of range"
                            )
                        key = r.str_()
                        val = _read_value(r, cids) if r.u8() == 1 else None
                        lam = r.varint()
                        flags = r.u8()
                        meta_d[(peer, ctr_)] = {
                            "row": row,
                            "key": key,
                            "value": val,
                            "lamport": lam,
                            "peer": peer,
                            "start": bool(flags & 1),
                            "deleted": bool(flags & 2),
                        }
                    batch.anchor_meta[di] = meta_d
                    batch.anchor_by_row[di] = {
                        a["row"]: pc for pc, a in meta_d.items()
                    }
            except (IndexError, ValueError, struct.error, UnicodeDecodeError) as e:
                raise DecodeError(
                    f"DeviceDocBatch state: malformed doc {di} ({e})"
                ) from None
        sh = doc_sharding(batch.mesh)
        from ..ops.fugue_batch import SeqColumnsU

        batch.cols = SeqColumnsU(**{f: jax.device_put(v, sh) for f, v in host.items()})
        batch.key_hi = jax.device_put(key_hi, sh)
        batch.key_lo = jax.device_put(key_lo, sh)
        return batch

    def richtexts(self) -> List[list]:
        """Materialize every doc as Quill-style [{insert, attributes?}]
        segments with styles resolved ON DEVICE (one launch): the
        standing-key sort yields char-positions for every row (anchors
        are zero-width rows), then winners resolve on the segment
        forest (ops/richtext_batch.richtext_by_key_batch).  The
        incremental sibling of Fleet.merge_richtext_changes for
        long-lived resident batches."""
        from ..ops.fugue_batch import pad_bucket
        from ..ops.richtext_batch import (
            RichtextPairs,
            richtext_by_key_batch,
            segments_from_device,
        )

        assert self.as_text, "richtexts() is for as_text=True batches"
        # batch-uniform key dictionary; per-doc value stores
        keys: List[str] = []
        key_idx: Dict[str, int] = {}
        doc_pairs: List[list] = []
        doc_values: List[list] = []
        for di in range(self.d):
            meta = self.anchor_meta[di]
            values: List = []
            pairs = []
            peers = sorted({a["peer"] for a in meta.values()})
            prank = {p: i for i, p in enumerate(peers)}
            for (peer, ctr), a in meta.items():
                if not a["start"]:
                    continue
                end = meta.get((peer, ctr + 1))
                if end is None or end["start"]:
                    continue  # unpaired (mid-transfer); inactive
                if a["deleted"]:
                    continue  # dead start = inactive pair (host walk)
                ki = key_idx.setdefault(a["key"], len(keys))
                if ki == len(keys):
                    keys.append(a["key"])
                if a["value"] is None:
                    vi = -1
                else:
                    vi = len(values)
                    values.append(a["value"])
                pairs.append(
                    (
                        a["row"],
                        # dead end anchor never pops: style runs to EOF
                        -1 if end["deleted"] else end["row"],
                        ki,
                        vi,
                        a["lamport"],
                        prank[a["peer"]],
                    )
                )
            doc_pairs.append(pairs)
            doc_values.append(values)
        n_keys = pad_bucket(max(1, len(keys)), floor=4)
        p = pad_bucket(max(1, max(len(x) for x in doc_pairs)), floor=16)

        def col(j, fill):
            out = np.full((self.d, p), fill, np.int32)
            for di, pairs in enumerate(doc_pairs):
                for i, row in enumerate(pairs):
                    out[di, i] = row[j]
            return out

        pv = np.zeros((self.d, p), bool)
        for di, pairs in enumerate(doc_pairs):
            pv[di, : len(pairs)] = True
        pairs_dev = RichtextPairs(
            start=jnp.asarray(col(0, 0)),
            end=jnp.asarray(col(1, 0)),
            key=jnp.asarray(col(2, 0)),
            value=jnp.asarray(col(3, -1)),
            lamport=jnp.asarray(col(4, 0)),
            peer=jnp.asarray(col(5, 0)),
            valid=jnp.asarray(pv),
        )
        codes, counts, bounds, win = richtext_by_key_batch(
            self.cols, self.key_hi, self.key_lo, pairs_dev, n_keys
        )
        codes = np.asarray(codes)
        counts = np.asarray(counts)
        bounds = np.asarray(bounds)
        win = np.asarray(win)
        return [
            segments_from_device(
                codes[i], counts[i], bounds[i], win[i], keys, doc_values[i]
            )
            for i in range(self.n_docs)
        ]


class _LazyValue:
    """Undecoded map value: payload bytes + native-reported offset.
    Decoded only if it wins the LWW (value_maps)."""

    __slots__ = ("payload", "offset", "cids")

    def __init__(self, payload: bytes, offset: int, cids):
        self.payload = payload
        self.offset = offset
        self.cids = cids

    def decode(self):
        from ..native import decode_value_at

        return decode_value_at(self.payload, self.offset, self.cids)


class DeviceMapBatch:
    """Device-resident LWW-map winners for a doc batch (the map analog
    of DeviceDocBatch).  Appends fold into per-(doc, slot) winners in
    one donated launch; values live host-side as per-doc ordinal lists.
    """

    def __init__(self, n_docs: int, slot_capacity: int, mesh=None,
                 auto_grow: bool = False):
        from ..ops.lww import NEG, LwwResident

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_docs = n_docs
        self.d = _mesh_pad(self.mesh, n_docs)
        self.s = slot_capacity
        self.auto_grow = auto_grow
        sh = doc_sharding(self.mesh)
        z = lambda dt, fill: jax.device_put(np.full((self.d, self.s), fill, dt), sh)
        self.res = LwwResident(
            lamport=z(np.int32, int(NEG)),
            peer_hi=z(np.uint32, 0),
            peer_lo=z(np.uint32, 0),
            value=z(np.int32, -2),
        )
        self.slot_of: List[Dict[Tuple[ContainerID, str], int]] = [dict() for _ in range(self.d)]
        self.values: List[List] = [[] for _ in range(self.d)]
        # ingest-epoch clock (parity with the seq/tree batches: the
        # server journals rounds against it; folds have no rows to
        # reclaim, so unlike theirs it never gates a compact())
        self.epoch = 0
        self._defer = None  # coalesced-ingest accumulator
        self._dev_lock = named_rlock("fleet.dev")

    # -- round coalescing (LWW fold is associative: one merged fold of
    # the group's rows lands the same winners as one fold per round;
    # the epoch clock still bumps per round in _fold_rows) -------------
    def begin_coalesce(self) -> None:
        if self._defer is not None:
            raise RuntimeError("coalesce group already open")
        self._defer = _DeferredFold(self.d)

    def detach_coalesce(self):
        d, self._defer = self._defer, None
        return d

    def commit_detached(self, d) -> None:
        if d is None or not any(d.rows):
            return
        self._device_fold(d.rows)
        obs.counter("pipeline.coalesced_rounds_total").inc(
            d.n_rounds, family="map"
        )

    def flush_coalesce(self) -> None:
        self.commit_detached(self.detach_coalesce())

    def grow(self, new_slot_capacity: int) -> None:
        """Repack the LWW winner columns to a larger slot capacity
        (resident lifecycle, r4 verdict #6)."""
        from ..ops.lww import LwwResident

        if new_slot_capacity <= self.s:
            return
        with self._dev_lock:  # vs an in-flight pipelined commit
            fills = _lww_fills(-2)
            res = _pad_axis1(
                {f: getattr(self.res, f) for f in self.res._fields},
                new_slot_capacity, fills, doc_sharding(self.mesh),
            )
            self.res = LwwResident(**res)
            self.s = new_slot_capacity

    def _require_slots(self, required: int) -> None:
        """Grow (auto_grow) or raise when a staged append needs more
        slots than the current capacity."""
        if required <= self.s:
            return
        if self.auto_grow:
            self.grow(_grow_target(required, self.s))
        else:
            raise ValueError(
                f"DeviceMapBatch slot capacity exceeded ({required} > "
                f"{self.s}); grow slot_capacity or pass auto_grow=True"
            )

    def release_doc(self, di: int) -> None:
        """Reset doc ``di`` to a never-used slot (tiered-residency
        eviction; see DeviceDocBatch.release_doc for the contract)."""
        from ..ops.lww import NEG, LwwResident

        self.slot_of[di] = {}
        self.values[di] = []
        with self._dev_lock:
            out = _release_rows(
                tuple(self.res), jnp.int32(di),
                (int(NEG), 0, 0, -2),
            )
            self.res = LwwResident(*out)
        obs.counter("fleet.doc_releases_total").inc(family="map")

    def append_changes(self, per_doc_changes: Sequence[Optional[Sequence[Change]]]) -> None:
        from ..core.change import MapSet
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import lww_update_resident

        per_doc_changes = list(per_doc_changes) + [None] * (self.d - len(per_doc_changes))
        # stage all mutations; commit only after every doc ingests clean
        # (a capacity error must leave the batch state untouched)
        rows_per_doc, new_slots, new_vals = [], [], []
        for di, changes in enumerate(per_doc_changes):
            rows = []
            rows_per_doc.append(rows)
            staged_slots: Dict = {}
            staged_vals: List = []
            new_slots.append(staged_slots)
            new_vals.append(staged_vals)
            if not changes:
                continue
            slot_of = self.slot_of[di]
            n_vals0 = len(self.values[di])
            for ch in changes:
                for op in ch.ops:
                    c = op.content
                    if not isinstance(c, MapSet):
                        continue
                    key = (op.container, c.key)
                    slot = slot_of.get(key)
                    if slot is None:
                        slot = staged_slots.get(key)
                    if slot is None:
                        slot = len(slot_of) + len(staged_slots)
                        staged_slots[key] = slot
                    lam = ch.lamport + (op.counter - ch.ctr_start)
                    if c.deleted:
                        vi = -1
                    else:
                        vi = n_vals0 + len(staged_vals)
                        staged_vals.append(c.value)
                    rows.append((slot, lam, ch.peer, vi))
        self._require_slots(
            max(
                (len(self.slot_of[di]) + len(new_slots[di]) for di in range(self.d)),
                default=0,
            )
        )
        for di in range(self.d):
            self.slot_of[di].update(new_slots[di])
            self.values[di].extend(new_vals[di])
        self._fold_rows(rows_per_doc)

    def append_payloads(self, per_doc_payloads: Sequence[Optional[bytes]]) -> None:
        """Native ingest: binary payloads -> C++ map explode -> one
        donated fold.  Values are NOT decoded here — the native decoder
        reports byte offsets and value_maps() decodes only the LWW
        winners (loser values never touch Python)."""
        from ..codec.binary import decode_changes
        from ..native import available, explode_map_payload
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import lww_update_resident

        if not available():
            self.append_changes(
                [decode_changes(p) if p else None for p in per_doc_payloads]
            )
            return
        per_doc_payloads = list(per_doc_payloads) + [None] * (self.d - len(per_doc_payloads))
        # staged exactly like append_changes: no state mutation until
        # every payload decodes and fits capacity
        rows_per_doc, new_slots, new_vals = [], [], []
        for di, payload in enumerate(per_doc_payloads):
            rows = []
            rows_per_doc.append(rows)
            staged_slots: Dict = {}
            staged_vals: List = []
            new_slots.append(staged_slots)
            new_vals.append(staged_vals)
            if not payload:
                continue
            out = explode_map_payload(payload)
            slot_of = self.slot_of[di]
            n_vals0 = len(self.values[di])
            n = len(out["cid_idx"])
            for j in range(n):
                key = (out["cids"][out["cid_idx"][j]], out["keys"][out["key_idx"][j]])
                slot = slot_of.get(key)
                if slot is None:
                    slot = staged_slots.get(key)
                if slot is None:
                    slot = len(slot_of) + len(staged_slots)
                    staged_slots[key] = slot
                off = int(out["value_offset"][j])
                if off < 0:
                    vi = -1
                else:
                    vi = n_vals0 + len(staged_vals)
                    # lazy cell: decoded on demand in value_maps()
                    staged_vals.append(_LazyValue(payload, off, out["cids"]))
                rows.append(
                    (slot, int(out["lamport"][j]), out["peer_u64"][j], vi)
                )
        self._require_slots(
            max(
                (len(self.slot_of[di]) + len(new_slots[di]) for di in range(self.d)),
                default=0,
            )
        )
        for di in range(self.d):
            self.slot_of[di].update(new_slots[di])
            self.values[di].extend(new_vals[di])
        self._fold_rows(rows_per_doc)

    def _fold_rows(self, rows_per_doc) -> None:
        self.epoch += 1  # post-validation: dates this append (journal clock)
        if not any(rows_per_doc):
            return
        if self._defer is not None:
            self._defer.extend(rows_per_doc)
            return
        self._device_fold(rows_per_doc)

    def _device_fold(self, rows_per_doc) -> None:
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import lww_update_resident

        obs.counter("fleet.device_launches_total").inc(family="resident_map")
        m = pad_bucket(max((len(r) for r in rows_per_doc), default=0), floor=16)
        slot = np.zeros((self.d, m), np.int32)
        lam = np.zeros((self.d, m), np.int32)
        hi = np.zeros((self.d, m), np.uint32)
        lo = np.zeros((self.d, m), np.uint32)
        val = np.full((self.d, m), -2, np.int32)
        valid = np.zeros((self.d, m), bool)
        for di, rows in enumerate(rows_per_doc):
            for j, (s_, l_, p_, v_) in enumerate(rows):
                slot[di, j] = s_
                lam[di, j] = l_
                hi[di, j] = p_ >> 32
                lo[di, j] = p_ & 0xFFFFFFFF
                val[di, j] = v_
                valid[di, j] = True
        with self._dev_lock:
            sh = doc_sharding(self.mesh)
            put = lambda a: jax.device_put(a, sh)
            self.res = lww_update_resident(
                self.res, put(slot), put(lam), put(hi), put(lo), put(valid),
                self.s, value=put(val),
            )

    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection for the sync pull path (the
        LWW fold holds no op history — delta framing rides the
        change-span index, like every family)."""
        return _batch_export_select(self, "map", index, requests, sup)

    def value_maps(self) -> List[Dict[Tuple[ContainerID, str], object]]:
        """Materialize {(container, key): value} per doc.  Keys carry
        the container id so the same key name in two map containers of
        one doc cannot collide.  Lazy cells (native ingest) decode here
        — winners only."""
        win = np.asarray(self.res.value)
        out = []
        for di in range(self.n_docs):
            m: Dict[Tuple[ContainerID, str], object] = {}
            for (cid, key), s_ in self.slot_of[di].items():
                vi = int(win[di, s_])
                if vi >= 0:
                    v = self.values[di][vi]
                    if isinstance(v, _LazyValue):
                        v = v.decode()
                        self.values[di][vi] = v
                    m[(cid, key)] = v
            out.append(m)
        return out

    def root_value_maps(self, name: str) -> List[Dict[str, object]]:
        """Flat {key: value} per doc for one root map container."""
        out = []
        for full in self.value_maps():
            out.append(
                {
                    key: v
                    for (cid, key), v in full.items()
                    if cid.is_root and cid.name == name
                }
            )
        return out

    # -- checkpoint/resume --------------------------------------------
    STATE_VERSION = 3  # v3: + ingest epoch clock

    def export_state(self) -> bytes:
        """Serialize the resident winners + slot/value dictionaries into
        an LTKV store (lazy values decode here — winners only live on)."""
        from ..codec.binary import Writer, _Dicts
        from ..storage import MemKvStore

        kv = MemKvStore()
        d = _Dicts()
        meta = Writer()
        meta.u8(self.STATE_VERSION)
        meta.varint(self.n_docs)
        meta.varint(self.d)
        meta.varint(self.s)
        meta.u8(1 if self.auto_grow else 0)  # v2
        meta.varint(self.epoch)  # v3
        kv.set(b"meta", bytes(meta.buf))
        _state_write_grid(kv, b"res", [np.asarray(a) for a in self.res])
        for di in range(self.d):
            w = Writer()
            w.varint(len(self.slot_of[di]))
            for (cid, key), s_ in self.slot_of[di].items():
                w.varint(d.cid(cid))
                w.str_(key)
                w.varint(s_)
            kv.set(b"doc/%08d/slots" % di, bytes(w.buf))
            w = Writer()
            _state_write_values(w, d, self.values[di])
            kv.set(b"doc/%08d/values" % di, bytes(w.buf))
        kv.set(b"dicts", _state_dicts_blob(d))
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "DeviceMapBatch":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..ops.lww import LwwResident
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, dicts_b = kv.get(b"meta"), kv.get(b"dicts")
        if meta_b is None or dicts_b is None:
            raise DecodeError("DeviceMapBatch state: missing meta/dicts")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"DeviceMapBatch state v{version} too new")
            n_docs, d_saved, s = r.varint(), r.varint(), r.varint()
            auto_grow = (r.u8() == 1) if version >= 2 else False
            epoch = r.varint() if version >= 3 else 0
        except (IndexError, ValueError) as e:
            raise DecodeError(f"DeviceMapBatch state: malformed meta ({e})") from None
        _state_sane_sizes("DeviceMapBatch", d_saved, slot_capacity=s)
        if not 0 < n_docs <= d_saved:
            raise DecodeError("DeviceMapBatch state: implausible n_docs")
        peers, cids = _state_read_dicts(dicts_b)
        batch = cls(n_docs, s, mesh=mesh, auto_grow=auto_grow)
        batch.epoch = epoch
        res_b = kv.get(b"res")
        if res_b is None:
            raise DecodeError("DeviceMapBatch state: missing res")
        grids = _state_read_grid(
            res_b,
            [((d_saved, s), dt) for dt in (np.int32, np.uint32, np.uint32, np.int32)],
        )
        host = [np.asarray(a).copy() for a in batch.res]
        lim = min(batch.d, d_saved)
        for h, g in zip(host, grids):
            h[:lim] = g[:lim]
        sh = doc_sharding(batch.mesh)
        batch.res = LwwResident(*[jax.device_put(h, sh) for h in host])
        for di in range(lim):
            slots_b = kv.get(b"doc/%08d/slots" % di)
            if slots_b is not None:
                try:
                    r = Reader(slots_b)
                    so: Dict[Tuple[ContainerID, str], int] = {}
                    for _ in range(r.varint()):
                        ci = r.varint()
                        if ci >= len(cids):
                            raise DecodeError("DeviceMapBatch state: cid index")
                        key = r.str_()
                        s_ = r.varint()
                        if s_ >= s:
                            raise DecodeError("DeviceMapBatch state: slot index")
                        so[(cids[ci], key)] = s_
                    batch.slot_of[di] = so
                except (IndexError, ValueError, UnicodeDecodeError) as e:
                    raise DecodeError(
                        f"DeviceMapBatch state: malformed slots ({e})"
                    ) from None
            vals_b = kv.get(b"doc/%08d/values" % di)
            if vals_b is not None:
                batch.values[di] = _state_read_values(vals_b, cids)
            # registered slots must reference in-range value ordinals
            # (value_maps would IndexError otherwise)
            for _ck, s_ in batch.slot_of[di].items():
                if int(host[3][di, s_]) >= len(batch.values[di]):
                    raise DecodeError("DeviceMapBatch state: value ordinal")
        return batch


class DeviceTreeBatch:
    """Device-resident movable-tree move logs for a doc batch (the tree
    member of the resident family next to DeviceDocBatch/DeviceMapBatch).

    Appends ship only NEW moves (one block scatter); materialization
    sorts each standing log by the global move key (lamport, peer,
    counter) on device and replays the cycle-checked scan
    (ops/tree_batch.tree_replay_log_batch).  Unlike LWW folds, tree
    moves do not commute — a late-arriving concurrent move with a lower
    lamport must replay BEFORE already-applied moves — so the resident
    state is the log, not the folded parents (the reference's
    TreeCacheForDiff keeps the same per-node move sets and re-walks
    them, diff_calc/tree.rs:230-396)."""

    def __init__(self, n_docs: int, move_capacity: int, node_capacity: int, mesh=None,
                 auto_grow: bool = False):
        from ..ops.tree_batch import ROOT, TreeLogCols

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_docs = n_docs
        self.d = _mesh_pad(self.mesh, n_docs)
        self.cap = move_capacity
        self.node_cap = node_capacity
        self.auto_grow = auto_grow
        self.counts = np.zeros(self.d, np.int64)
        # ingest epochs date move rows for compaction (see compact())
        self.epoch = 0
        self.move_epoch = np.full((self.d, move_capacity), -1, np.int64)
        # per-doc node dictionaries + host move metadata for sibling
        # positions: (lamport, peer, counter, target_ord, is_delete, pos)
        self.node_ids: List[Dict] = [dict() for _ in range(self.d)]
        self.nodes: List[list] = [[] for _ in range(self.d)]
        self.move_meta: List[list] = [[] for _ in range(self.d)]
        sh = doc_sharding(self.mesh)
        z = lambda dt, fill: jax.device_put(np.full((self.d, move_capacity), fill, dt), sh)
        self.cols = TreeLogCols(
            lamport=z(np.int32, 0),
            peer_hi=z(np.uint32, 0),
            peer_lo=z(np.uint32, 0),
            counter=z(np.int32, 0),
            target=z(np.int32, 0),
            parent=z(np.int32, ROOT),
            valid=z(bool, False),
        )
        self._defer = None  # coalesced-ingest accumulator
        self._dev_lock = named_rlock("fleet.dev")

    # -- round coalescing (same contract as DeviceDocBatch) ------------
    def begin_coalesce(self) -> None:
        if self._defer is not None:
            raise RuntimeError("coalesce group already open")
        self._defer = _DeferredSeqDevice(self.counts.copy())

    def detach_coalesce(self):
        d, self._defer = self._defer, None
        return d

    def commit_detached(self, d) -> None:
        from ..ops.fugue_batch import pad_bucket
        from ..ops.tree_batch import ROOT

        if d is None or not d.rounds:
            return
        with self._dev_lock:
            fills = dict(
                lamport=0, peer_hi=0, peer_lo=0, counter=0, target=0,
                parent=ROOT, valid=False,
            )
            total = np.zeros(self.d, np.int64)
            for _blk, n_new in d.rounds:
                total += np.asarray(n_new, np.int64)
            width = pad_bucket(int(total.max()), floor=16)
            need = max(
                (int(d.base0[di]) + width
                 for di in range(self.d) if total[di]),
                default=0,
            )
            if need > self.cap:
                # bucket rounding outgrew capacity: per-round fallback
                # (no grow here — it would race the next group's stage)
                off = d.base0.astype(np.int64).copy()
                for blk, n_new in d.rounds:
                    self._device_commit_moves(blk, off.astype(np.int32), n_new)
                    off += np.asarray(n_new, np.int64)
            else:
                blk = {
                    f: np.full((self.d, width), fill,
                               dtype=d.rounds[0][0][f].dtype)
                    for f, fill in fills.items()
                }
                pos = np.zeros(self.d, np.int64)
                for rblk, n_new in d.rounds:
                    for di, k in enumerate(n_new):
                        if not k:
                            continue
                        p = int(pos[di])
                        for f in blk:
                            blk[f][di, p : p + k] = rblk[f][di, :k]
                        pos[di] += k
                self._device_commit_moves(blk, d.base0.astype(np.int32), total)
            obs.counter("pipeline.coalesced_rounds_total").inc(
                len(d.rounds), family="tree"
            )

    def flush_coalesce(self) -> None:
        self.commit_detached(self.detach_coalesce())

    def _device_commit_moves(self, blk, offsets, n_new) -> None:
        obs.counter("fleet.device_launches_total").inc(family="resident_tree")
        obs.counter("fleet.pad_waste_rows_total").inc(
            int(self.d * blk["valid"].shape[1] - int(np.sum(n_new))),
            family="resident_tree",
        )
        with self._dev_lock:
            sh = doc_sharding(self.mesh)
            self.cols = _scatter_tree_rows(
                self.cols,
                {f: jax.device_put(v, sh) for f, v in blk.items()},
                jax.device_put(
                    np.asarray(offsets, np.int32), replicated(self.mesh)
                ),
            )

    def release_doc(self, di: int) -> None:
        """Reset doc ``di`` to a never-used slot (tiered-residency
        eviction; see DeviceDocBatch.release_doc for the contract)."""
        from ..ops.tree_batch import ROOT, TreeLogCols

        self.counts[di] = 0
        self.move_epoch[di, :] = -1
        self.node_ids[di] = {}
        self.nodes[di] = []
        self.move_meta[di] = []
        if self._defer is not None:
            self._defer.base0[di] = 0
        with self._dev_lock:
            fields = list(self.cols._fields)
            fills = dict(
                lamport=0, peer_hi=0, peer_lo=0, counter=0, target=0,
                parent=ROOT, valid=False,
            )
            out = _release_rows(
                tuple(getattr(self.cols, f) for f in fields),
                jnp.int32(di),
                tuple(fills[f] for f in fields),
            )
            self.cols = TreeLogCols(**dict(zip(fields, out)))
        obs.counter("fleet.doc_releases_total").inc(family="tree")

    def append_changes(self, per_doc_changes: Sequence[Optional[Sequence[Change]]], cid) -> None:
        """Incremental ingest: each doc's new causally-ordered changes
        (None = no update); TreeMove ops become appended log rows.  All
        node registration and rows are STAGED before any validation, so
        a capacity error leaves the batch untouched (the DeviceDocBatch
        atomicity contract)."""
        per_doc_changes = list(per_doc_changes) + [None] * (self.d - len(per_doc_changes))
        rows_per_doc: List[list] = []
        staged_nodes: List[list] = []
        for di, changes in enumerate(per_doc_changes):
            rows: list = []
            staged_order: list = []
            rows_per_doc.append(rows)
            staged_nodes.append(staged_order)
            if changes:
                self._explode_changes_into(di, changes, cid, rows, staged_order)
        self._commit_moves(rows_per_doc, staged_nodes)

    def append_payloads(self, per_doc_payloads: Sequence[Optional[bytes]], cid) -> None:
        """Incremental NATIVE ingest: envelope-stripped binary payloads
        -> C++ tree explode (wire order — the device replay sorts by the
        global move key anyway) -> one block scatter.  Falls back to the
        Python decoder per payload on unresolvable input."""
        from ..codec.binary import decode_changes, read_tables
        from ..core.ids import TreeID
        from ..native import available, explode_tree_payload
        from ..ops.tree_batch import ROOT, TRASH

        if not available():
            self.append_changes(
                [decode_changes(p) if p else None for p in per_doc_payloads], cid
            )
            return
        per_doc_payloads = list(per_doc_payloads) + [None] * (
            self.d - len(per_doc_payloads)
        )
        rows_per_doc: List[list] = []
        staged_nodes: List[list] = []
        fallback: List[Tuple[int, bytes]] = []
        for di, payload in enumerate(per_doc_payloads):
            rows: list = []
            staged: Dict = {}
            staged_order: list = []
            rows_per_doc.append(rows)
            staged_nodes.append(staged_order)
            if not payload:
                continue
            ids = self.node_ids[di]
            n_committed = len(self.nodes[di])

            def node_idx(tid):
                i = ids.get(tid)
                if i is None:
                    i = staged.get(tid)
                if i is None:
                    i = n_committed + len(staged_order)
                    staged[tid] = i
                    staged_order.append(tid)
                return i

            try:
                peers_wire, _keys, cids, _r = read_tables(payload)
                try:
                    target = cids.index(cid)
                except ValueError:
                    continue  # no ops for this container
                out = explode_tree_payload(payload, target)
                fl = out["flags"]
                for i in range(len(out["lamport"])):
                    tid = TreeID(
                        int(peers_wire[int(out["target_peer_idx"][i])]),
                        int(out["target_ctr"][i]),
                    )
                    t = node_idx(tid)
                    if fl[i] & 2:  # delete
                        p = TRASH
                        is_del = True
                    elif fl[i] & 4:  # has parent
                        p = node_idx(
                            TreeID(
                                int(peers_wire[int(out["parent_peer_idx"][i])]),
                                int(out["parent_ctr"][i]),
                            )
                        )
                        is_del = False
                    else:
                        p = ROOT
                        is_del = False
                    pos = None
                    if fl[i] & 8:
                        o = int(out["pos_off"][i])
                        pos = bytes(payload[o : o + int(out["pos_len"][i])])
                    rows.append(
                        (
                            int(out["lamport"][i]),
                            int(peers_wire[int(out["peer_idx"][i])]),
                            int(out["counter"][i]),
                            t,
                            p,
                            is_del,
                            pos,
                        )
                    )
            except ValueError:
                rows.clear()
                staged.clear()
                staged_order.clear()
                fallback.append((di, payload))
        for di, payload in fallback:  # python walk per unresolvable payload
            self._explode_changes_into(
                di, decode_changes(payload), cid, rows_per_doc[di], staged_nodes[di]
            )
        self._commit_moves(rows_per_doc, staged_nodes)

    def _explode_changes_into(self, di, changes, cid, rows, staged_order) -> None:
        """Python change walk appending into pre-staged row/node lists
        (the append_payloads fallback)."""
        from ..core.change import TreeMove
        from ..ops.tree_batch import ROOT, TRASH

        ids = self.node_ids[di]
        n_committed = len(self.nodes[di])
        staged = {tid: n_committed + i for i, tid in enumerate(staged_order)}

        def node_idx(tid):
            i = ids.get(tid)
            if i is None:
                i = staged.get(tid)
            if i is None:
                i = n_committed + len(staged_order)
                staged[tid] = i
                staged_order.append(tid)
            return i

        for ch in changes:
            for op in ch.ops:
                if op.container != cid or not isinstance(op.content, TreeMove):
                    continue
                c = op.content
                lam = ch.lamport + (op.counter - ch.ctr_start)
                t = node_idx(c.target)
                if c.is_delete:
                    p = TRASH
                elif c.parent is None:
                    p = ROOT
                else:
                    p = node_idx(c.parent)
                rows.append((lam, ch.peer, op.counter, t, p, c.is_delete, c.position))

    def grow(self, move_capacity: int = None, node_capacity: int = None) -> None:
        """Repack move-log columns and/or raise the node ceiling
        (resident lifecycle, r4 verdict #6).  node_capacity is a launch
        parameter (tree_replay_log_batch pads per launch), so that half
        is a scalar bump."""
        from ..ops.tree_batch import ROOT, TreeLogCols

        if move_capacity is not None and move_capacity > self.cap:
            with self._dev_lock:  # vs an in-flight pipelined commit
                fills = dict(
                    lamport=0, peer_hi=0, peer_lo=0, counter=0, target=0,
                    parent=ROOT, valid=False,
                )
                cols = _pad_axis1(
                    {f: getattr(self.cols, f) for f in self.cols._fields},
                    move_capacity, fills, doc_sharding(self.mesh),
                )
                self.cols = TreeLogCols(**cols)
                me = np.full((self.d, move_capacity), -1, np.int64)
                me[:, : self.cap] = self.move_epoch
                self.move_epoch = me
                self.cap = move_capacity
        if node_capacity is not None and node_capacity > self.node_cap:
            self.node_cap = node_capacity

    def _commit_moves(self, rows_per_doc, staged_nodes) -> None:
        """Shared tail: validate capacities, commit staged nodes, block-
        scatter the new move rows."""
        from ..ops.fugue_batch import pad_bucket
        from ..ops.tree_batch import ROOT

        max_new = (
            pad_bucket(max((len(r) for r in rows_per_doc), default=0), floor=16)
            if any(rows_per_doc)
            else 0
        )
        # validate BEFORE mutating anything
        req_moves = max(
            (int(self.counts[di]) + max_new
             for di, rows in enumerate(rows_per_doc) if rows),
            default=0,
        )
        req_nodes = max(
            (len(self.nodes[di]) + len(staged_nodes[di]) for di in range(self.d)),
            default=0,
        )
        if req_moves > self.cap:
            if self.auto_grow:
                self.grow(move_capacity=_grow_target(req_moves, self.cap))
            else:
                raise RuntimeError(
                    f"DeviceTreeBatch move capacity exceeded: a doc needs "
                    f"{req_moves} rows > {self.cap}"
                )
        if req_nodes > self.node_cap:
            if self.auto_grow:
                self.grow(node_capacity=_grow_target(req_nodes, self.node_cap))
            else:
                raise RuntimeError(
                    f"DeviceTreeBatch node capacity exceeded: a doc needs "
                    f"{req_nodes} nodes > {self.node_cap}"
                )
        # the clock ticks for EVERY appended round — including rounds
        # that stage no move rows (a tree server fed a map-only edit).
        # Every family batch shares this contract (journal epochs are
        # strictly monotone per round): a lazy bump here stamped those
        # rounds' journal records with epoch 0 / duplicate epochs,
        # which recovery replay skips and which un-pin WAL retention
        # under a live follower (chaos seed 4).
        self.epoch += 1
        if not max_new:
            return
        # commit staged node registrations
        for di, staged_order in enumerate(staged_nodes):
            for tid in staged_order:
                self.node_ids[di][tid] = len(self.nodes[di])
                self.nodes[di].append(tid)
        blk_shape = (self.d, max_new)
        blk = {
            "lamport": np.zeros(blk_shape, np.int32),
            "peer_hi": np.zeros(blk_shape, np.uint32),
            "peer_lo": np.zeros(blk_shape, np.uint32),
            "counter": np.zeros(blk_shape, np.int32),
            "target": np.zeros(blk_shape, np.int32),
            "parent": np.full(blk_shape, ROOT, np.int32),
            "valid": np.zeros(blk_shape, bool),
        }
        offsets = np.zeros(self.d, np.int32)
        for di, rows in enumerate(rows_per_doc):
            if not rows:
                continue
            k = len(rows)
            arr = np.asarray([(r[0], r[2], r[3], r[4]) for r in rows], np.int64)
            pu = np.asarray([r[1] for r in rows], np.uint64)
            blk["lamport"][di, :k] = arr[:, 0]
            blk["peer_hi"][di, :k] = (pu >> np.uint64(32)).astype(np.uint32)
            blk["peer_lo"][di, :k] = (pu & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            blk["counter"][di, :k] = arr[:, 1]
            blk["target"][di, :k] = arr[:, 2]
            blk["parent"][di, :k] = arr[:, 3]
            blk["valid"][di, :k] = True
            base = int(self.counts[di])
            offsets[di] = base
            self.move_epoch[di, base : base + k] = self.epoch
            self.counts[di] += k
            self.move_meta[di].extend(
                (r[0], r[1], r[2], r[3], r[5], r[6]) for r in rows
            )
        n_new = [len(r) for r in rows_per_doc]
        if self._defer is not None:
            self._defer.rounds.append((blk, n_new))
        else:
            self._device_commit_moves(blk, offsets, n_new)

    def _replay(self):
        from ..ops.tree_batch import tree_replay_log_batch

        return tree_replay_log_batch(self.cols, self.node_cap)

    def compact(self, stable_epochs: Sequence[Optional[int]]) -> int:
        """Collapse the move log over its causally-stable prefix: per
        node, keep only the WINNING stable move (the last effected one
        in global key order); drop every superseded or cycle-rejected
        stable row.  Rows newer than the doc's stable epoch all stay.

        Sound because (a) every future move's lamport exceeds every
        stable move's lamport (its author's frontier dominates the
        stable set), so future rows sort strictly after the stable
        prefix, and (b) replaying only winners reproduces the stable
        tree state: at any winner's position the reduced state is a
        sub-chain of the full state per node (ABSENT where a superseded
        move once pointed), and the ancestor cycle-walk over sub-chains
        can only stop earlier — a move accepted in full replay is never
        spuriously rejected in the reduced one.  move_meta stays
        row-aligned (children_maps' sibling tiebreak uses relative key
        order, which filtering preserves).  Node dictionaries are not
        reclaimed (targets keep their ordinals).  Returns rows dropped.
        Reference analog: loro's tree uses the same last-writer state
        under its shallow-snapshot floor (shallow_snapshot.rs:16-40)."""
        from ..ops.tree_batch import ROOT, TreeLogCols

        if len(stable_epochs) > self.d:
            raise ValueError(
                f"compact: {len(stable_epochs)} stable_epochs for a "
                f"{self.d}-doc batch"
            )
        stable_epochs = list(stable_epochs) + [None] * (self.d - len(stable_epochs))
        fills = dict(lamport=0, peer_hi=0, peer_lo=0, counter=0,
                     target=0, parent=ROOT, valid=False)
        host = None
        eff = None
        reclaimed = 0
        for di, stable_e in enumerate(stable_epochs):
            if stable_e is None or not int(self.counts[di]):
                continue
            if host is None:
                _parents, eff_dev = self._replay()
                eff = np.asarray(eff_dev)
                host = {f: np.asarray(getattr(self.cols, f)).copy()
                        for f in self.cols._fields}
            k = int(self.counts[di])
            stable = self.move_epoch[di, :k] <= int(stable_e)
            stable &= self.move_epoch[di, :k] >= 0  # undated rows stay
            if not stable.any():
                continue
            lam = host["lamport"][di, :k]
            phi = host["peer_hi"][di, :k]
            plo = host["peer_lo"][di, :k]
            ctr = host["counter"][di, :k]
            tgt = host["target"][di, :k]
            order = np.lexsort((ctr, plo, phi, lam))
            winner: Dict[int, int] = {}
            for r in order:
                if stable[r] and eff[di, r]:
                    winner[int(tgt[r])] = int(r)
            win_rows = set(winner.values())
            keep = ~stable  # unstable rows all stay
            for r in win_rows:
                keep[r] = True
            n_keep = int(keep.sum())
            if n_keep == k:
                continue
            reclaimed += k - n_keep
            old_rows = np.flatnonzero(keep)  # original append order
            for f in self.cols._fields:
                row = host[f][di]
                vals = row[:k][old_rows]  # fancy index: already a copy
                row[:] = fills[f]
                row[:n_keep] = vals
            me = self.move_epoch[di, :k][old_rows]
            self.move_epoch[di, :] = -1
            self.move_epoch[di, :n_keep] = me
            self.move_meta[di] = [self.move_meta[di][int(r)] for r in old_rows]
            self.counts[di] = n_keep
        if host is not None and reclaimed:
            sh = doc_sharding(self.mesh)
            self.cols = TreeLogCols(
                **{f: jax.device_put(v, sh) for f, v in host.items()}
            )
        return reclaimed

    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection for the sync pull path."""
        return _batch_export_select(self, "tree", index, requests, sup)

    def parent_maps(self) -> List[dict]:
        """{TreeID: parent TreeID | None} of alive nodes per doc (one
        launch; same contract as Fleet.merge_tree_changes)."""
        from ..ops.tree_batch import ABSENT, ROOT, is_deleted_batch

        parents, _eff = self._replay()
        deleted = np.asarray(is_deleted_batch(parents))
        parents = np.asarray(parents)
        out = []
        for di in range(self.n_docs):
            res = {}
            nodes = self.nodes[di]
            for j, tid in enumerate(nodes):
                p = int(parents[di, j])
                if p == ABSENT or deleted[di, j]:
                    continue
                res[tid] = None if p == ROOT else nodes[p]
            out.append(res)
        return out

    # -- checkpoint/resume --------------------------------------------
    STATE_VERSION = 2  # v2: + epoch clock, move-epoch columns
    _STATE_SCHEMA = (
        ("lamport", np.int32),
        ("peer_hi", np.uint32),
        ("peer_lo", np.uint32),
        ("counter", np.int32),
        ("target", np.int32),
        ("parent", np.int32),
    )

    def export_state(self) -> bytes:
        """Serialize the resident move logs + node dictionaries + host
        move metadata (fractional positions) into an LTKV store."""
        from ..codec.binary import Writer
        from ..storage import MemKvStore

        kv = MemKvStore()
        meta = Writer()
        meta.u8(self.STATE_VERSION)
        meta.varint(self.n_docs)
        meta.varint(self.d)
        meta.varint(self.cap)
        meta.varint(self.node_cap)
        for di in range(self.d):
            meta.varint(int(self.counts[di]))
        meta.varint(self.epoch)  # v2
        meta.u8(1 if self.auto_grow else 0)  # v2
        kv.set(b"meta", bytes(meta.buf))
        cols = {f: np.asarray(getattr(self.cols, f)) for f, _ in self._STATE_SCHEMA}
        for di in range(self.d):
            k = int(self.counts[di])
            w = Writer()
            for f, dt in self._STATE_SCHEMA:
                w.bytes_(cols[f][di, :k].astype(dt).tobytes())
            kv.set(b"doc/%08d/log" % di, bytes(w.buf))
            if k:
                kv.set(
                    b"doc/%08d/moveepoch" % di,
                    self.move_epoch[di, :k].astype(np.int64).tobytes(),
                )
            w = Writer()
            w.varint(len(self.nodes[di]))
            for tid in self.nodes[di]:
                w.u64le(tid.peer)
                w.zigzag(tid.counter)
            kv.set(b"doc/%08d/nodes" % di, bytes(w.buf))
            w = Writer()
            w.varint(len(self.move_meta[di]))
            for lam, peer, ctr, t, is_del, pos in self.move_meta[di]:
                w.varint(lam)
                w.u64le(peer)
                w.zigzag(ctr)
                w.varint(t)
                w.u8((1 if is_del else 0) | (2 if pos is not None else 0))
                if pos is not None:
                    w.bytes_(pos)
            kv.set(b"doc/%08d/meta" % di, bytes(w.buf))
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "DeviceTreeBatch":
        from ..codec.binary import Reader
        from ..core.ids import TreeID
        from ..errors import DecodeError
        from ..ops.tree_batch import TreeLogCols
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b = kv.get(b"meta")
        if meta_b is None:
            raise DecodeError("DeviceTreeBatch state: missing meta")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"DeviceTreeBatch state v{version} too new")
            n_docs, d_saved = r.varint(), r.varint()
            cap, node_cap = r.varint(), r.varint()
            counts = [r.varint() for _ in range(d_saved)]
            epoch = r.varint() if version >= 2 else 0
            auto_grow = (r.u8() == 1) if version >= 2 else False
        except (IndexError, ValueError) as e:
            raise DecodeError(f"DeviceTreeBatch state: malformed meta ({e})") from None
        _state_sane_sizes("DeviceTreeBatch", d_saved, move_capacity=cap, node_capacity=node_cap)
        if not 0 < n_docs <= d_saved:
            raise DecodeError("DeviceTreeBatch state: implausible n_docs")
        batch = cls(n_docs, cap, node_cap, mesh=mesh, auto_grow=auto_grow)
        batch.epoch = epoch
        for di in range(batch.d, d_saved):
            if counts[di]:
                raise DecodeError("DeviceTreeBatch state: importer mesh too narrow")
        host = {f: np.asarray(getattr(batch.cols, f)).copy() for f in batch.cols._fields}
        try:
            for di in range(min(batch.d, d_saved)):
                k = counts[di]
                if k > cap:
                    raise DecodeError("DeviceTreeBatch state: count exceeds capacity")
                log_b = kv.get(b"doc/%08d/log" % di)
                if k and log_b is None:
                    raise DecodeError(f"DeviceTreeBatch state: missing log for doc {di}")
                if log_b is not None:
                    r = Reader(log_b)
                    for f, dt in cls._STATE_SCHEMA:
                        buf = np.frombuffer(r.bytes_(), dt)
                        if len(buf) != k:
                            raise DecodeError("DeviceTreeBatch state: log column length")
                        host[f][di, :k] = buf.astype(host[f].dtype)
                    host["valid"][di, :k] = True
                    batch.counts[di] = k
                    me_b = kv.get(b"doc/%08d/moveepoch" % di)
                    if me_b is not None:
                        me = np.frombuffer(me_b, np.int64)
                        if len(me) != k:
                            raise DecodeError(
                                "DeviceTreeBatch state: move epoch column length"
                            )
                        batch.move_epoch[di, :k] = me
                nodes_b = kv.get(b"doc/%08d/nodes" % di)
                if nodes_b is not None:
                    r = Reader(nodes_b)
                    nodes = []
                    for _ in range(r.varint()):
                        nodes.append(TreeID(r.u64le(), r.zigzag()))
                    if len(nodes) > node_cap:
                        raise DecodeError("DeviceTreeBatch state: node overflow")
                    batch.nodes[di] = nodes
                    batch.node_ids[di] = {tid: i for i, tid in enumerate(nodes)}
                mm_b = kv.get(b"doc/%08d/meta" % di)
                if mm_b is not None:
                    r = Reader(mm_b)
                    mm = []
                    for _ in range(r.varint()):
                        lam = r.varint()
                        peer = r.u64le()
                        ctr = r.zigzag()
                        t = r.varint()
                        flags = r.u8()
                        pos = r.bytes_() if flags & 2 else None
                        mm.append((lam, peer, ctr, t, bool(flags & 1), pos))
                    batch.move_meta[di] = mm
                if k:
                    # node ordinals must stay inside the node dict
                    # (parent_maps would IndexError on nodes[p])
                    n_nodes = len(batch.nodes[di])
                    tgt = host["target"][di, :k].astype(np.int64)
                    par = host["parent"][di, :k].astype(np.int64)
                    if tgt.min() < 0 or tgt.max() >= n_nodes:
                        raise DecodeError("DeviceTreeBatch state: target ordinal")
                    if par.min() < -2 or par.max() >= n_nodes:
                        raise DecodeError("DeviceTreeBatch state: parent ordinal")
        except (IndexError, ValueError, struct.error) as e:
            raise DecodeError(f"DeviceTreeBatch state: malformed doc ({e})") from None
        sh = doc_sharding(batch.mesh)
        batch.cols = TreeLogCols(**{f: jax.device_put(v, sh) for f, v in host.items()})
        return batch

    def children_maps(self) -> List[dict]:
        """{parent | None: [children in (fractional-index, move-key)
        order]} per doc — the materialized tree shape (same contract as
        Fleet.merge_tree_children)."""
        from ..ops.tree_batch import ABSENT, ROOT, is_deleted_batch

        parents, eff = self._replay()
        deleted = np.asarray(is_deleted_batch(parents))
        parents = np.asarray(parents)
        eff = np.asarray(eff)
        out = []
        for di in range(self.n_docs):
            nodes = self.nodes[di]
            # winning position = last effected non-delete move per node
            # in key order; sibling tiebreak = the winning move's key
            # order (exactly merge_tree_children's host walk)
            meta = self.move_meta[di]
            order = sorted(range(len(meta)), key=lambda i: meta[i][:3])
            pos: Dict[int, object] = {}
            last_eff: Dict[int, int] = {}
            for oi, i in enumerate(order):
                _lam, _peer, _ctr, t, is_del, p_ = meta[i]
                if eff[di, i]:
                    last_eff[t] = oi
                    if not is_del:
                        pos[t] = p_
            kids: Dict = {}
            for j, tid in enumerate(nodes):
                p = int(parents[di, j])
                if p == ABSENT or deleted[di, j]:
                    continue
                key = None if p == ROOT else nodes[p]
                kids.setdefault(key, []).append(
                    (pos.get(j) or b"", last_eff.get(j, 0), tid)
                )
            out.append(
                {
                    k: [t for _, _, t in sorted(v, key=lambda x: (x[0], x[1]))]
                    for k, v in kids.items()
                }
            )
        return out


class _DeferredSeqDevice:
    """Accumulated device work of one coalesced ingest group over a
    DeviceDocBatch/DeviceTreeBatch: per-round host blocks (already
    host-committed — epochs, order engines, id maps, counts) waiting
    for the single merged scatter at flush_coalesce()."""

    __slots__ = ("base0", "rounds", "renumbered", "del_d", "del_r", "key_snap")

    def __init__(self, base0: np.ndarray):
        self.base0 = base0          # per-doc counts at group start
        self.rounds: List[tuple] = []
        self.renumbered: set = set()
        self.del_d: List[np.ndarray] = []
        self.del_r: List[np.ndarray] = []
        self.key_snap = None        # detach-time key rows (renumbered docs)


class _DeferredFold:
    """Accumulated fold rows of one coalesced group over an LWW/counter
    resident (per-doc row lists concatenated across rounds; the folds
    are associative — max by (lamport, peer) / float add — so one
    merged fold lands the same winners as one fold per round)."""

    __slots__ = ("rows", "n_rounds")

    def __init__(self, n_docs: int):
        self.rows: List[list] = [[] for _ in range(n_docs)]
        self.n_rounds = 0  # non-empty rounds folded (metric unit parity
        #                    with the seq/tree per-round block counts)

    def extend(self, rows_per_doc) -> None:
        if any(rows_per_doc):
            self.n_rounds += 1
        for di, rows in enumerate(rows_per_doc):
            if rows:
                self.rows[di].extend(rows)


def _windowed_scatter_field(col, nbl, vbl, off):
    """One doc-row of the block scatter: padding rows of a block restore
    the window's previous values so short updates don't clobber
    neighbors (shared by the seq and tree resident ingest paths)."""
    window = jax.lax.dynamic_slice(col, (off,), (nbl.shape[0],))
    return jax.lax.dynamic_update_slice(col, jnp.where(vbl, nbl, window), (off,))


@functools.partial(jax.jit, donate_argnums=(0,))
def _set_key_rows(keys, d_idx, kh_rows, kl_rows):
    """Replace whole key rows for renumbered docs (donated, one launch
    for the whole epoch; duplicate pad indices write identical rows)."""
    key_hi, key_lo = keys
    return key_hi.at[d_idx].set(kh_rows), key_lo.at[d_idx].set(kl_rows)


@functools.partial(jax.jit, donate_argnums=(0,))
def _set_deleted(deleted, d_idx, r_idx):
    """Tombstone (doc, row) pairs in one donated launch."""
    return deleted.at[d_idx, r_idx].set(True)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(2,))
def _release_rows(arrays, di, fills):
    """Reset doc row ``di`` of every [d, cap] array to its construction
    fill (donated, one launch) — the device half of ``release_doc``:
    the tiered-residency eviction path (parallel/residency.py) recycles
    the slot for a different doc, so the row must be indistinguishable
    from a never-used one.  ``fills`` is a static tuple aligned with
    ``arrays``; shapes are the resident capacities, so there is exactly
    one compile per family per capacity bucket (LT-PAD holds: no
    data-dependent shapes)."""
    return tuple(
        a.at[di].set(jnp.full((a.shape[1],), f, a.dtype))  # tpulint: disable=LT-PAD(in-jit row fill at the array's OWN static capacity — already bucketed at allocation, no new shape can exist)
        for a, f in zip(arrays, fills)
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(state, blk, offsets):
    """Write each doc's new-row block at its per-doc offset (donated
    update — the old buffer is reused, no [D, N] copy).  `state` is
    (SeqColumnsU, key_hi, key_lo)."""
    cols, key_hi, key_lo = state
    out = {}
    for f in cols._fields:
        out[f] = jax.vmap(_windowed_scatter_field)(
            getattr(cols, f), blk[f], blk["valid"], offsets
        )
    new_hi = jax.vmap(_windowed_scatter_field)(key_hi, blk["key_hi"], blk["valid"], offsets)
    new_lo = jax.vmap(_windowed_scatter_field)(key_lo, blk["key_lo"], blk["valid"], offsets)
    return type(cols)(**out), new_hi, new_lo


class DeviceMovableBatch:
    """Device-resident MovableList state for a doc batch — the last
    member of the resident family.

    Decomposition (reference semantics diff_calc.rs:1669-2020): position
    SLOTS are sequence elements (they ride an internal DeviceDocBatch:
    standing ShadowOrder keys, O(delta) ingest, tombstones); per element
    the winning slot (last move by (lamport, peer)) and winning value
    (last set) are LWW — both kept as RESIDENT folds (LwwResident with
    the slot ROW / value ordinal as the folded value).  Materialization
    is ONE [E]-sized sort: each element gathers its winning slot's
    standing key + tombstone (a tombstoned winner hides the element; a
    newer concurrent move revives it), no slot-level re-rank."""

    def __init__(self, n_docs: int, capacity: int, elem_capacity: int, mesh=None,
                 auto_grow: bool = False):
        from ..ops.lww import NEG, LwwResident

        self.seq = DeviceDocBatch(
            n_docs, capacity, mesh=mesh, as_text=False, auto_grow=auto_grow
        )
        self.mesh = self.seq.mesh
        self.n_docs = n_docs
        self.d = self.seq.d
        self.e_cap = elem_capacity
        self.auto_grow = auto_grow
        self.elem_ids: List[Dict] = [dict() for _ in range(self.d)]
        self.values: List[list] = [[] for _ in range(self.d)]
        sh = doc_sharding(self.mesh)
        z = lambda dt, fill: jax.device_put(np.full((self.d, elem_capacity), fill, dt), sh)
        mk = lambda vfill: LwwResident(
            lamport=z(np.int32, int(NEG)),
            peer_hi=z(np.uint32, 0),
            peer_lo=z(np.uint32, 0),
            value=z(np.int32, vfill),
        )
        self.moves = mk(0)  # value = winning slot ROW in the seq buffer
        self.vals = mk(-2)  # value = winning value ordinal
        self._defer_moves = None  # coalesced-ingest accumulators
        self._defer_vals = None
        self._dev_lock = named_rlock("fleet.dev")

    # -- round coalescing (slots ride the inner seq batch's deferral;
    # the two element folds accumulate here — both associative) --------
    def begin_coalesce(self) -> None:
        if self._defer_moves is not None:
            raise RuntimeError("coalesce group already open")
        self.seq.begin_coalesce()
        self._defer_moves = _DeferredFold(self.d)
        self._defer_vals = _DeferredFold(self.d)

    def detach_coalesce(self):
        dm, self._defer_moves = self._defer_moves, None
        dv, self._defer_vals = self._defer_vals, None
        return (self.seq.detach_coalesce(), dm, dv)

    def commit_detached(self, pending) -> None:
        if pending is None:
            return
        seq_d, dm, dv = pending
        self.seq.commit_detached(seq_d)
        if dm is not None and any(dm.rows):
            self._device_fold_elem(dm.rows, "moves")
        if dv is not None and any(dv.rows):
            self._device_fold_elem(dv.rows, "vals")
        if dm is not None and dm.n_rounds:
            obs.counter("pipeline.coalesced_rounds_total").inc(
                dm.n_rounds, family="movable"
            )

    def flush_coalesce(self) -> None:
        self.commit_detached(self.detach_coalesce())

    def release_doc(self, di: int) -> None:
        """Reset doc ``di`` to a never-used slot (tiered-residency
        eviction; see DeviceDocBatch.release_doc for the contract).
        The inner seq batch releases its slot rows; both element folds
        reset to their construction fills."""
        from ..ops.lww import NEG, LwwResident

        self.seq.release_doc(di)
        self.elem_ids[di] = {}
        self.values[di] = []
        with self._dev_lock:
            self.moves = LwwResident(*_release_rows(
                tuple(self.moves), jnp.int32(di), (int(NEG), 0, 0, 0),
            ))
            self.vals = LwwResident(*_release_rows(
                tuple(self.vals), jnp.int32(di), (int(NEG), 0, 0, -2),
            ))
        obs.counter("fleet.doc_releases_total").inc(family="movable")

    def append_changes(self, per_doc_changes: Sequence[Optional[Sequence[Change]]], cid) -> None:
        """Incremental ingest: slots append into the internal seq batch
        (one block scatter), element winners fold (two donated LWW
        updates).  Staged before validation — capacity errors leave the
        batch untouched."""
        # NOTE: _walk_movable_changes intentionally mirrors
        # DeviceDocBatch._python_rows (same parent-resolution and
        # delete-span contract) but diverges in what it PRODUCES per row
        # (element ordinals + move/set fold rows vs content codes) — a
        # shared walk would need per-row callbacks for every arm; the
        # differential fuzzers pin both walks to the host engine.
        per_doc_changes = list(per_doc_changes) + [None] * (self.d - len(per_doc_changes))
        rows_per_doc: List[list] = []
        overlays: List[Dict[Tuple[int, int], int]] = []
        move_rows: List[list] = []  # (elem, lam, peer, slot_row)
        set_rows: List[list] = []  # (elem, lam, peer, value_ordinal)
        staged_elems: List[list] = []
        staged_vals: List[list] = []
        del_pairs: List[Tuple[int, int]] = []
        for di, changes in enumerate(per_doc_changes):
            rows, overlay, mrows, srows, e_staged, e_order, v_staged = self._stage_doc(
                rows_per_doc, overlays, move_rows, set_rows, staged_elems, staged_vals
            )
            if not changes:
                continue
            self._walk_movable_changes(
                di, changes, cid, rows, overlay, mrows, srows,
                e_staged, e_order, v_staged, del_pairs,
            )
        self._commit_movable(
            rows_per_doc, overlays, move_rows, set_rows,
            staged_elems, staged_vals, del_pairs,
        )

    @staticmethod
    def _stage_doc(rows_per_doc, overlays, move_rows, set_rows, staged_elems, staged_vals):
        """Allocate + register one doc's staging structures (shared by
        both ingest entry points so they commit identical shapes)."""
        rows: list = []
        overlay: Dict[Tuple[int, int], int] = {}
        mrows: list = []
        srows: list = []
        e_staged: Dict = {}
        e_order: list = []
        v_staged: list = []
        rows_per_doc.append(rows)
        overlays.append(overlay)
        move_rows.append(mrows)
        set_rows.append(srows)
        staged_elems.append(e_order)
        staged_vals.append(v_staged)
        return rows, overlay, mrows, srows, e_staged, e_order, v_staged

    def _elem_registrar(self, di, e_staged, e_order):
        """Staged element-ordinal lookup shared by BOTH ingest paths —
        the numbering must stay in lockstep with the commit loop."""
        eids = self.elem_ids[di]

        def eidx(eid):
            i = eids.get(eid)
            if i is None:
                i = e_staged.get(eid)
            if i is None:
                i = len(eids) + len(e_order)
                e_staged[eid] = i
                e_order.append(eid)
            return i

        return eidx

    def _walk_movable_changes(
        self, di, changes, cid, rows, overlay, mrows, srows,
        e_staged, e_order, v_staged, del_pairs,
    ) -> None:
        """Per-doc python change walk (also the append_payloads
        fallback): produces slot rows + move/set fold rows + staged
        element/value registrations."""
        from ..core.change import MovableMove, MovableSet, SeqDelete, SeqInsert
        from ..oplog.oplog import _RunCont

        idmap = self.seq.id2row[di]
        base = int(self.seq.counts[di])
        n_vals = len(self.values[di])
        eidx = self._elem_registrar(di, e_staged, e_order)

        def vidx(v):
            v_staged.append(v)
            return n_vals + len(v_staged) - 1

        def resolve(key):
            return _resolve_row(overlay, idmap, key, di, "movable op parent")

        def resolve_parent(c, peer, counter):
            if isinstance(c.parent, _RunCont):
                return resolve((peer, counter - 1))
            if c.parent is None:
                return -1
            return resolve((c.parent.peer, c.parent.counter))

        for ch in changes:
            for op in ch.ops:
                if op.container != cid:
                    continue
                c = op.content
                lam = ch.lamport + (op.counter - ch.ctr_start)
                if isinstance(c, SeqInsert):
                    body = c.content
                    for j in range(len(body)):
                        if j == 0:
                            prow = resolve_parent(c, ch.peer, op.counter)
                            side = int(c.side)
                        else:
                            prow = base + len(rows) - 1
                            side = 1
                        row = base + len(rows)
                        eid = (ch.peer, op.counter + j)
                        ei = eidx(eid)
                        overlay[eid] = row
                        rows.append((prow, side, op.counter + j, ei, ch.peer))
                        mrows.append((ei, lam + j, ch.peer, row))
                        srows.append((ei, lam + j, ch.peer, vidx(body[j])))
                elif isinstance(c, MovableMove):
                    prow = resolve_parent(c, ch.peer, op.counter)
                    row = base + len(rows)
                    ei = eidx((c.elem.peer, c.elem.counter))
                    overlay[(ch.peer, op.counter)] = row
                    rows.append((prow, int(c.side), op.counter, ei, ch.peer))
                    mrows.append((ei, lam, ch.peer, row))
                elif isinstance(c, MovableSet):
                    ei = eidx((c.elem.peer, c.elem.counter))
                    srows.append((ei, lam, ch.peer, vidx(c.value)))
                elif isinstance(c, SeqDelete):
                    # deletes tolerate unknown targets (same as the
                    # native paths): a missing target means the insert
                    # is missing too, which the parent resolution flags
                    for sp in c.spans:
                        for ctr in range(sp.start, sp.end):
                            row_d = overlay.get((sp.peer, ctr))
                            if row_d is None:
                                row_d = idmap.get((sp.peer, ctr))
                            if row_d is not None:
                                del_pairs.append((di, row_d))

    def append_payloads(self, per_doc_payloads: Sequence[Optional[bytes]], cid) -> None:
        """Incremental NATIVE ingest: envelope-stripped payloads -> C++
        movable delta explode (cross-epoch slot parents resolved through
        the seq batch's id maps via the ext-ref protocol) -> one block
        scatter + two donated folds.  Falls back to the Python walk per
        unresolvable payload."""
        from ..codec.binary import decode_changes, read_tables
        from ..native import available, decode_value_at, explode_movable_delta_payload

        if not available():
            self.append_changes(
                [decode_changes(p) if p else None for p in per_doc_payloads], cid
            )
            return
        per_doc_payloads = list(per_doc_payloads) + [None] * (
            self.d - len(per_doc_payloads)
        )
        rows_per_doc: List[list] = []
        overlays: List[Dict[Tuple[int, int], int]] = []
        move_rows: List[list] = []
        set_rows: List[list] = []
        staged_elems: List[list] = []
        staged_vals: List[list] = []
        del_pairs: List[Tuple[int, int]] = []
        for di, payload in enumerate(per_doc_payloads):
            rows, overlay, mrows, srows, e_staged, e_order, v_staged = self._stage_doc(
                rows_per_doc, overlays, move_rows, set_rows, staged_elems, staged_vals
            )
            if not payload:
                continue
            idmap = self.seq.id2row[di]
            base = int(self.seq.counts[di])
            n_vals = len(self.values[di])
            n_dels_start = len(del_pairs)
            eidx = self._elem_registrar(di, e_staged, e_order)

            # NOTE: per-row python loop (vs the seq analog's vectorized
            # fast path) — movable epochs are move/set-dominated and
            # small; vectorize like DeviceDocBatch.append_payloads if a
            # full-history movable ingest ever shows up hot
            try:
                peers_wire, _keys, cids, _r = read_tables(payload)
                try:
                    target = cids.index(cid)
                except ValueError:
                    continue  # no ops for this container
                out = explode_movable_delta_payload(payload, target)
                sl = out["slots"]
                n = len(sl["parent"])
                for i in range(n):
                    prow = int(sl["parent"][i])
                    if prow >= 0:
                        prow = base + prow
                    elif prow == -2:  # cross-epoch parent: id-map lookup
                        key = (
                            int(peers_wire[int(sl["ext_peer_idx"][i])]),
                            int(sl["ext_counter"][i]),
                        )
                        r_ = overlay.get(key)
                        prow = idmap[key] if r_ is None else r_
                    peer = int(peers_wire[int(sl["peer_idx"][i])])
                    ctr_v = int(sl["counter"][i])
                    ei = eidx(
                        (int(peers_wire[int(sl["elem_peer_idx"][i])]), int(sl["elem_ctr"][i]))
                    )
                    row = base + i
                    overlay[(peer, ctr_v)] = row
                    rows.append((prow, int(sl["side"][i]), ctr_v, ei, peer))
                    mrows.append((ei, int(sl["lamport"][i]), peer, row))
                st = out["sets"]
                for i in range(len(st["lamport"])):
                    ei = eidx(
                        (int(peers_wire[int(st["elem_peer_idx"][i])]), int(st["elem_ctr"][i]))
                    )
                    v_staged.append(
                        decode_value_at(payload, int(st["value_off"][i]), cids)
                    )
                    srows.append(
                        (
                            ei,
                            int(st["lamport"][i]),
                            int(peers_wire[int(st["peer_idx"][i])]),
                            n_vals + len(v_staged) - 1,
                        )
                    )
                dl = out["dels"]
                for i in range(len(dl["peer_idx"])):
                    dp = int(peers_wire[int(dl["peer_idx"][i])])
                    for ctr_v in range(int(dl["start"][i]), int(dl["end"][i])):
                        row = overlay.get((dp, ctr_v))
                        if row is None:
                            row = idmap.get((dp, ctr_v))
                        if row is not None:
                            del_pairs.append((di, row))
            except (KeyError, ValueError):
                rows.clear()
                overlay.clear()
                mrows.clear()
                srows.clear()
                e_staged.clear()
                e_order.clear()
                v_staged.clear()
                del del_pairs[n_dels_start:]
                self._walk_movable_changes(
                    di, decode_changes(payload), cid, rows, overlay, mrows,
                    srows, e_staged, e_order, v_staged, del_pairs,
                )
        self._commit_movable(
            rows_per_doc, overlays, move_rows, set_rows,
            staged_elems, staged_vals, del_pairs,
        )

    @property
    def epoch(self) -> int:
        """Ingest-epoch clock (rides the inner seq batch; snapshot after
        an append, pass back to compact() once every replica acked it)."""
        return self.seq.epoch

    def compact(self, stable_epochs: Sequence[Optional[int]]) -> int:
        """Reclaim stable dead SLOT rows: tombstoned ones (deleted
        elements' history) AND superseded ones — a move's losing slot is
        invisible forever but only droppable once the WINNING slot's
        ingest epoch is acked everywhere (a replica that hasn't seen the
        winner still treats the old slot as visible).  Slots are
        sequence elements, so the seq batch's compaction rules apply;
        every element's winning slot row (the moves fold stores device
        ROW indices) is protected and the fold is rewritten through the
        row remap afterwards.  Element registries and value stores are
        untouched (ordinals, not rows)."""
        from ..ops.lww import NEG

        stable_list = list(stable_epochs) + [None] * (self.d - len(stable_epochs))
        if all(e is None for e in stable_list):
            return 0  # nothing to do: skip the device fetches
        mh = np.asarray(self.moves.value).copy()
        # untouched fold slots carry the value FILL (0) — only slots a
        # move actually folded into (lamport != NEG) reference rows
        folded = np.asarray(self.moves.lamport) != int(NEG)
        mh[~folded] = -1
        content = np.asarray(self.seq.cols.content)
        protect: List[Optional[np.ndarray]] = []
        extra_dead: List[Optional[np.ndarray]] = []
        for di in range(self.d):
            wr = mh[di][mh[di] >= 0].astype(np.int64)
            protect.append(np.unique(wr) if len(wr) else None)
            stable_e = stable_list[di]
            k = int(self.seq.counts[di])
            if stable_e is None or not k or not len(wr):
                extra_dead.append(None)
                continue
            # superseded slot r (element e = content[r], winner w != r)
            # is stable-dead when the winner's ingest epoch is acked
            e_arr = content[di, :k].astype(np.int64)
            valid_e = e_arr >= 0
            w_of_row = np.where(valid_e, mh[di][np.clip(e_arr, 0, None)], -1)
            w_epoch = np.where(
                w_of_row >= 0,
                self.seq.row_epoch[di][np.clip(w_of_row, 0, None)],
                -1,
            )
            sup = (
                valid_e
                & (w_of_row >= 0)
                & (w_of_row != np.arange(k))
                & (w_epoch >= 0)
                & (w_epoch <= int(stable_e))
            )
            rows_s = np.flatnonzero(sup)
            extra_dead.append(rows_s if len(rows_s) else None)
        reclaimed, remaps = self.seq.compact(
            stable_epochs,
            extra_protect=protect,
            extra_dead=extra_dead,
            return_remaps=True,
        )
        if reclaimed and remaps:
            # rewrite on a FRESH copy: mh is the protection scratch with
            # unfolded slots forced to -1, and persisting that would
            # change the documented fill (0) of untouched fold slots
            out = np.asarray(self.moves.value).copy()
            for di, remap in remaps.items():
                row = out[di]
                mask = folded[di] & (row >= 0) & (row < len(remap))
                row[mask] = remap[row[mask]]
            self.moves = self.moves._replace(
                value=jax.device_put(out, doc_sharding(self.mesh))
            )
        return reclaimed

    def grow(self, capacity: int = None, elem_capacity: int = None) -> None:
        """Repack: slot rows grow through the inner seq batch; element
        winner columns re-pad here (resident lifecycle, r4 verdict #6)."""
        from ..ops.lww import LwwResident

        if capacity is not None:
            self.seq.grow(capacity)
        if elem_capacity is not None and elem_capacity > self.e_cap:
            with self._dev_lock:  # vs an in-flight pipelined commit
                sh = doc_sharding(self.mesh)
                for name, vfill in (("moves", 0), ("vals", -2)):
                    res = getattr(self, name)
                    fills = _lww_fills(vfill)
                    setattr(
                        self,
                        name,
                        LwwResident(**_pad_axis1(
                            {f: getattr(res, f) for f in res._fields},
                            elem_capacity, fills, sh,
                        )),
                    )
                self.e_cap = elem_capacity

    def _commit_movable(
        self, rows_per_doc, overlays, move_rows, set_rows,
        staged_elems, staged_vals, del_pairs,
    ) -> None:
        """Shared tail: validate, commit registrations, scatter + folds."""
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import lww_update_resident

        # validate BEFORE mutating (element capacity; the seq batch
        # validates row capacity in _commit_rows before ITS mutation)
        req_elems = max(
            (len(self.elem_ids[di]) + len(staged_elems[di]) for di in range(self.d)),
            default=0,
        )
        if req_elems > self.e_cap:
            if self.auto_grow:
                self.grow(elem_capacity=_grow_target(req_elems, self.e_cap))
            else:
                raise RuntimeError(
                    f"DeviceMovableBatch element capacity exceeded: a doc "
                    f"needs {req_elems} elements > {self.e_cap}"
                )
        self.seq._commit_rows(rows_per_doc, overlays, del_pairs)
        # commit staged element/value registrations
        for di in range(self.d):
            for eid in staged_elems[di]:
                self.elem_ids[di][eid] = len(self.elem_ids[di])
            self.values[di].extend(staged_vals[di])
        # fold element winners (moves then values)
        if self._defer_moves is not None:
            set_only = not any(move_rows) and any(set_rows)
            self._defer_moves.extend(move_rows)
            self._defer_vals.extend(set_rows)
            if set_only:
                # a set-only round still shipped device work: count it
                # on the moves accumulator (the group's round tally)
                self._defer_moves.n_rounds += 1
            return
        for rows_set, res_name in ((move_rows, "moves"), (set_rows, "vals")):
            if any(rows_set):
                self._device_fold_elem(rows_set, res_name)

    def _device_fold_elem(self, rows_set, res_name: str) -> None:
        from ..ops.fugue_batch import pad_bucket
        from ..ops.lww import lww_update_resident

        obs.counter("fleet.device_launches_total").inc(family="resident_movable")
        with self._dev_lock:
            sh = doc_sharding(self.mesh)
            put = lambda a: jax.device_put(a, sh)
            m = pad_bucket(max(len(r) for r in rows_set), floor=16)
            shp = (self.d, m)
            elem = np.full(shp, self.e_cap, np.int32)
            lam = np.zeros(shp, np.int32)
            hi = np.zeros(shp, np.uint32)
            lo = np.zeros(shp, np.uint32)
            val = np.full(shp, -2, np.int32)
            valid = np.zeros(shp, bool)
            for di, rws in enumerate(rows_set):
                for i, (ei, lm, peer, v) in enumerate(rws):
                    elem[di, i] = ei
                    lam[di, i] = lm
                    hi[di, i] = peer >> 32
                    lo[di, i] = peer & 0xFFFFFFFF
                    val[di, i] = v
                    valid[di, i] = True
            setattr(
                self,
                res_name,
                lww_update_resident(
                    getattr(self, res_name),
                    put(elem),
                    put(lam),
                    put(hi),
                    put(lo),
                    put(valid),
                    self.e_cap,
                    value=put(val),
                ),
            )

    # -- checkpoint/resume --------------------------------------------
    STATE_VERSION = 2  # v2: + auto_grow lifecycle flag

    def export_state(self) -> bytes:
        """Serialize the movable batch: the nested slot-sequence batch
        rides its own export; element folds, dictionaries and values
        layer on top."""
        from ..codec.binary import Writer, _Dicts
        from ..storage import MemKvStore

        kv = MemKvStore()
        d = _Dicts()
        meta = Writer()
        meta.u8(self.STATE_VERSION)
        meta.varint(self.n_docs)
        meta.varint(self.d)
        meta.varint(self.e_cap)
        meta.u8(1 if self.auto_grow else 0)  # v2
        kv.set(b"meta", bytes(meta.buf))
        kv.set(b"seq", self.seq.export_state())
        _state_write_grid(kv, b"moves", [np.asarray(a) for a in self.moves])
        _state_write_grid(kv, b"vals", [np.asarray(a) for a in self.vals])
        for di in range(self.d):
            w = Writer()
            w.varint(len(self.elem_ids[di]))
            for (peer, ctr), i in self.elem_ids[di].items():
                w.u64le(peer)
                w.zigzag(ctr)
                w.varint(i)
            kv.set(b"doc/%08d/elems" % di, bytes(w.buf))
            w = Writer()
            _state_write_values(w, d, self.values[di])
            kv.set(b"doc/%08d/values" % di, bytes(w.buf))
        kv.set(b"dicts", _state_dicts_blob(d))
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "DeviceMovableBatch":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..ops.lww import LwwResident
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, dicts_b, seq_b = kv.get(b"meta"), kv.get(b"dicts"), kv.get(b"seq")
        if meta_b is None or dicts_b is None or seq_b is None:
            raise DecodeError("DeviceMovableBatch state: missing sections")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"DeviceMovableBatch state v{version} too new")
            n_docs, d_saved, e_cap = r.varint(), r.varint(), r.varint()
            auto_grow = (r.u8() == 1) if version >= 2 else False
        except (IndexError, ValueError) as e:
            raise DecodeError(
                f"DeviceMovableBatch state: malformed meta ({e})"
            ) from None
        _state_sane_sizes("DeviceMovableBatch", d_saved, elem_capacity=e_cap)
        if not 0 < n_docs <= d_saved:
            raise DecodeError("DeviceMovableBatch state: implausible n_docs")
        _peers, cids = _state_read_dicts(dicts_b)
        seq = DeviceDocBatch.import_state(seq_b, mesh=mesh)
        batch = cls.__new__(cls)
        batch.seq = seq
        batch.mesh = seq.mesh
        batch.n_docs = n_docs
        batch.d = seq.d
        batch.e_cap = e_cap
        batch.auto_grow = auto_grow  # review r5: __new__ skips __init__
        batch._defer_moves = batch._defer_vals = None
        batch._dev_lock = named_rlock("fleet.dev")
        batch.elem_ids = [dict() for _ in range(batch.d)]
        batch.values = [[] for _ in range(batch.d)]
        sh = doc_sharding(batch.mesh)
        lim = min(batch.d, d_saved)
        for name in ("moves", "vals"):
            blob = kv.get(name.encode())
            if blob is None:
                raise DecodeError(f"DeviceMovableBatch state: missing {name}")
            grids = _state_read_grid(
                blob,
                [
                    ((d_saved, e_cap), dt)
                    for dt in (np.int32, np.uint32, np.uint32, np.int32)
                ],
            )
            from ..ops.lww import NEG

            _f = _lww_fills(0 if name == "moves" else -2)
            defaults = (_f["lamport"], _f["peer_hi"], _f["peer_lo"], _f["value"])
            host = [
                np.full((batch.d, e_cap), fill, dt)
                for fill, dt in zip(defaults, (np.int32, np.uint32, np.uint32, np.int32))
            ]
            for h, g in zip(host, grids):
                h[:lim] = g[:lim]
            if name == "vals":
                vals_host_value = host[3]
            elif name == "moves":
                # folded slot-row references must stay inside the seq
                # buffer (compact's winner-epoch lookup and the kernel's
                # row gathers index with them)
                folded = host[0] != int(NEG)
                wrow = host[3][folded].astype(np.int64)
                if wrow.size and (wrow.min() < 0 or wrow.max() >= batch.seq.cap):
                    raise DecodeError("DeviceMovableBatch state: winner row")
            setattr(batch, name, LwwResident(*[jax.device_put(h, sh) for h in host]))
        try:
            for di in range(lim):
                elems_b = kv.get(b"doc/%08d/elems" % di)
                if elems_b is not None:
                    r = Reader(elems_b)
                    eids: Dict = {}
                    for _ in range(r.varint()):
                        peer = r.u64le()
                        ctr = r.zigzag()
                        i = r.varint()
                        if i >= e_cap:
                            raise DecodeError("DeviceMovableBatch state: elem ordinal")
                        eids[(peer, ctr)] = i
                    batch.elem_ids[di] = eids
                vals_b = kv.get(b"doc/%08d/values" % di)
                if vals_b is not None:
                    batch.values[di] = _state_read_values(vals_b, cids)
                # folded value ordinals must stay inside the value store
                # (value_lists would IndexError otherwise)
                vv = vals_host_value[di].astype(np.int64)
                vv = vv[vv >= 0]
                if vv.size and vv.max() >= len(batch.values[di]):
                    raise DecodeError("DeviceMovableBatch state: value ordinal")
        except (IndexError, ValueError, struct.error) as e:
            raise DecodeError(
                f"DeviceMovableBatch state: malformed doc ({e})"
            ) from None
        return batch

    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection for the sync pull path."""
        return _batch_export_select(self, "movable", index, requests, sup)

    def value_lists(self) -> List[list]:
        """Materialize every doc's ordered element values (one launch;
        same contract as Fleet.merge_movable_changes per doc)."""
        from ..ops.movable_batch import movable_by_key_batch

        out_idx, counts = movable_by_key_batch(
            self.seq.cols.valid,
            self.seq.cols.deleted,
            self.seq.key_hi,
            self.seq.key_lo,
            self.moves.value,
            self.moves.lamport,
            self.vals.value,
        )
        out_idx = np.asarray(out_idx)
        counts = np.asarray(counts)
        return [
            [self.values[di][j] for j in out_idx[di, : counts[di]]]
            for di in range(self.n_docs)
        ]


# ---- shared checkpoint helpers (fleet-scale checkpoint/resume) --------


def _state_sane_sizes(cls_name: str, d_saved: int, **fields) -> None:
    """Reject implausible size fields BEFORE allocating host/device
    arrays from them — a few flipped meta bytes must produce
    DecodeError, not a multi-GB allocation (checkpoint fuzz contract).
    Bounds are generous (16M per axis, 128M grid entries)."""
    from ..errors import DecodeError

    if not 0 < d_saved <= 1 << 20:
        raise DecodeError(f"{cls_name} state: implausible doc width {d_saved}")
    for name, v in fields.items():
        if not 0 < v <= 1 << 24:
            raise DecodeError(f"{cls_name} state: implausible {name} {v}")
        if d_saved * v > 1 << 27:
            raise DecodeError(
                f"{cls_name} state: implausible grid {d_saved}x{v} ({name})"
            )


def _state_dicts_blob(d) -> bytes:
    """Serialize the peer/cid dictionaries (cid peers pre-registered —
    the encode_changes guard)."""
    from ..codec.binary import Writer, _write_cid

    for c in d.cids:
        if not c.is_root:
            d.peer(c.peer)
    w = Writer()
    w.varint(len(d.peers))
    for p in d.peers:
        w.u64le(p)
    w.varint(len(d.cids))
    for c in d.cids:
        _write_cid(w, d, c)
    return bytes(w.buf)


def _state_read_dicts(blob: bytes):
    from ..codec.binary import Reader, _read_cid
    from ..errors import DecodeError

    try:
        r = Reader(blob)
        peers = [r.u64le() for _ in range(r.varint())]
        cids: List[ContainerID] = []
        for _ in range(r.varint()):
            cids.append(_read_cid(r, peers))
        return peers, cids
    except (IndexError, ValueError, struct.error) as e:
        raise DecodeError(f"resident state: malformed dicts ({e})") from None


def _state_write_values(w, d, values) -> None:
    from ..codec.binary import _write_value

    w.varint(len(values))
    for i, v in enumerate(values):
        if isinstance(v, _LazyValue):
            v = v.decode()
            values[i] = v  # cache: repeat exports stay O(new values)
        _write_value(w, d, v)


def _state_read_values(blob: bytes, cids) -> list:
    from ..codec.binary import Reader, _read_value
    from ..errors import DecodeError

    try:
        r = Reader(blob)
        return [_read_value(r, cids) for _ in range(r.varint())]
    except (IndexError, ValueError, struct.error, UnicodeDecodeError) as e:
        raise DecodeError(f"resident state: malformed values ({e})") from None


def _state_write_grid(kv, key: bytes, arrays) -> None:
    """One [D, S] array set as raw little-endian buffers."""
    from ..codec.binary import Writer

    w = Writer()
    for a in arrays:
        w.bytes_(np.asarray(a).tobytes())
    kv.set(key, bytes(w.buf))


def _state_read_grid(blob: bytes, shapes_dtypes):
    from ..codec.binary import Reader
    from ..errors import DecodeError

    try:
        r = Reader(blob)
        out = []
        for shape, dt in shapes_dtypes:
            buf = np.frombuffer(r.bytes_(), dt)
            if buf.size != int(np.prod(shape)):
                raise DecodeError("resident state: grid size mismatch")
            out.append(buf.reshape(shape).copy())
        return out
    except (IndexError, ValueError) as e:
        raise DecodeError(f"resident state: malformed grid ({e})") from None


class DeviceCounterBatch:
    """Device-resident counter sums for a doc batch (increments are
    commutative, so the resident state IS the fold — one donated
    scatter-add per append, the cheapest member of the resident
    family).

    Precision contract: device sums are float32 (x64 is disabled on the
    TPU path; same contract as the one-shot merge_counter_changes), so
    values match the host's f64 CounterState exactly for integer-valued
    deltas up to 2^24 and to f32 rounding otherwise."""

    def __init__(self, n_docs: int, slot_capacity: int, mesh=None,
                 auto_grow: bool = False):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_docs = n_docs
        self.d = _mesh_pad(self.mesh, n_docs)
        self.s = slot_capacity
        self.auto_grow = auto_grow
        self.slot_of: List[Dict[ContainerID, int]] = [dict() for _ in range(self.d)]
        self.sums = jax.device_put(
            np.zeros((self.d, self.s), np.float32), doc_sharding(self.mesh)
        )
        # ingest-epoch clock (parity with the seq/tree batches — the
        # server journals rounds against it; folds never compact)
        self.epoch = 0
        self._defer = None  # coalesced-ingest accumulator
        self._dev_lock = named_rlock("fleet.dev")

    # -- round coalescing (float add is associative for the documented
    # integer-delta precision contract; epoch still bumps per round) ---
    def begin_coalesce(self) -> None:
        if self._defer is not None:
            raise RuntimeError("coalesce group already open")
        self._defer = _DeferredFold(self.d)

    def detach_coalesce(self):
        d, self._defer = self._defer, None
        return d

    def commit_detached(self, d) -> None:
        if d is None or not any(d.rows):
            return
        self._device_fold(d.rows)
        obs.counter("pipeline.coalesced_rounds_total").inc(
            d.n_rounds, family="counter"
        )

    def flush_coalesce(self) -> None:
        self.commit_detached(self.detach_coalesce())

    def grow(self, new_slot_capacity: int) -> None:
        """Repack counter sums to a larger slot capacity (resident
        lifecycle, r4 verdict #6)."""
        if new_slot_capacity <= self.s:
            return
        with self._dev_lock:  # vs an in-flight pipelined commit
            self.sums = _pad_axis1(
                {"sums": self.sums}, new_slot_capacity, {"sums": 0.0},
                doc_sharding(self.mesh),
            )["sums"]
            self.s = new_slot_capacity

    def release_doc(self, di: int) -> None:
        """Reset doc ``di`` to a never-used slot (tiered-residency
        eviction; see DeviceDocBatch.release_doc for the contract)."""
        self.slot_of[di] = {}
        with self._dev_lock:
            (self.sums,) = _release_rows(
                (self.sums,), jnp.int32(di), (0.0,)
            )
        obs.counter("fleet.doc_releases_total").inc(family="counter")

    def append_changes(self, per_doc_changes: Sequence[Optional[Sequence[Change]]]) -> None:
        from ..core.change import CounterIncr
        from ..ops.fugue_batch import pad_bucket

        per_doc_changes = list(per_doc_changes) + [None] * (self.d - len(per_doc_changes))
        rows_per_doc: List[list] = []
        staged_slots: List[list] = []
        for di, changes in enumerate(per_doc_changes):
            rows: list = []
            staged: Dict = {}
            order: list = []
            rows_per_doc.append(rows)
            staged_slots.append(order)
            if not changes:
                continue
            slots = self.slot_of[di]

            def slot_idx(cid):
                i = slots.get(cid)
                if i is None:
                    i = staged.get(cid)
                if i is None:
                    i = len(slots) + len(order)
                    staged[cid] = i
                    order.append(cid)
                return i

            for ch in changes:
                for op in ch.ops:
                    if isinstance(op.content, CounterIncr):
                        rows.append((slot_idx(op.container), float(op.content.delta)))
        req = max(
            (len(self.slot_of[di]) + len(staged_slots[di]) for di in range(self.d)),
            default=0,
        )
        if req > self.s:
            if self.auto_grow:
                self.grow(_grow_target(req, self.s))
            else:
                raise RuntimeError(
                    f"DeviceCounterBatch slot capacity exceeded: a doc needs "
                    f"{req} slots > {self.s}"
                )
        self.epoch += 1  # post-validation: dates this append (journal clock)
        if not any(rows_per_doc):
            return
        for di, order in enumerate(staged_slots):
            for cid in order:
                self.slot_of[di][cid] = len(self.slot_of[di])
        if self._defer is not None:
            self._defer.extend(rows_per_doc)
            return
        self._device_fold(rows_per_doc)

    def _device_fold(self, rows_per_doc) -> None:
        from ..ops.fugue_batch import pad_bucket

        obs.counter("fleet.device_launches_total").inc(family="resident_counter")
        with self._dev_lock:
            m = pad_bucket(max(len(r) for r in rows_per_doc), floor=16)
            slot = np.full((self.d, m), self.s, np.int32)  # dump slot
            delta = np.zeros((self.d, m), np.float32)
            for di, rows in enumerate(rows_per_doc):
                for i, (s_, dl) in enumerate(rows):
                    slot[di, i] = s_
                    delta[di, i] = dl
            sh = doc_sharding(self.mesh)
            self.sums = _fold_counter_rows(
                self.sums, jax.device_put(slot, sh), jax.device_put(delta, sh)
            )

    def export_select(self, index, requests, sup=None):
        """Batched read-plane selection for the sync pull path (the
        counter fold keeps no per-op rows — the change-span index is
        the only delta history, same as map)."""
        return _batch_export_select(self, "counter", index, requests, sup)

    def value_maps(self) -> List[Dict[ContainerID, float]]:
        sums = np.asarray(self.sums)
        return [
            {cid: float(sums[di, s_]) for cid, s_ in self.slot_of[di].items()}
            for di in range(self.n_docs)
        ]

    # -- checkpoint/resume --------------------------------------------
    STATE_VERSION = 3  # v3: + ingest epoch clock

    def export_state(self) -> bytes:
        from ..codec.binary import Writer, _Dicts
        from ..storage import MemKvStore

        kv = MemKvStore()
        d = _Dicts()
        meta = Writer()
        meta.u8(self.STATE_VERSION)
        meta.varint(self.n_docs)
        meta.varint(self.d)
        meta.varint(self.s)
        meta.u8(1 if self.auto_grow else 0)  # v2
        meta.varint(self.epoch)  # v3
        kv.set(b"meta", bytes(meta.buf))
        _state_write_grid(kv, b"sums", [np.asarray(self.sums)])
        for di in range(self.d):
            w = Writer()
            w.varint(len(self.slot_of[di]))
            for cid, s_ in self.slot_of[di].items():
                w.varint(d.cid(cid))
                w.varint(s_)
            kv.set(b"doc/%08d/slots" % di, bytes(w.buf))
        kv.set(b"dicts", _state_dicts_blob(d))
        return kv.export_all()

    @classmethod
    def import_state(cls, data: bytes, mesh=None) -> "DeviceCounterBatch":
        from ..codec.binary import Reader
        from ..errors import DecodeError
        from ..storage import MemKvStore

        kv = MemKvStore()
        kv.import_all(data)
        meta_b, dicts_b = kv.get(b"meta"), kv.get(b"dicts")
        if meta_b is None or dicts_b is None:
            raise DecodeError("DeviceCounterBatch state: missing meta/dicts")
        try:
            r = Reader(meta_b)
            version = r.u8()
            if version > cls.STATE_VERSION:
                raise DecodeError(f"DeviceCounterBatch state v{version} too new")
            n_docs, d_saved, s = r.varint(), r.varint(), r.varint()
            auto_grow = (r.u8() == 1) if version >= 2 else False
            epoch = r.varint() if version >= 3 else 0
        except (IndexError, ValueError) as e:
            raise DecodeError(f"DeviceCounterBatch state: malformed meta ({e})") from None
        _state_sane_sizes("DeviceCounterBatch", d_saved, slot_capacity=s)
        if not 0 < n_docs <= d_saved:
            raise DecodeError("DeviceCounterBatch state: implausible n_docs")
        _peers, cids = _state_read_dicts(dicts_b)
        batch = cls(n_docs, s, mesh=mesh, auto_grow=auto_grow)
        batch.epoch = epoch
        sums_b = kv.get(b"sums")
        if sums_b is None:
            raise DecodeError("DeviceCounterBatch state: missing sums")
        (grid,) = _state_read_grid(sums_b, [((d_saved, s), np.float32)])
        host = np.asarray(batch.sums).copy()
        lim = min(batch.d, d_saved)
        host[:lim] = grid[:lim]
        batch.sums = jax.device_put(host, doc_sharding(batch.mesh))
        for di in range(lim):
            slots_b = kv.get(b"doc/%08d/slots" % di)
            if slots_b is not None:
                try:
                    r = Reader(slots_b)
                    so: Dict[ContainerID, int] = {}
                    for _ in range(r.varint()):
                        ci = r.varint()
                        if ci >= len(cids):
                            raise DecodeError("DeviceCounterBatch state: cid index")
                        s_ = r.varint()
                        if s_ >= s:
                            raise DecodeError("DeviceCounterBatch state: slot index")
                        so[cids[ci]] = s_
                    batch.slot_of[di] = so
                except (IndexError, ValueError) as e:
                    raise DecodeError(
                        f"DeviceCounterBatch state: malformed slots ({e})"
                    ) from None
        return batch


@functools.partial(jax.jit, donate_argnums=(0,))
def _fold_counter_rows(sums, slot, delta):
    from ..ops.lww import counter_merge_doc

    def per_doc(acc, s_, dl):
        # one canonical counter-sum kernel (rows with slot >= S are the
        # padding the dump slot swallows)
        return acc + counter_merge_doc(s_, dl, s_ < acc.shape[0], acc.shape[0])

    return jax.vmap(per_doc)(sums, slot, delta)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_tree_rows(cols, blk, offsets):
    """Tree-log variant of _scatter_rows (shared window semantics via
    _windowed_scatter_field)."""
    out = {
        f: jax.vmap(_windowed_scatter_field)(
            getattr(cols, f), blk[f], blk["valid"], offsets
        )
        for f in cols._fields
    }
    return type(cols)(**out)


@functools.lru_cache(maxsize=32)
def _lww_sharded_fn(mesh, n_slots: int):
    from ..ops.lww import make_lww_sharded

    return make_lww_sharded(mesh, n_slots)


@functools.lru_cache(maxsize=32)
def _lww_batch_fn(mesh, n_slots: int):
    in_sh = NamedSharding(mesh, P(DOC_AXIS))

    @functools.partial(jax.jit, in_shardings=(MapOpCols(*([in_sh] * 5)),))
    def run(cols: MapOpCols):
        return jax.vmap(lambda c: lww_merge_doc(c, n_slots))(cols)

    return run
