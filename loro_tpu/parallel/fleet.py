"""Fleet merge engine: reconcile batches of documents in one XLA launch.

The north-star path (BASELINE.json): a server holds thousands of docs;
incoming update blobs are decoded host-side into columnar element
tables (ops/columnar.py), the doc axis is sharded over the device mesh,
and one jit launch resolves every document's final sequence order /
LWW winners.  This replaces the reference's per-doc sequential
`OpLog::import -> DiffCalculator` replay (loro.rs:568 -> diff_calc.rs)
with data-parallel kernels.

Shapes are bucket-padded (pad_bucket) so the jit cache stays small
across varying doc sizes.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.change import Change
from ..core.ids import ContainerID
from ..utils import tracing
from ..ops.columnar import MapExtract, SeqExtract, extract_map_ops, extract_seq_container, pad_rows
from ..ops.fugue_batch import SeqColumns, materialize_content_batch, pad_bucket
from ..ops.lww import MapOpCols, lww_merge_doc
from .mesh import DOC_AXIS, doc_sharding, make_mesh, replicated


@dataclass
class TextMergeResult:
    texts: List[str]


class Fleet:
    """Batched merge front-end bound to a device mesh."""

    def __init__(self, mesh=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self._text_fn = None

    # ------------------------------------------------------------------
    # text / list sequence merge
    # ------------------------------------------------------------------
    def _build_text_fn(self):
        mesh = self.mesh
        in_sh = NamedSharding(mesh, P(DOC_AXIS))
        out_sh = NamedSharding(mesh, P(DOC_AXIS))

        @functools.partial(
            jax.jit,
            in_shardings=(SeqColumns(*([in_sh] * 7)),),
            out_shardings=(out_sh, out_sh),
        )
        def run(cols: SeqColumns):
            return materialize_content_batch(cols)

        return run

    def merge_text_docs(
        self, extracts: Sequence[SeqExtract], pad_docs: Optional[int] = None
    ) -> TextMergeResult:
        """Resolve final text for a batch of documents (one launch).
        Documents are padded to a common bucketed element count and the
        doc axis is padded to a multiple of the mesh's doc dimension."""
        if self._text_fn is None:
            self._text_fn = self._build_text_fn()
        tracing.instant("fleet.merge_text_docs", docs=len(extracts))
        n = pad_bucket(max(e.n for e in extracts))
        d_mesh = self.mesh.shape[DOC_AXIS]
        d = len(extracts)
        d_pad = pad_docs or ((d + d_mesh - 1) // d_mesh) * d_mesh
        cols_np = [e.to_seq_columns(pad_to=n) for e in extracts]
        empty = SeqColumns(
            parent=np.full(n, -1, np.int32),
            side=np.zeros(n, np.int32),
            peer=np.zeros(n, np.int32),
            counter=np.zeros(n, np.int32),
            deleted=np.ones(n, bool),
            content=np.full(n, -1, np.int32),
            valid=np.zeros(n, bool),
        )
        cols_np += [empty] * (d_pad - d)
        batched = SeqColumns(
            *[np.stack([getattr(c, f) for c in cols_np]) for f in SeqColumns._fields]
        )
        sh = doc_sharding(self.mesh)
        batched = SeqColumns(*[jax.device_put(a, sh) for a in batched])
        codes, counts = self._text_fn(batched)
        codes = np.asarray(codes)
        counts = np.asarray(counts)
        texts = [
            "".join(map(chr, codes[i, : counts[i]])) for i in range(d)
        ]
        return TextMergeResult(texts)

    def merge_text_changes(
        self, docs_changes: Sequence[Sequence[Change]], cid: ContainerID
    ) -> TextMergeResult:
        """Convenience: decode + merge each doc's change list."""
        extracts = [extract_seq_container(chs, cid) for chs in docs_changes]
        return self.merge_text_docs(extracts)

    def merge_text_payloads(
        self, payloads: Sequence[bytes], cid: ContainerID
    ) -> TextMergeResult:
        """Full ingest pipeline: binary update payloads -> native C++
        wire->SoA decode -> one sharded device launch.  This is the
        server-side bulk-sync path the north star describes: the decode
        stage never materializes Python op objects.

        Payloads are envelope-stripped bytes; integrity (CRC) is the
        envelope layer's job (LoroDoc._parse_envelope) — a corrupted
        payload here decodes to garbage-but-safe output, never a crash.
        """
        from ..codec.binary import decode_changes
        from ..ops.columnar import extract_seq_from_payload

        extracts = []
        for p in payloads:
            try:
                ex = extract_seq_from_payload(p, cid)
            except ValueError:
                # native path can't resolve (e.g. incremental payload
                # referencing elements outside it): python fallback
                ex = None
            if ex is None:
                ex = extract_seq_container(decode_changes(p), cid)
            extracts.append(ex)
        return self.merge_text_docs(extracts)

    # ------------------------------------------------------------------
    # LWW map merge
    # ------------------------------------------------------------------
    def merge_map_docs(self, extracts: Sequence[MapExtract]) -> List[Dict[str, object]]:
        """Resolve LWW winners for a batch of docs; returns per-doc
        {key: value} for root map containers."""
        m = pad_bucket(max(1, max(len(e.slot) for e in extracts)))
        s = max(1, max(len(e.slots) for e in extracts))
        d = len(extracts)
        d_mesh = self.mesh.shape[DOC_AXIS]
        d_pad = ((d + d_mesh - 1) // d_mesh) * d_mesh

        def col(rows_list, fill, dtype):
            out = np.full((d_pad, m), fill, dtype)
            for i, r in enumerate(rows_list):
                out[i, : len(r)] = r
            return out

        batched = MapOpCols(
            slot=col([e.slot for e in extracts], 0, np.int32),
            lamport=col([e.lamport for e in extracts], 0, np.int32),
            peer=col([e.peer for e in extracts], 0, np.int32),
            value_idx=col([e.value_idx for e in extracts], 0, np.int32),
            valid=col([e.valid for e in extracts], False, bool),
        )
        sh = doc_sharding(self.mesh)
        batched = MapOpCols(*[jax.device_put(np.asarray(a), sh) for a in batched])
        fn = _lww_batch_fn(self.mesh, s)
        vi, _, _ = fn(batched)
        vi = np.asarray(vi)
        out: List[Dict[str, object]] = []
        for i, e in enumerate(extracts):
            got: Dict[str, object] = {}
            for si, (cid, key) in enumerate(e.slots):
                idx = int(vi[i, si])
                if idx >= 0:
                    got[key] = e.values[idx]
            out.append(got)
        return out


@functools.lru_cache(maxsize=32)
def _lww_batch_fn(mesh, n_slots: int):
    in_sh = NamedSharding(mesh, P(DOC_AXIS))

    @functools.partial(jax.jit, in_shardings=(MapOpCols(*([in_sh] * 5)),))
    def run(cols: MapOpCols):
        return jax.vmap(lambda c: lww_merge_doc(c, n_slots))(cols)

    return run
