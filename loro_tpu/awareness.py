"""Presence: Awareness + EphemeralStore.

reference: crates/loro-internal/src/awareness.rs — non-persistent
peer-presence state outside the CRDT history: `Awareness` maps peer ->
(state value, counter, timestamp); `EphemeralStore` is a key->value LWW
store by wall-clock timestamp with inactivity expiry and its own little
wire format + local/remote subscriptions.
"""
from __future__ import annotations

import json
import struct
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .core.ids import PeerID


@dataclass
class PeerInfo:
    state: Any
    counter: int
    timestamp: float


class Awareness:
    """``clock`` is injectable (fake-clock tests drive TTL expiry the
    way DeviceSupervisor retry tests do); the produced wall-clock
    timestamps are presence metadata, never CRDT history."""

    def __init__(self, peer: PeerID, timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.time):
        self.peer = peer
        self.timeout_s = timeout_s
        self.clock = clock
        self.peers: Dict[PeerID, PeerInfo] = {}

    def set_local_state(self, state: Any) -> None:
        cur = self.peers.get(self.peer)
        counter = (cur.counter + 1) if cur else 1
        self.peers[self.peer] = PeerInfo(state, counter, self.clock())

    def get_local_state(self) -> Any:
        info = self.peers.get(self.peer)
        return info.state if info else None

    def encode(self, peers: Optional[List[PeerID]] = None) -> bytes:
        """Compact binary presence blob: magic 'LTAW' + varint count +
        per entry (u64 peer, varint counter, len-prefixed json state)."""
        from .codec.binary import Writer

        w = Writer()
        w.buf += b"LTAW"
        entries = [
            (p, info)
            for p, info in self.peers.items()
            if peers is None or p in peers
        ]
        w.varint(len(entries))
        for p, info in entries:
            w.u64le(p)
            w.varint(info.counter)
            w.bytes_(json.dumps(info.state).encode())
        return bytes(w.buf)

    def encode_all(self) -> bytes:
        return self.encode()

    def apply(self, data: bytes) -> Tuple[List[PeerID], List[PeerID]]:
        """Returns (updated peers, added peers).  Raises ValueError on
        malformed blobs."""
        from .codec.binary import Reader

        if data[:4] != b"LTAW":
            raise ValueError("bad awareness blob")
        try:
            r = Reader(data[4:])
            entries = []
            for _ in range(r.varint()):
                p = r.u64le()
                counter = r.varint()
                state = json.loads(r.bytes_().decode())
                entries.append((p, counter, state))
        except (IndexError, ValueError, struct.error) as e:
            raise ValueError(f"malformed awareness blob: {e}") from e
        updated, added = [], []
        now = self.clock()
        for p, counter, state in entries:
            cur = self.peers.get(p)
            if cur is None:
                self.peers[p] = PeerInfo(state, counter, now)
                added.append(p)
            elif counter > cur.counter:
                self.peers[p] = PeerInfo(state, counter, now)
                updated.append(p)
        return updated, added

    def remove_outdated(self) -> List[PeerID]:
        now = self.clock()
        dead = [p for p, i in self.peers.items() if now - i.timestamp > self.timeout_s]
        for p in dead:
            del self.peers[p]
        return dead

    def get_all_states(self) -> Dict[PeerID, Any]:
        return {p: i.state for p, i in self.peers.items()}


@dataclass
class _Entry:
    value: Any
    timestamp: float
    deleted: bool = False


class EphemeralStore:
    """key -> LWW-by-timestamp value with inactivity expiry.
    reference: awareness.rs:250+ EphemeralStore."""

    def __init__(self, timeout_ms: int = 30_000,
                 clock: Callable[[], float] = time.time):
        self.timeout_ms = timeout_ms
        self.clock = clock  # injectable (fake-clock expiry tests)
        self._data: Dict[str, _Entry] = {}
        self._local_subs: List[Callable[[bytes], None]] = []
        self._subs: List[Callable[[dict], None]] = []

    # -- local mutation -----------------------------------------------
    def set(self, key: str, value: Any) -> None:
        self._data[key] = _Entry(value, self.clock() * 1000)
        self._emit_local([key])
        self._emit({"by": "local", "added": [], "updated": [key], "removed": []})

    def delete(self, key: str) -> None:
        if key in self._data:
            self._data[key] = _Entry(None, self.clock() * 1000, deleted=True)
            self._emit_local([key])
            self._emit({"by": "local", "added": [], "updated": [], "removed": [key]})

    def get(self, key: str) -> Any:
        e = self._data.get(key)
        return None if e is None or e.deleted else e.value

    def keys(self) -> List[str]:
        self.remove_outdated()
        return sorted(k for k, e in self._data.items() if not e.deleted)

    def get_all_states(self) -> Dict[str, Any]:
        self.remove_outdated()
        return {k: e.value for k, e in self._data.items() if not e.deleted}

    # -- wire ---------------------------------------------------------
    def encode(self, key: Optional[str] = None) -> bytes:
        """Compact binary: magic 'LTEP' + varint count + per entry
        (len-prefixed key, f64 timestamp, u8 deleted, json value)."""
        from .codec.binary import Writer

        w = Writer()
        w.buf += b"LTEP"
        items = [
            (k, e) for k, e in self._data.items() if key is None or k == key
        ]
        w.varint(len(items))
        for k, e in items:
            w.str_(k)
            w.f64(e.timestamp)
            w.u8(1 if e.deleted else 0)
            w.bytes_(json.dumps(e.value).encode())
        return bytes(w.buf)

    def encode_all(self) -> bytes:
        return self.encode()

    def apply(self, data: bytes) -> None:
        from .codec.binary import Reader

        if data[:4] != b"LTEP":
            raise ValueError("bad ephemeral blob")
        try:
            r = Reader(data[4:])
            decoded = []
            for _ in range(r.varint()):
                k = r.str_()
                t = r.f64()
                d = bool(r.u8())
                v = json.loads(r.bytes_().decode())
                decoded.append({"k": k, "v": v, "t": t, "d": d})
        except (IndexError, ValueError, struct.error) as e:
            raise ValueError(f"malformed ephemeral blob: {e}") from e
        added, updated, removed = [], [], []
        for it in decoded:
            k = it["k"]
            cur = self._data.get(k)
            if cur is None or it["t"] > cur.timestamp:
                existed = cur is not None and not cur.deleted
                self._data[k] = _Entry(it["v"], it["t"], it.get("d", False))
                if it.get("d", False):
                    if existed:
                        removed.append(k)
                elif existed:
                    updated.append(k)
                else:
                    added.append(k)
        if added or updated or removed:
            self._emit({"by": "import", "added": added, "updated": updated, "removed": removed})

    def remove_outdated(self) -> List[str]:
        now = self.clock() * 1000
        dead = [k for k, e in self._data.items() if now - e.timestamp > self.timeout_ms]
        removed = []
        for k in dead:
            if not self._data[k].deleted:
                removed.append(k)
            del self._data[k]
        if removed:
            self._emit({"by": "timeout", "added": [], "updated": [], "removed": removed})
        return removed

    # -- subscriptions ------------------------------------------------
    def subscribe_local_update(self, cb: Callable[[bytes], None]) -> Callable[[], None]:
        self._local_subs.append(cb)
        return lambda: self._local_subs.remove(cb)

    def subscribe(self, cb: Callable[[dict], None]) -> Callable[[], None]:
        self._subs.append(cb)
        return lambda: self._subs.remove(cb)

    def _emit_local(self, keys: List[str]) -> None:
        if self._local_subs:
            from .codec.binary import Writer

            w = Writer()
            w.buf += b"LTEP"
            w.varint(len(keys))
            for k in keys:
                e = self._data[k]
                w.str_(k)
                w.f64(e.timestamp)
                w.u8(1 if e.deleted else 0)
                w.bytes_(json.dumps(e.value).encode())
            payload = bytes(w.buf)
            for cb in self._local_subs:
                cb(payload)

    def _emit(self, ev: dict) -> None:
        for cb in list(self._subs):
            cb(ev)
