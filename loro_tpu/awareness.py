"""Presence: Awareness + EphemeralStore.

reference: crates/loro-internal/src/awareness.rs — non-persistent
peer-presence state outside the CRDT history: `Awareness` maps peer ->
(state value, counter, timestamp); `EphemeralStore` is a key->value LWW
store by wall-clock timestamp with inactivity expiry and its own little
wire format + local/remote subscriptions.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .core.ids import PeerID


@dataclass
class PeerInfo:
    state: Any
    counter: int
    timestamp: float


class Awareness:
    def __init__(self, peer: PeerID, timeout_s: float = 30.0):
        self.peer = peer
        self.timeout_s = timeout_s
        self.peers: Dict[PeerID, PeerInfo] = {}

    def set_local_state(self, state: Any) -> None:
        cur = self.peers.get(self.peer)
        counter = (cur.counter + 1) if cur else 1
        self.peers[self.peer] = PeerInfo(state, counter, time.time())

    def get_local_state(self) -> Any:
        info = self.peers.get(self.peer)
        return info.state if info else None

    def encode(self, peers: Optional[List[PeerID]] = None) -> bytes:
        now = time.time()
        out = []
        for p, info in self.peers.items():
            if peers is not None and p not in peers:
                continue
            out.append({"peer": str(p), "state": info.state, "counter": info.counter})
        return json.dumps(out).encode()

    def encode_all(self) -> bytes:
        return self.encode()

    def apply(self, data: bytes) -> Tuple[List[PeerID], List[PeerID]]:
        """Returns (updated peers, added peers)."""
        updated, added = [], []
        now = time.time()
        for entry in json.loads(data.decode()):
            p = int(entry["peer"])
            counter = entry["counter"]
            cur = self.peers.get(p)
            if cur is None:
                self.peers[p] = PeerInfo(entry["state"], counter, now)
                added.append(p)
            elif counter > cur.counter:
                self.peers[p] = PeerInfo(entry["state"], counter, now)
                updated.append(p)
        return updated, added

    def remove_outdated(self) -> List[PeerID]:
        now = time.time()
        dead = [p for p, i in self.peers.items() if now - i.timestamp > self.timeout_s]
        for p in dead:
            del self.peers[p]
        return dead

    def get_all_states(self) -> Dict[PeerID, Any]:
        return {p: i.state for p, i in self.peers.items()}


@dataclass
class _Entry:
    value: Any
    timestamp: float
    deleted: bool = False


class EphemeralStore:
    """key -> LWW-by-timestamp value with inactivity expiry.
    reference: awareness.rs:250+ EphemeralStore."""

    def __init__(self, timeout_ms: int = 30_000):
        self.timeout_ms = timeout_ms
        self._data: Dict[str, _Entry] = {}
        self._local_subs: List[Callable[[bytes], None]] = []
        self._subs: List[Callable[[dict], None]] = []

    # -- local mutation -----------------------------------------------
    def set(self, key: str, value: Any) -> None:
        self._data[key] = _Entry(value, time.time() * 1000)
        self._emit_local([key])
        self._emit({"by": "local", "added": [], "updated": [key], "removed": []})

    def delete(self, key: str) -> None:
        if key in self._data:
            self._data[key] = _Entry(None, time.time() * 1000, deleted=True)
            self._emit_local([key])
            self._emit({"by": "local", "added": [], "updated": [], "removed": [key]})

    def get(self, key: str) -> Any:
        e = self._data.get(key)
        return None if e is None or e.deleted else e.value

    def keys(self) -> List[str]:
        self.remove_outdated()
        return sorted(k for k, e in self._data.items() if not e.deleted)

    def get_all_states(self) -> Dict[str, Any]:
        self.remove_outdated()
        return {k: e.value for k, e in self._data.items() if not e.deleted}

    # -- wire ---------------------------------------------------------
    def encode(self, key: Optional[str] = None) -> bytes:
        items = []
        for k, e in self._data.items():
            if key is not None and k != key:
                continue
            items.append({"k": k, "v": e.value, "t": e.timestamp, "d": e.deleted})
        return json.dumps(items).encode()

    def encode_all(self) -> bytes:
        return self.encode()

    def apply(self, data: bytes) -> None:
        added, updated, removed = [], [], []
        for it in json.loads(data.decode()):
            k = it["k"]
            cur = self._data.get(k)
            if cur is None or it["t"] > cur.timestamp:
                existed = cur is not None and not cur.deleted
                self._data[k] = _Entry(it["v"], it["t"], it.get("d", False))
                if it.get("d", False):
                    if existed:
                        removed.append(k)
                elif existed:
                    updated.append(k)
                else:
                    added.append(k)
        if added or updated or removed:
            self._emit({"by": "import", "added": added, "updated": updated, "removed": removed})

    def remove_outdated(self) -> List[str]:
        now = time.time() * 1000
        dead = [k for k, e in self._data.items() if now - e.timestamp > self.timeout_ms]
        removed = []
        for k in dead:
            if not self._data[k].deleted:
                removed.append(k)
            del self._data[k]
        if removed:
            self._emit({"by": "timeout", "added": [], "updated": [], "removed": removed})
        return removed

    # -- subscriptions ------------------------------------------------
    def subscribe_local_update(self, cb: Callable[[bytes], None]) -> Callable[[], None]:
        self._local_subs.append(cb)
        return lambda: self._local_subs.remove(cb)

    def subscribe(self, cb: Callable[[dict], None]) -> Callable[[], None]:
        self._subs.append(cb)
        return lambda: self._subs.remove(cb)

    def _emit_local(self, keys: List[str]) -> None:
        if self._local_subs:
            payload = json.dumps(
                [
                    {"k": k, "v": self._data[k].value, "t": self._data[k].timestamp, "d": self._data[k].deleted}
                    for k in keys
                ]
            ).encode()
            for cb in self._local_subs:
                cb(payload)

    def _emit(self, ev: dict) -> None:
        for cb in list(self._subs):
            cb(ev)
