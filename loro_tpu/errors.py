"""Framework exception types (importable without doc.py's import graph)."""


class LoroError(Exception):
    pass


class DecodeError(LoroError):
    pass
