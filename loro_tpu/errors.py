"""Framework exception types (importable without doc.py's import graph)."""


class LoroError(Exception):
    pass


class DecodeError(LoroError):
    pass


class CodecDecodeError(DecodeError, ValueError):
    """Truncated / bit-flipped / otherwise malformed wire bytes.

    Subclasses ValueError on purpose: every ingest path that falls back
    to the Python decoder on `except ValueError` (fleet payload extract,
    resident append_payloads) keeps working unchanged, while callers
    that want the typed contract can catch CodecDecodeError (or
    DecodeError) specifically.
    """


class ConfigError(LoroError, ValueError):
    """Invalid tuning-knob environment value (RANK_ALGO, PALLAS_RANK_ALGO,
    PLACE_ALGO, PALLAS_RULING_K, RANK_BLOCK, ...), raised at FIRST USE
    (trace time) with the accepted values/range spelled out — never a
    silent fall-back to the default algorithm.

    Subclasses ValueError so pre-existing ``except ValueError`` guards
    (and tests) keep working.
    """

    def __init__(self, knob: str, got: object, accepted: str):
        self.knob = knob
        self.got = got
        self.accepted = accepted
        super().__init__(f"{knob}={got!r} invalid: accepted {accepted}")


class PersistError(LoroError):
    """Durability-layer failure (loro_tpu/persist/): a WAL append or
    checkpoint write did not reach disk, or a durable directory is in a
    state the requested operation cannot honor (e.g. opening an
    existing log as a fresh server).  Corrupt *reads* raise DecodeError
    subclasses instead — this type is for the write/lifecycle side."""


class SyncError(LoroError):
    """Base for the sync front-end (loro_tpu/sync/, docs/SYNC.md)."""


class PushRejected(SyncError):
    """A pushed update payload did not decode (poison): the push's
    ticket fails typed with this, other sessions' pushes in the same
    fan-in batch land normally.  The client should re-export and retry;
    the server state never half-applied the payload."""


class StaleFrontier(SyncError):
    """The client's frontier is below the server oracle's shallow root
    (history there was trimmed by the checkpoint ladder) AND the client
    is not empty, so neither a delta nor a snapshot can be served — the
    client must resync from scratch (fresh doc, then ``pull()`` takes
    the first-sync snapshot path)."""


class SessionClosed(SyncError):
    """Operation on a session that was closed or TTL-expired."""


class NetError(LoroError):
    """Base for the network edge (loro_tpu/net/, docs/NET.md): frame-
    layer violations (oversized frames, send-queue overflow, a closed
    or refused connection) and client-side transport failures.  A
    NetError fails exactly ONE connection — the accept loop and every
    other live session keep serving.  Truncated / bit-flipped frame
    *bytes* raise ``CodecDecodeError`` (the codec-harden contract);
    sync-layer outcomes crossing the wire re-raise their own types
    (``PushRejected``, ``StaleFrontier``, ``NotLeader``, ...)."""


class NetProtocolError(NetError):
    """The peer spoke the wrong protocol: bad HELLO magic, an
    unsupported protocol version, an unknown message type, or a frame
    whose declared length exceeds the negotiated maximum.  The
    connection closes typed; reconnect-with-frontier resume applies."""


class ShardingError(LoroError):
    """Sharded-fleet lifecycle misuse (loro_tpu/parallel/sharded.py,
    docs/SHARDING.md): migrating to a shard with no free slot, moving a
    doc on/off a degraded shard, a shard manifest that does not match
    the durable directories under it.  Invalid shard-count *knob*
    values (LORO_SHARDS, divisibility) raise ConfigError instead."""


class ResidencyError(LoroError):
    """Tiered-residency lifecycle failure (loro_tpu/parallel/residency.py,
    docs/RESIDENCY.md): a round touched more docs than the hot-slot
    budget can hold, no evictable victim exists (every hot doc is still
    un-journaled), or an injected/real failure interrupted an evict or
    revive.  The contract: a failed EVICT leaves the doc hot (no torn
    tier state); a failed REVIVE fails only the triggering round/ticket
    and leaves the doc warm/cold — the server itself stays healthy
    either way.  Passes through DeviceSupervisor untouched (LoroError),
    so it can never be misread as a device failure and trigger
    degradation."""


class ReplicationError(LoroError):
    """Base for WAL-shipping replication (loro_tpu/replication/,
    docs/REPLICATION.md): leader-side shipping, follower apply loops,
    fencing and promotion."""


class NotLeader(ReplicationError):
    """A write (push/ingest) reached a read-only follower.  Carries the
    current leader's identity so clients can redirect instead of
    guessing."""

    def __init__(self, msg: str, leader=None):
        self.leader = leader
        super().__init__(msg + (f" (leader: {leader})" if leader else ""))


class FencedLeader(ReplicationError):
    """A fenced (deposed) leader attempted a WAL append: a follower was
    promoted with a newer leader token, so this process must fail-stop
    — continuing to journal would fork the replicated history.  Raised
    BEFORE any bytes reach the segment (no partial record)."""


class StaleFollower(ReplicationError):
    """The follower's shipped position fell below the leader's WAL
    prune floor (its retention pin was dropped by the staleness
    cutoff, then the history it still needed was deleted).  The
    follower must re-bootstrap from a fresh directory — resuming would
    silently fabricate a truncated history."""


class ReplicaLag(ReplicationError):
    """A ``pull(min_epoch=...)`` read-your-writes gate timed out: the
    replica has not applied the requested epoch yet.  Retry, or pull
    from the leader."""


class ObsError(LoroError):
    """Observability-tooling failure (loro_tpu/obs/): an unreadable or
    malformed trace/flight artifact handed to ``python -m
    loro_tpu.obs.trace``, or a merge over artifacts with no common
    epoch stamps.  Always raised typed so the CLI exits with a legible
    message instead of a stack trace."""


class AnalysisError(LoroError):
    """Base for the static-analysis / invariant-witness subsystem
    (loro_tpu/analysis/, docs/ANALYSIS.md)."""


class LockOrderViolation(AnalysisError):
    """The runtime lock witness observed an acquisition the declared
    partial order in analysis/lockorder.py forbids, or a cycle in the
    witnessed lock graph (a latent deadlock).  Raised only in strict
    witness mode (tests) — production code never enables it."""


class ChaosError(LoroError):
    """Chaos-plane lifecycle misuse (loro_tpu/chaos/, docs/RESILIENCE.md
    "Chaos plane"): a malformed replay artifact, a plan step the runner
    does not understand, or orchestration misuse (resuming a run whose
    journal is missing).  Invalid chaos *knob* values raise ConfigError
    instead; invariant VIOLATIONS are never exceptions — they are data
    (``chaos.invariants.Violation``) so a run can report all of them."""


class ResilienceError(LoroError):
    """Base for the resilience subsystem (loro_tpu/resilience/)."""


class DeviceFailure(ResilienceError):
    """Supervisor-declared device failure: a launch raised a
    non-recoverable runtime error, or exhausted its retry budget on
    transient ``UNAVAILABLE``-class errors.  Callers degrade to the
    host ``models/`` engine or surface this typed error — never an
    untyped crash, never a hang."""

    def __init__(self, label: str, attempts: int = 1, cause: str = ""):
        self.label = label
        self.attempts = attempts
        super().__init__(
            f"device failure at {label!r} after {attempts} attempt(s)"
            + (f": {cause}" if cause else "")
        )


class BackendUnavailable(DeviceFailure):
    """Backend init never came up within the probe deadline (the
    rounds-4/5 TPU-pool lottery, as a typed error instead of a hang)."""


class DeadlineExceeded(ResilienceError):
    """A cooperative deadline expired BETWEEN launches.  Raised only at
    launch boundaries — never by signaling a process mid-compile or
    mid-transfer (the tunnel-wedge post-mortems in docs/RESILIENCE.md)."""
