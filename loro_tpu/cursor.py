"""Stable cursors: positions that survive concurrent edits.

reference: crates/loro-internal/src/cursor.rs — a cursor stores the id
of the element it's anchored to (or a container end), and is resolved
against the *current* state at query time; if the element was deleted
the nearest surviving neighbor is used.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from .core.ids import ContainerID, ContainerType, ID
from .doc import LoroDoc, LoroError


class CursorSide(enum.IntEnum):
    Left = -1
    Middle = 0
    Right = 1


@dataclass(frozen=True)
class Cursor:
    container: ContainerID
    id: Optional[ID]  # None = container start/end depending on side
    side: CursorSide = CursorSide.Middle
    origin_pos: int = 0  # position when created (drift diagnostics)


@dataclass
class AbsolutePosition:
    pos: int
    side: CursorSide
    # True when the anchor element is gone and the cursor should be
    # re-created at `pos` (reference: cursor update hint)
    update_needed: bool = False


def get_cursor(doc: LoroDoc, container, pos: int, side: CursorSide = CursorSide.Middle) -> Cursor:
    """Create a stable cursor at visible position `pos`."""
    cid = container.id if hasattr(container, "id") else container
    st = doc.state.get_or_create(cid)
    seq = getattr(st, "seq", None)
    if seq is None:
        raise LoroError(f"{cid} does not support cursors")
    if pos >= seq.visible_len:
        return Cursor(cid, None, CursorSide.Right, pos)
    elem = seq.elem_at(pos)
    assert elem is not None
    # MovableList sequence elements are position *slots* whose content is
    # the stable element id — anchor to that so the cursor follows moves
    anchor = elem.content if cid.ctype == ContainerType.MovableList else elem.id
    return Cursor(cid, anchor, side, pos)


def get_cursor_pos(doc: LoroDoc, cursor: Cursor) -> AbsolutePosition:
    """Resolve a cursor against the current state."""
    st = doc.state.get_or_create(cursor.container)
    seq = getattr(st, "seq", None)
    if seq is None:
        raise LoroError(f"{cursor.container} does not support cursors")
    if cursor.id is None:
        return AbsolutePosition(seq.visible_len, cursor.side)
    if cursor.container.ctype == ContainerType.MovableList:
        entry = st.elems.get(cursor.id)  # type: ignore[union-attr]
        if entry is not None and not entry.deleted:
            idx = seq.visible_index_of(entry.slot)
            if idx is not None:
                return AbsolutePosition(idx, cursor.side)
        return AbsolutePosition(min(cursor.origin_pos, seq.visible_len), cursor.side, True)
    elem = seq.by_id.get((cursor.id.peer, cursor.id.counter))
    if elem is None:
        return AbsolutePosition(min(cursor.origin_pos, seq.visible_len), cursor.side, True)
    if elem.vis_w:
        return AbsolutePosition(seq.treap.visible_rank(elem), cursor.side)
    # anchor tombstoned: walk to the nearest visible successor
    from .utils.treap import Treap

    cur = Treap.successor(elem)
    while cur is not None and not cur.vis_w:
        cur = Treap.successor(cur)
    if cur is not None:
        return AbsolutePosition(seq.treap.visible_rank(cur), cursor.side, True)
    return AbsolutePosition(seq.visible_len, cursor.side, True)
