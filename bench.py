#!/usr/bin/env python
"""North-star benchmark: batched concurrent import of the automerge-perf
trace across a fleet of documents (BASELINE.md config 3).

Per doc, this performs the work of the reference's
`OpLog::import -> DiffCalculator -> apply` replay of the full trace
(reference harness: crates/loro-internal/benches/text_r.rs B4): resolve
the final Fugue sequence order of every element (insert integration +
tombstones) and materialize the visible document.  The fleet dimension
is the TPU win: all documents merge in one XLA launch per chunk.

Prints the compact flagship JSON line LAST (hard-budgeted under
FLAGSHIP_BUDGET chars so a 2,000-char tail window always captures it):
  {"metric": ..., "value": ops_merged_per_sec, "unit": ..., "vs_baseline": ...}
Verbose notes + the metrics/resilience/pipeline sidecars ride a
separate `sidecars_for` line printed just before it.

WEDGE-PROOF DESIGN (rounds 1+2 post-mortem: the driver artifact was
[cpu_fallback] twice because the device child burned its budget on cold
trace caches and risky compiles, then got SIGTERMed mid-flight, which
wedges the axon tunnel):
  * trace caches are COMMITTED to the repo (bench_utils) — a fresh
    checkout pays seconds, not ~300s of 1-core host replay
  * the device child runs BANKED PHASES in ascending risk order (XLA
    pilot -> XLA budget -> pallas compile -> pallas budget -> latency
    -> e2e), writing an incremental JSON checkpoint after each phase;
    the first device-provenance number exists minutes into the run
  * the parent emits the newest checkpoint when the child times out —
    a partial device number SURVIVES a later wedge; CPU fallback only
    happens when there is no device measurement at all
  * every stderr note carries elapsed seconds so a wedged run's tail
    localizes the hang

Baseline denominator: single-threaded reference (Rust) B4 import
throughput.  The reference repo publishes no numbers (BASELINE.md);
Rust is not installed in this image, so we use 2.0e6 ops/s — an
estimate on the generous side for loro's snapshot-import fast path on
this trace (~130ms for 260k ops) — and publish an explicit x2 band
(baseline_band) rather than a bare point estimate.
"""
import json
import os
import re
import sys
import time

import numpy as np

RUST_SINGLE_THREAD_OPS_PER_SEC = 2.0e6  # see module docstring
BASELINE_BAND = [1.0e6, 4.0e6]  # x/2 .. x2 sensitivity band around the estimate
BASELINE_NOTE = (
    "denominator is an ESTIMATE (2.0e6 ops/s single-thread Rust B4; Rust "
    "unavailable in image — BASELINE.md says measure, we cannot); "
    "baseline_band gives the x2 sensitivity band: divide value by band "
    "edges for the conservative/optimistic speedup"
)

# peak HBM bandwidth by TPU generation (bytes/s) for the roofline fields
HBM_PEAK = {"v5e": 819e9, "v5": 819e9, "v4": 1228e9, "v6": 1640e9}


def _fetch_sync(out) -> None:
    """Honest device sync for the kernel micro-phases: fetch the
    smallest array leaf with np.asarray.  block_until_ready is NOT a
    sync under the axon tunnel (timings come back ~0ms while the queue
    drains later) — the LT-TUNNEL post-mortem in docs/ANALYSIS.md."""
    import jax

    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "dtype")]
    if leaves:
        np.asarray(min(leaves, key=lambda a: getattr(a, "size", 1 << 62)))

T0 = time.time()


def note(msg: str) -> None:
    try:
        print(f"bench[{time.time() - T0:6.1f}s]: {msg}", file=sys.stderr, flush=True)
    except (BrokenPipeError, OSError):
        pass  # abandoned child whose parent (and pipe) is gone; keep banking


def _ckpt_path() -> str | None:
    return os.environ.get("BENCH_CHECKPOINT")


_CKPT: dict = {}


def _metrics_sidecar() -> dict | None:
    """The obs registry as a compact dict (docs/OBSERVABILITY.md
    "bench sidecar"): pad-waste / jit-shape / launch / epoch counters
    ride every BENCH_r*.json record from now on.  None when the obs
    package is unavailable or empty (the parent process never merges
    fleet work, so its sidecar would be noise)."""
    try:
        from loro_tpu.obs import sidecar

        side = sidecar()
        return side or None
    except Exception:  # tpulint: disable=LT-EXC(sidecars are optional; the flagship JSON line must always emit)
        return None


def _resilience_sidecar() -> dict | None:
    """Supervisor outcome dict (retries, degradations, drain budget)
    plus the parent's probe outcome — so BENCH_r*.json records capture
    flaky-pool sessions (docs/RESILIENCE.md) instead of losing them."""
    try:
        from loro_tpu.resilience import get_supervisor

        rep = get_supervisor().report()
        probe = os.environ.get("BENCH_PROBE_OUTCOME")
        if probe:
            rep["probe"] = probe
        return rep if (rep.get("launches") or probe) else None
    except Exception:  # tpulint: disable=LT-EXC(sidecars are optional; the flagship JSON line must always emit)
        return None


def bank(phase: str, **fields) -> None:
    """Merge fields into the checkpoint and atomically persist it.  The
    parent emits the newest checkpoint if this child never finishes.
    Every bank refreshes the metrics + resilience sidecars so a
    timeout-abandoned child still leaves its newest counters behind."""
    _CKPT.update(fields)
    side = _metrics_sidecar()
    if side:
        _CKPT["metrics"] = side
    res = _resilience_sidecar()
    if res:
        _CKPT["resilience"] = res
    _CKPT["last_phase"] = phase
    _CKPT["elapsed_s"] = round(time.time() - T0, 1)
    p = _ckpt_path()
    if p:
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_CKPT, f)
        os.replace(tmp, p)


def _final_record() -> dict:
    """Assemble the ONE output line from the checkpoint state."""
    ck = dict(_CKPT)
    return assemble_record(ck)


# ---------------------------------------------------------------------------
# flagship-line emission (round-5 verdict: the final JSON line was so
# fat with sidecars + notes that a 2,000-char tail window truncated the
# flagship fields).  The record now splits: verbose prose (*_note),
# dict sidecars (metrics/resilience/pipeline) and per-flight series
# ride a SECONDARY line tagged `sidecars_for`, printed first; the LAST
# line is always the compact flagship record, hard-budgeted under
# FLAGSHIP_BUDGET chars so any tail capture parses it whole.
# ---------------------------------------------------------------------------

FLAGSHIP_BUDGET = 2000

# never dropped from the flagship line, whatever the budget says
_CORE_KEYS = (
    "metric", "value", "unit", "vs_baseline", "device", "failure",
    "partial", "last_phase", "sidecars",
)
# always routed to the sidecar line: prose, dict sidecars, series —
# plus the roofline model/measured numerics, which ride with their
# notes (the flagship keeps the serving + kernel headline numbers)
_SIDECAR_KEYS = (
    "metrics", "resilience", "pipeline", "rank", "sync", "shard", "tier",
    "readplane", "repl", "trace", "net", "health",
    "gather_rows_per_sec", "hbm_bytes_per_op_model",
    "achieved_hbm_gbps_model", "hbm_frac_model", "rank_ms_measured",
    "place_ms_measured", "gather_rows_per_sec_measured",
    "achieved_hbm_gbps_measured", "hbm_frac",
    "baseline_note", "latency_note", "roofline_note",
    "roofline_measured_note", "resident_note", "resident_durable_note",
    "resident_pipeline_note", "e2e_note", "e2e_unit", "richtext_unit",
    "latency_series_ms", "xla_flight_ms", "pallas_flight_ms",
    "wedge_info",
)


def split_record(rec: dict):
    """``(flagship, sidecars_or_None)``: flagship keeps the metric /
    value / vs_baseline / device numerics and stays under
    FLAGSHIP_BUDGET chars (over-budget extras spill to the sidecar
    line, largest first, core fields never)."""
    flag = {k: v for k, v in rec.items() if k not in _SIDECAR_KEYS}
    extras = {k: rec[k] for k in _SIDECAR_KEYS if k in rec}
    while len(json.dumps(flag)) > FLAGSHIP_BUDGET - 100:
        droppable = [k for k in flag if k not in _CORE_KEYS]
        if not droppable:
            break
        big = max(droppable, key=lambda k: len(json.dumps(flag[k])))
        extras[big] = flag.pop(big)
    if not extras:
        return flag, None
    side = {"sidecars_for": flag.get("metric", "?")}
    side.update(extras)
    flag["sidecars"] = "previous_line"
    return flag, side


def emit_record(rec: dict) -> None:
    """Print the (optional) sidecar line, then the compact flagship
    line LAST — the driver's tail window and _last_json_record both key
    on the final ``metric`` line."""
    flag, side = split_record(rec)
    if side:
        print(json.dumps(side), flush=True)
    print(json.dumps(flag), flush=True)


def _ambient_fields(rec: dict) -> dict:
    """Attach wedge info + ambient load to a record (r4 verdict weak #7:
    cross-round CPU comparisons are load-confounded).  setdefault only —
    a child-measured load is more truthful than a parent re-measurement."""
    wi = os.environ.get("BENCH_WEDGE_INFO")
    if wi:
        rec.setdefault("wedge_info", wi)
    try:
        rec.setdefault("load_avg_1m", round(os.getloadavg()[0], 2))
    except OSError:
        pass
    return rec


def assemble_record(ck: dict) -> dict:
    """Build the output JSON from a (possibly partial) checkpoint dict.
    Shared by the child (complete run) and the parent (timeout path)."""
    value = ck.get("value")
    metric = ck.get("metric", "ops_merged_per_sec_per_chip")
    label = os.environ.get("BENCH_LABEL")
    if label:
        metric = f"{metric} [{label}]"
    rec = {
        "metric": metric,
        "value": round(value) if value else 0,
        "unit": ck.get("unit", "ops/s"),
        "vs_baseline": round((value or 0) / RUST_SINGLE_THREAD_OPS_PER_SEC, 2),
        "baseline_band": BASELINE_BAND,
        "baseline_note": BASELINE_NOTE,
    }
    for k in (
        "device",
        "phases_done",
        "last_phase",
        "partial",
        "kernel",
        "place_algo",
        "xla_flight_median",
        "pallas_flight_median",
        "merge_latency_ms_p50",
        "merge_latency_ms_p99",
        "merge_latency_ms_max",
        "latency_samples",
        "latency_note",
        "tunnel_rtt_ms",
        "xla_rank_value",
        "ring_tokens_per_doc",
        "rank_rounds",
        "rank_gather_reduction",
        "rank_gather_rows_per_op",
        "rank",
        "gather_rows_per_sec",
        "hbm_bytes_per_op_model",
        "achieved_hbm_gbps_model",
        "hbm_frac_model",
        "roofline_note",
        "rank_ms_measured",
        "place_ms_measured",
        "gather_rows_per_sec_measured",
        "achieved_hbm_gbps_measured",
        "hbm_frac",
        "roofline_measured_note",
        "e2e_value",
        "e2e_unit",
        "e2e_vs_baseline",
        "e2e_note",
        "resident_rows_per_sec",
        "resident_rows_per_sec_best",
        "resident_note",
        "resident_sync_rows_per_sec",
        "resident_pipeline_rows_per_sec",
        "resident_pipeline_speedup",
        "resident_pipeline_note",
        "pipeline",
        "resident_durable_rows_per_sec",
        "resident_durable_replayed_rounds",
        "resident_durable_note",
        "resident_durable_fsyncs",
        "resident_durable_group_fsyncs",
        "resident_durable_group_rows_per_sec",
        "richtext_value",
        "richtext_unit",
        "richtext_vs_baseline",
        "sync_sessions",
        "sync_pushes_per_sec",
        "sync_push_to_visible_ms_p50",
        "sync_push_to_visible_ms_p99",
        "sync",
        "sync_readers",
        "sync_pulls_per_sec",
        "sync_pulls_per_sec_oracle",
        "sync_read_speedup",
        "sync_pull_ms_p50",
        "sync_pull_ms_p99",
        "readplane",
        "repl_readers",
        "repl_pulls_per_sec",
        "repl_pulls_per_sec_leader_only",
        "repl_read_scaling_x",
        "repl_lag_ms_p50",
        "repl_lag_ms_p99",
        "repl_promotion_downtime_ms",
        "repl",
        "net_connections",
        "net_pushes_per_sec",
        "net_push_to_visible_ms_p50",
        "net_push_to_visible_ms_p99",
        "net",
        "shard_count",
        "shard_rows_per_sec",
        "shard_scaling_x",
        "shard",
        "tier_hit_rate",
        "tier_revive_ms_p50",
        "tier_revive_ms_p99",
        "tier_rows_per_sec",
        "tier_all_hot_rows_per_sec",
        "tier_vs_all_hot",
        "tier_hot_path_ratio",
        "tier",
        "health_tick_ns",
        "health_skew_ratio",
        "health",
        "trace",
        "metrics",
        "resilience",
        "elapsed_s",
    ):
        if k in ck and ck[k] is not None:
            rec[k] = ck[k]
    return _ambient_fields(rec)


def _emit_simple(metric: str, ops_per_sec: float, extras: dict | None = None) -> None:
    label = os.environ.get("BENCH_LABEL")
    if label:
        metric = f"{metric} [{label}]"
    rec = {
        "metric": metric,
        "value": round(ops_per_sec),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / RUST_SINGLE_THREAD_OPS_PER_SEC, 2),
    }
    if extras:
        rec.update(extras)
    side = _metrics_sidecar()
    if side:
        rec["metrics"] = side
    emit_record(_ambient_fields(rec))


# ---------------------------------------------------------------------------
# secondary configs (BENCH_CONFIG=map|tree|movable|richtext|size)
# ---------------------------------------------------------------------------


def bench_map() -> None:
    """BASELINE config 1: batched LWW-map concurrent import."""
    import jax

    from loro_tpu.ops.lww import MapOpCols, lww_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "1024"))
    m = int(os.environ.get("BENCH_MAP_OPS", "65536"))
    s = int(os.environ.get("BENCH_MAP_SLOTS", "4096"))
    rng = np.random.default_rng(0)
    cols = MapOpCols(
        slot=rng.integers(0, s, (docs, m)).astype(np.int32),
        lamport=rng.integers(0, 1 << 20, (docs, m)).astype(np.int32),
        peer=rng.integers(0, 64, (docs, m)).astype(np.int32),
        value_idx=np.arange(docs * m, dtype=np.int32).reshape(docs, m) % (1 << 20),
        valid=np.ones((docs, m), bool),
    )
    dev = MapOpCols(*[jax.device_put(a) for a in cols])
    out = lww_merge_batch(dev, s)
    _fetch_sync(out)
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        out = lww_merge_batch(dev, s)
    _fetch_sync(out)
    dt = (time.perf_counter() - t0) / reps
    _emit_simple(f"lww_map ops merged/sec ({docs}-doc batch, {m} ops/doc)", docs * m / dt)


def bench_tree() -> None:
    """BASELINE config 5: deep hierarchy, concurrent move/reparent."""
    import jax

    from loro_tpu.ops.tree_batch import TreeOpCols, tree_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "1024"))
    n_nodes = int(os.environ.get("BENCH_TREE_NODES", "512"))
    m = int(os.environ.get("BENCH_TREE_MOVES", "2048"))
    rng = np.random.default_rng(0)
    target = rng.integers(0, n_nodes, (docs, m)).astype(np.int32)
    parent = rng.integers(-2, n_nodes, (docs, m)).astype(np.int32)
    cols = TreeOpCols(target=target, parent=parent, valid=np.ones((docs, m), bool))
    dev = TreeOpCols(*[jax.device_put(a) for a in cols])
    d_max = os.environ.get("BENCH_TREE_DEPTH")
    d_max = int(d_max) if d_max else None
    out = tree_merge_batch(dev, n_nodes, d_max)
    _fetch_sync(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = tree_merge_batch(dev, n_nodes, d_max)
    _fetch_sync(out)
    dt = (time.perf_counter() - t0) / reps
    _emit_simple(f"tree moves merged/sec ({docs}-doc batch, {m} moves/doc)", docs * m / dt)


def bench_movable() -> None:
    """BASELINE config ~4/5 hybrid: movable-list concurrent move/set."""
    import jax

    from loro_tpu.ops.fugue_batch import SeqColumns
    from loro_tpu.ops.movable_batch import MovableCols, movable_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "256"))
    s = int(os.environ.get("BENCH_SLOTS", "8192"))  # slots per doc
    n_elems = s // 2
    rng = np.random.default_rng(0)
    parent = np.concatenate(
        [
            np.arange(-1, n_elems - 1, dtype=np.int32),
            rng.integers(0, n_elems, s - n_elems).astype(np.int32),
        ]
    )
    elem = np.concatenate(
        [np.arange(n_elems, dtype=np.int32), rng.integers(0, n_elems, s - n_elems).astype(np.int32)]
    )
    lam = np.concatenate(
        [
            np.arange(n_elems, dtype=np.int32),
            rng.integers(n_elems, 4 * n_elems, s - n_elems).astype(np.int32),
        ]
    )
    seq = SeqColumns(
        parent=np.broadcast_to(parent, (docs, s)).copy(),
        side=np.ones((docs, s), np.int32),
        peer=np.zeros((docs, s), np.int32),
        counter=np.broadcast_to(np.arange(s, dtype=np.int32), (docs, s)).copy(),
        deleted=np.zeros((docs, s), bool),
        content=np.broadcast_to(elem, (docs, s)).copy(),
        valid=np.ones((docs, s), bool),
    )
    cols = MovableCols(
        seq=SeqColumns(*[jax.device_put(a) for a in seq]),
        lamport=jax.device_put(np.broadcast_to(lam, (docs, s)).copy()),
        set_elem=jax.device_put(
            np.broadcast_to(np.arange(n_elems, dtype=np.int32), (docs, n_elems)).copy()
        ),
        set_lamport=jax.device_put(np.zeros((docs, n_elems), np.int32)),
        set_peer=jax.device_put(np.zeros((docs, n_elems), np.int32)),
        set_value=jax.device_put(
            np.broadcast_to(np.arange(n_elems, dtype=np.int32), (docs, n_elems)).copy()
        ),
        set_valid=jax.device_put(np.ones((docs, n_elems), bool)),
    )
    out = movable_merge_batch(cols, n_elems)
    _fetch_sync(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = movable_merge_batch(cols, n_elems)
    _fetch_sync(out)
    dt = (time.perf_counter() - t0) / reps
    _emit_simple(f"movable_list ops merged/sec ({docs}-doc batch, {s} slots/doc)", docs * s / dt)


def bench_size() -> None:
    """Encoded-size harness (reference: examples/benches/mergeable_size
    + encode.rs): bytes per op for updates / snapshot / state-only on
    the automerge trace prefix."""
    from loro_tpu import ExportMode, LoroDoc
    from loro_tpu.bench_utils import load_automerge_patches

    n_txn = int(os.environ.get("BENCH_TXN_LIMIT", "20000"))
    patches, _ = load_automerge_patches(limit=n_txn)
    doc = LoroDoc(peer=1)
    t = doc.get_text("text")
    for pos, dels, ins in patches:
        if dels:
            t.delete(pos, dels)
        if ins:
            t.insert(pos, ins)
    doc.commit()
    updates = len(doc.export_updates())
    snapshot = len(doc.export(ExportMode.Snapshot))
    state_only = len(doc.export(ExportMode.StateOnly))
    n_ops = len(patches)
    print(
        json.dumps(
            {
                "metric": (
                    f"update bytes/op ({n_ops} ops; snapshot={snapshot}B "
                    f"state_only={state_only}B)"
                ),
                "value": round(updates / n_ops, 2),
                "unit": "bytes/op",
                "vs_baseline": 1.0,
            }
        ),
        flush=True,
    )


def bench_richtext(emit: bool = True) -> float:
    """BASELINE config 4: concurrent formatting spans + text edits at
    fleet scale — full merge (Fugue order + Peritext style resolution)
    of concurrent multi-peer rich-text docs, correctness-gated against
    the host oracle (reference: text_r.rs richtext analogs + style
    semantics in style_range_map.rs)."""
    import jax

    from loro_tpu.bench_utils import RICHTEXT_KEYS, richtext_bench_docs
    from loro_tpu.ops.richtext_batch import (
        RichtextChainCols,
        richtext_chain_merge_batch,
        segments_from_device,
    )

    docs_total = int(os.environ.get("BENCH_RT_DOCS", "512"))
    chunk = int(os.environ.get("BENCH_RT_CHUNK", "16"))
    n_distinct = int(os.environ.get("BENCH_RT_DISTINCT", "8"))
    distinct, pad_n, pad_p, pad_c = richtext_bench_docs(n_distinct=n_distinct)
    n_keys = len(RICHTEXT_KEYS)
    note(f"richtext: {n_distinct} distinct docs, pad_n={pad_n} pad_p={pad_p} pad_c={pad_c}")
    from loro_tpu.ops.fugue_batch import ChainColumns

    idx0 = [j % n_distinct for j in range(chunk)]
    chunk_cols = [distinct[i]["cols"] for i in idx0]
    batch = RichtextChainCols(
        chain=ChainColumns(
            *[
                jax.device_put(np.stack([getattr(c.chain, f) for c in chunk_cols]))
                for f in ChainColumns._fields
            ]
        ),
        **{
            f: jax.device_put(np.stack([getattr(c, f) for c in chunk_cols]))
            for f in RichtextChainCols._fields
            if f != "chain"
        },
    )
    codes, counts, bounds, win = richtext_chain_merge_batch(batch, n_keys)
    for j in range(min(chunk, n_distinct)):  # one slot per distinct doc
        d = distinct[idx0[j]]
        segs = segments_from_device(
            np.asarray(codes[j]), counts[j], bounds[j], win[j], d["keys"], d["values"]
        )
        assert segs == d["oracle"], f"richtext device merge != host oracle (doc {j})"
    ops_per_chunk = sum(distinct[i]["n_ops"] for i in idx0)
    np.asarray(counts)  # fetch-sync (block_until_ready lies under axon)
    n_chunks = max(1, docs_total // chunk)
    t0 = time.perf_counter()
    out = None
    for i in range(n_chunks):
        out = richtext_chain_merge_batch(batch, n_keys)
    np.asarray(out[1])
    dt = time.perf_counter() - t0
    ops_s = ops_per_chunk * n_chunks / dt
    if emit:
        _emit_simple(
            f"richtext ops merged/sec ({n_chunks * chunk}-doc concurrent import, "
            f"{n_distinct} distinct multi-peer docs, marks+edits)",
            ops_s,
        )
    return ops_s


# ---------------------------------------------------------------------------
# flagship config: phased, banked, wedge-proof
# ---------------------------------------------------------------------------


def main() -> None:
    # bench runs on the real chip (ambient platform) by default; an
    # explicit JAX_PLATFORMS env must win even though the axon plugin
    # overrides it at the config level
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    config = os.environ.get("BENCH_CONFIG", "text")
    if config == "map":
        return bench_map()
    if config == "tree":
        return bench_tree()
    if config == "movable":
        return bench_movable()
    if config == "size":
        return bench_size()
    if config == "richtext":
        return bench_richtext()

    from loro_tpu.bench_utils import (
        automerge_final_text,
        automerge_seq_extract,
        concurrent_trace_variants,
    )
    from loro_tpu.ops.columnar import chain_columns, contract_chains
    from loro_tpu.ops.fugue_batch import (
        ChainColumns,
        chain_merge_docs_checksum_v,
        chain_merge_docs_v,
    )

    docs_total = int(os.environ.get("BENCH_DOCS", "10240"))
    chunk = int(os.environ.get("BENCH_CHUNK", "8"))
    budget_s = float(os.environ.get("BENCH_BUDGET", "240"))  # flagship loop
    xla_budget_s = float(os.environ.get("BENCH_XLA_BUDGET", "75"))
    lat_budget_s = float(os.environ.get("BENCH_LAT_BUDGET", "150"))
    e2e_docs_req = int(os.environ.get("BENCH_E2E_DOCS", "64"))
    e2e_budget_s = float(os.environ.get("BENCH_E2E_BUDGET", "90"))
    n_variants = int(os.environ.get("BENCH_VARIANTS", "8"))
    child_deadline = T0 + float(os.environ.get("BENCH_CHILD_DEADLINE", "660"))
    limit = os.environ.get("BENCH_TXN_LIMIT")
    limit = int(limit) if limit else None

    def remaining() -> float:
        return child_deadline - time.time()

    # every device phase below routes through one DeviceSupervisor:
    # bounded in-flight budget (drain_every=8, the post-mortem rule),
    # cooperative deadline at the child deadline minus a drain margin
    # (checked BETWEEN launches — an expiry surfaces as a typed
    # DeadlineExceeded at a launch boundary, never a signal), and its
    # report() banks as the `resilience` sidecar on every checkpoint
    from loro_tpu.resilience import DeviceSupervisor, set_supervisor

    sup = DeviceSupervisor(drain_every=8, deadline_s=max(30.0, remaining() - 15))
    set_supervisor(sup)

    # ---- phase 0: device contact (banked BEFORE anything else) -------
    # A wedged axon tunnel hangs on the FIRST device op; banking a
    # device-provenance record immediately lets the parent distinguish
    # "tunnel dead at first contact" from "wedged after N phases".
    note("phase-0: device contact (jax.devices() + tiny fetch)...")
    dev0 = jax.devices()[0]
    platform = dev0.platform
    device_kind = getattr(dev0, "device_kind", platform)
    on_tpu = platform == "tpu" or "TPU" in str(device_kind)
    bank("device_contact", device=f"{platform}:{device_kind}")
    import jax.numpy as jnp

    # the x+1-fetch probe now lives in obs (feeds the tunnel.rtt_ms
    # gauge for the sidecar AND returns the median RTT for banking)
    from loro_tpu.obs import measure_tunnel_rtt

    rtt = measure_tunnel_rtt(reps=3)
    note(f"device: platform={platform} kind={device_kind}, tunnel RTT ~{rtt * 1e3:.0f}ms")
    bank("device_fetch", tunnel_rtt_ms=round(rtt * 1e3, 1))

    # ---- phase: extraction (seconds — caches are committed) ----------
    note("extracting trace + concurrent variants (committed caches)...")
    ex0, n_ops = automerge_seq_extract(limit=limit)
    variants = concurrent_trace_variants(n_variants=n_variants, limit=limit)
    extracts = [ex0] + [v["extract"] for v in variants]
    per_doc_ops = [n_ops] + [v["n_ops"] for v in variants]
    want0 = automerge_final_text(limit=limit)
    note(f"extraction done ({len(extracts)} distinct traces)")
    import loro_tpu.bench_utils as _bu

    if _bu.SYNTHETIC_FALLBACK:
        # no automerge-perf file in this image: numbers are NOT
        # comparable to real-trace rounds — tag the record
        note("automerge trace file absent: SYNTHETIC fallback trace in use")
        bank("extraction", trace="synthetic_fallback")
    else:
        bank("extraction")

    # the trace set is fixed for the whole run, so pad to the batch max
    # on a fine quantum instead of power-of-two buckets: ranking cost is
    # linear in pad_c (the ring is 2*(pad_c+1) tokens)
    def pad_to(n: int, q: int) -> int:
        return -(-n // q) * q

    pad_n = pad_to(max(e.n for e in extracts), 8192)
    pad_c = pad_to(max(contract_chains(e).n_chains for e in extracts), 1024)
    per_doc_cols = [chain_columns(e, pad_n=pad_n, pad_c=pad_c) for e in extracts]
    per_doc_rows = [e.n for e in extracts]
    n_distinct = len(per_doc_cols)
    n_batches = max(1, -(-n_distinct // chunk))
    host_batches = []
    batch_ops = []
    batch_rows = []
    for b in range(n_batches):
        idxs = [(b * chunk + j) % n_distinct for j in range(chunk)]
        docs = [per_doc_cols[i] for i in idxs]
        batch_ops.append(sum(per_doc_ops[i] for i in idxs))
        batch_rows.append(sum(per_doc_rows[i] for i in idxs))
        host_batches.append(
            ChainColumns(*[np.stack([getattr(c, f) for c in docs]) for f in ChainColumns._fields])
        )
    from loro_tpu.obs import metrics as obs_m

    obs_m.unique("fleet.padded_shapes_distinct").add(
        ("chain_text", pad_n, pad_c, chunk)
    )

    def sync(o) -> None:
        # jax.block_until_ready does NOT synchronize under the axon
        # tunnel; every sync point fetches a scalar with np.asarray
        np.asarray(o[0])

    # ---- phase: upload pilot batch + XLA compile + correctness -------
    note(f"uploading pilot batch ({chunk} docs, pad_n={pad_n} pad_c={pad_c})...")
    batches = [ChainColumns(*[jax.device_put(a) for a in host_batches[0]])]
    note("compiling XLA merge kernel (first compile ~20-40s)...")
    codes, counts = chain_merge_docs_v(batches[0], rank_impl="xla")
    got = "".join(map(chr, np.asarray(codes[0])[: int(counts[0])]))
    assert got == want0, f"device merge mismatch: {len(got)} vs {len(want0)} chars"
    if variants and chunk >= 2:
        got1 = "".join(map(chr, np.asarray(codes[1])[: int(counts[1])]))
        assert got1 == variants[0]["text"], "variant merge mismatch vs host oracle"
    note("XLA kernel correctness gates passed")

    metric = (
        "ops_merged_per_sec_per_chip (automerge-perf trace, "
        f"{{docs}}-doc concurrent import, {n_distinct} distinct traces cycled)"
    )

    # checksum variant (cheap fetches) for all timed loops
    sync(chain_merge_docs_checksum_v(batches[0], rank_impl="xla"))
    t0 = time.perf_counter()
    sync(chain_merge_docs_checksum_v(batches[0], rank_impl="xla"))
    t_pilot = time.perf_counter() - t0
    pilot_ops_s = batch_ops[0] / max(t_pilot, 1e-9)
    note(f"XLA pilot chunk: {t_pilot * 1e3:.0f}ms ({pilot_ops_s / 1e6:.1f}M ops/s w/ RTT)")
    bank(
        "xla_pilot",
        value=pilot_ops_s,
        kernel="xla",
        metric=metric.format(docs=chunk),
        partial="pilot only (1 chunk, incl. tunnel RTT)",
    )

    # remaining uploads
    note(f"uploading remaining {n_batches - 1} chunk batches...")
    for hb in host_batches[1:]:
        batches.append(ChainColumns(*[jax.device_put(a) for a in hb]))
    note(f"uploaded {n_batches} batches ({n_distinct} distinct traces)")

    def budget_loop(fn, secs: float, label: str):
        """Timed throughput loop: flights of `drain` launches with a
        fetch-sync between flights (bounds the in-device queue; the
        queue drains through the final fetch so wall-clock spans real
        work).  Launches route through the DeviceSupervisor, whose
        drain_every matches the flight size — the supervisor's
        auto-drain IS the between-flight sync, so the in-flight queue
        provably never exceeds the budget.  Returns (ops/s, docs_done,
        flight_times)."""
        drain = sup.drain_every
        n_chunks_req = max(1, docs_total // chunk)
        n_chunks = max(1, min(n_chunks_req, int(secs / max(t_pilot / 4, 1e-9))))
        flights = []
        t0 = time.perf_counter()
        out = None
        ops_done = 0
        i = 0
        tf = t0
        while i < n_chunks:
            b = batches[i % n_batches]
            out = sup.launch(lambda b=b: fn(b), label=f"bench.{label}")
            ops_done += batch_ops[i % n_batches]
            i += 1
            if i % drain == 0:
                # the supervisor auto-drained at this boundary (depth
                # hit drain_every on the launch above); flight is timed
                # against that fetch-sync
                now = time.perf_counter()
                flights.append(now - tf)
                tf = now
                if (now - t0) > secs or remaining() < 30:
                    note(f"{label}: budget expired after {i}/{n_chunks} chunks")
                    break
        sup.drain(lambda: sync(out))
        dt = time.perf_counter() - t0
        # fleet accounting for the sidecar: the budget loop is the
        # bench's merge front-end, so it ticks the same counters the
        # Fleet API does (family chain_text = direct chain kernel)
        rows_done = sum(batch_rows[j % n_batches] for j in range(i))
        obs_m.counter("fleet.merge_calls_total").inc(i, family="chain_text")
        obs_m.counter("fleet.device_launches_total").inc(i, family="chain_text")
        obs_m.counter("fleet.docs_merged_total").inc(i * chunk, family="chain_text")
        obs_m.counter("fleet.ops_merged_total").inc(rows_done, family="chain_text")
        obs_m.counter("fleet.pad_waste_rows_total").inc(
            i * chunk * pad_n - rows_done, family="chain_text"
        )
        obs_m.gauge("tunnel.drain_depth").set(drain)
        return ops_done / dt, i * chunk, flights

    def flight_median_rate(ops_s: float, flights) -> float | None:
        """Load-robust throughput: ops-per-flight / median flight time.
        The mean rate is confounded by ambient load spikes (r4 verdict
        weak #7: same code measured 0.82x vs 1.53x under different
        session load); the median flight is the stable cross-round
        comparator."""
        if len(flights) < 3:
            return None
        ops_per_flight = ops_s * sum(flights) / len(flights)
        med = sorted(flights)[len(flights) // 2]
        return ops_per_flight / med

    # ---- phase: XLA budget loop (banked device number, low risk) -----
    note(f"XLA budget loop ({xla_budget_s:.0f}s)...")
    xla_ops_s, xla_docs, xla_flights = budget_loop(
        lambda b: chain_merge_docs_checksum_v(b, rank_impl="xla"), xla_budget_s, "xla"
    )
    note(f"XLA kernel: {xla_ops_s / 1e6:.1f}M ops/s over {xla_docs} docs")
    xla_med = flight_median_rate(xla_ops_s, xla_flights)
    bank(
        "xla_budget",
        value=xla_ops_s,
        kernel="xla",
        place_algo=os.environ.get("PLACE_ALGO", "sort"),
        metric=metric.format(docs=xla_docs),
        partial="XLA rank kernel (pallas phase not yet run)",
        xla_rank_value=round(xla_ops_s),
        xla_flight_median=round(xla_med) if xla_med is not None else None,
        # per-flight wall times (8 launches each): postmortem time series
        xla_flight_ms=[round(t * 1e3, 1) for t in xla_flights],
    )

    # ---- phase: rank A/B (gather-count reduction, CPU-mesh-provable) --
    # ISSUE 6: ranking gathers are ~all of merge cost on chip, so the
    # reduction is judged by COUNTS (rank_model is the shared ledger):
    # base = the wyllie default, new = run-coalesced ring + ruling
    # sub-rank at a budget sized from the measured run statistics.
    # Byte-identity gates on the pilot batch; wall-clock rides along as
    # a sanity field only.
    if remaining() > 45 and os.environ.get("BENCH_SKIP_RANK_AB") != "1":
        try:
            from loro_tpu.ops import rank_model as _rm
            from loro_tpu.ops.fugue_batch import chain_rank_checksum_v as _crank_v

            note("rank A/B phase: run-coalesced vs wyllie gather counts...")
            rings = [
                _rm.build_ring(
                    np.asarray(c.c_parent), np.asarray(c.c_side), np.asarray(c.c_valid)
                )
                for c in per_doc_cols
            ]
            stats = [_rm.ring_stats(s) for s in rings]
            n_runs_max = max(st["n_runs"] for st in stats)
            mean_run = float(np.mean([st["mean_run"] for st in stats]))
            ring_budget = _rm.coalesce_budget(n_runs_max)
            # realized (simulated rounds) + analytic cap, once per
            # DISTINCT ring, multiplied by its occurrence count in the
            # pilot chunk (docs cycle j % n_distinct)
            occur = [0] * n_distinct
            for j in range(chunk):
                occur[j % n_distinct] += 1
            base_rows = new_rows = 0
            for s, cnt in zip(rings, occur):
                if not cnt:
                    continue
                base_rows += cnt * _rm.simulate(s, "wyllie")[1]["global_rows"]
                new_rows += cnt * _rm.simulate(
                    s, "coalesced", r_pad=ring_budget
                )[1]["global_rows"]
            m_ring_len = len(rings[0])  # all rings share the padded length
            model_base = chunk * _rm.gather_model(m_ring_len, "wyllie")["global_rows"]
            model_new = chunk * _rm.gather_model(
                m_ring_len, "coalesced", r_pad=ring_budget
            )["global_rows"]
            # correctness gates: byte-identical text + identical rank
            # checksums (every algorithm computes the same distances)
            codes_c, counts_c = chain_merge_docs_v(
                batches[0], rank_impl="xla:coalesced", ring_budget=ring_budget
            )
            got_c = "".join(map(chr, np.asarray(codes_c[0])[: int(counts_c[0])]))
            assert got_c == want0, "coalesced merge mismatch vs ground truth"
            cs_base = np.asarray(_crank_v(batches[0], rank_impl="xla:wyllie"))
            cs_new = np.asarray(
                _crank_v(batches[0], rank_impl="xla:coalesced", ring_budget=ring_budget)
            )
            assert (cs_base == cs_new).all(), "coalesced rank checksum mismatch"
            note("rank A/B correctness gates passed (text + rank checksums)")

            def timed_rank(spec, budget=None, reps=3):
                fn = lambda b: _crank_v(b, rank_impl=spec, ring_budget=budget)  # noqa: E731
                np.asarray(fn(batches[0]))
                ts = []
                for _ in range(reps):
                    t1 = time.perf_counter()
                    np.asarray(fn(batches[0]))
                    ts.append(time.perf_counter() - t1)
                return sorted(ts)[len(ts) // 2]

            t_base = max(timed_rank("xla:wyllie") - rtt, 1e-4)
            t_new = max(timed_rank("xla:coalesced", ring_budget) - rtt, 1e-4)
            ops_chunk = batch_ops[0]
            reduction = base_rows / max(new_rows, 1)
            note(
                f"rank A/B: {base_rows}->{new_rows} global gather rows/chunk "
                f"(x{reduction:.2f}), wall {t_base * 1e3:.0f}->{t_new * 1e3:.0f}ms"
            )
            bank(
                "rank_ab",
                rank_gather_reduction=round(reduction, 2),
                rank_gather_rows_per_op=round(new_rows / ops_chunk, 2),
                rank={
                    "algo_base": "xla:wyllie",
                    "algo_new": "xla:coalesced",
                    "ring_tokens": 2 * (pad_c + 1),
                    "n_runs_max": n_runs_max,
                    "mean_run": round(mean_run, 2),
                    "ring_budget": ring_budget,
                    "gather_rows_base": int(base_rows),
                    "gather_rows_new": int(new_rows),
                    "gather_rows_base_per_op": round(base_rows / ops_chunk, 2),
                    "gather_rows_new_per_op": round(new_rows / ops_chunk, 2),
                    "model_rows_base": int(model_base),
                    "model_rows_new": int(model_new),
                    "rank_ms_base": round(t_base * 1e3, 1),
                    "rank_ms_new": round(t_new * 1e3, 1),
                    "gather_rows_per_sec_base": round(base_rows / t_base),
                    "gather_rows_per_sec_new": round(new_rows / t_new),
                    "note": (
                        "global random-gather rows per pilot chunk, realized "
                        "(simulated adaptive rounds on the real rings) and "
                        "analytic-cap model; reduction is count-based — wall "
                        "times are rank-only fetch-synced medians net of RTT "
                        "and only sanity-check the counts"
                    ),
                },
            )
        except Exception as e:  # an extra, never the headline — tpulint: disable=LT-EXC(rank-A/B extra, never the headline)
            note(f"rank A/B phase failed ({type(e).__name__}: {e})")
            bank("rank_ab_failed", partial=f"rank A/B failed: {type(e).__name__}")

    # ---- phase: pallas compile + budget loop (the flagship) ----------
    flagship_fn = lambda b: chain_merge_docs_checksum_v(b, rank_impl="xla")  # noqa: E731
    kernel_name = "xla"
    kernel_ops_s = xla_ops_s
    kernel_docs = xla_docs
    from loro_tpu.ops.pallas_rank import HAVE_PALLAS, PALLAS_RANK_MAX_M

    ring_ok = 2 * (pad_c + 1) <= PALLAS_RANK_MAX_M
    want_pallas = os.environ.get("BENCH_PALLAS", "1") != "0"
    if on_tpu and HAVE_PALLAS and ring_ok and want_pallas and remaining() > 90:
        # the pallas compile rides the remote-compile service; it runs
        # ONLY after the XLA numbers are banked (a wedge here cannot
        # erase the device measurement)
        note("compiling pallas rank kernel (remote compile; banked numbers are safe)...")
        try:
            codes, counts = chain_merge_docs_v(batches[0], rank_impl="pallas")
            got = "".join(map(chr, np.asarray(codes[0])[: int(counts[0])]))
            assert got == want0, "pallas merge mismatch vs ground truth"
            if variants and chunk >= 2:
                got1 = "".join(map(chr, np.asarray(codes[1])[: int(counts[1])]))
                assert got1 == variants[0]["text"], "pallas variant mismatch vs host oracle"
            note("pallas kernel correctness gates passed")
            sync(chain_merge_docs_checksum_v(batches[0], rank_impl="pallas"))
            t0 = time.perf_counter()
            sync(chain_merge_docs_checksum_v(batches[0], rank_impl="pallas"))
            t_pilot_p = time.perf_counter() - t0
            note(f"pallas pilot chunk: {t_pilot_p * 1e3:.0f}ms")
            bank("pallas_pilot", partial="pallas pilot done, budget loop pending")
            secs = min(budget_s, max(remaining() - 150, 30))
            note(f"pallas budget loop ({secs:.0f}s)...")
            p_ops_s, p_docs, p_flights = budget_loop(
                lambda b: chain_merge_docs_checksum_v(b, rank_impl="pallas"),
                secs,
                "pallas",
            )
            note(f"pallas kernel: {p_ops_s / 1e6:.1f}M ops/s over {p_docs} docs")
            if p_ops_s > kernel_ops_s:
                kernel_ops_s, kernel_docs, kernel_name = p_ops_s, p_docs, "pallas"
                flagship_fn = lambda b: chain_merge_docs_checksum_v(  # noqa: E731
                    b, rank_impl="pallas"
                )
            p_med = flight_median_rate(p_ops_s, p_flights)
            bank(
                "pallas_budget",
                value=kernel_ops_s,
                kernel=kernel_name,
                metric=metric.format(docs=kernel_docs),
                partial=None,
                pallas_flight_median=round(p_med) if p_med is not None else None,
                pallas_flight_ms=[round(t * 1e3, 1) for t in p_flights],
            )
        except Exception as e:  # pallas is an upgrade, never a downgrade — tpulint: disable=LT-EXC(pallas is an upgrade, never a downgrade)
            note(f"pallas phase failed ({type(e).__name__}: {e}); keeping XLA numbers")
            bank("pallas_failed", partial=f"pallas failed: {type(e).__name__}")
    else:
        why = (
            "off-TPU" if not on_tpu else
            "no pallas" if not HAVE_PALLAS else
            "ring too long" if not ring_ok else
            "BENCH_PALLAS=0" if not want_pallas else "deadline"
        )
        note(f"skipping pallas phase ({why})")

    # ---- phase: per-launch latency distribution (true p99) -----------
    if remaining() > 45 and os.environ.get("BENCH_SKIP_LAT") != "1":
        secs = min(lat_budget_s, remaining() - 30)
        n_lat_max = int(os.environ.get("BENCH_LAT_SAMPLES", "1024"))
        note(f"latency phase: fetch-synced chunk merges for up to {secs:.0f}s...")
        lat = []
        t0 = time.perf_counter()
        i = 0
        while len(lat) < n_lat_max and (time.perf_counter() - t0) < secs:
            t1 = time.perf_counter()
            sync(flagship_fn(batches[i % n_batches]))
            lat.append(time.perf_counter() - t1)
            i += 1
        lat.sort()
        n_lat = len(lat)
        if n_lat >= 8:
            p50 = lat[n_lat // 2]
            p99 = lat[min(n_lat - 1, (n_lat * 99) // 100)]
            bank(
                "latency",
                merge_latency_ms_p50=round(p50 * 1e3, 1),
                merge_latency_ms_p99=round(p99 * 1e3, 1),
                merge_latency_ms_max=round(lat[-1] * 1e3, 1),
                latency_samples=n_lat,
                latency_note=(
                    f"fetch-synced {chunk}-doc chunk merges incl. one host round "
                    f"trip (tunnel RTT ~{rtt * 1e3:.0f}ms), full trace per doc, "
                    f"{n_lat} samples"
                ),
                # full sorted series lives in the checkpoint only (the
                # emitted record carries the percentiles)
                latency_series_ms=[round(v * 1e3, 1) for v in lat],
            )
            note(
                f"latency: p50 {p50 * 1e3:.0f}ms p99 {p99 * 1e3:.0f}ms over {n_lat} samples"
            )

    # shared roofline constants (both the measured and the model phase
    # read the SAME byte model — keep them from drifting apart):
    #   ranking ring: m = 2*(pad_c+1) u32 tokens; XLA path gathers the
    #     [m, 2] row table log2(m) times from HBM (8B/row/round);
    #     pallas path loads/stores the ring once (VMEM-resident loop)
    #   placement: rank-delta scatter (C rows) + N-cumsum + one stable
    #     sort of (u32 key, i32 content) — modeled as 3 passes over
    #     8B/row (TPU sort is multi-pass; this is the documented floor)
    #   unpack/stream: content + flags ~ 10B/row read, 4B/row write
    m_ring = 2 * (pad_c + 1)
    rank_rounds = int(np.ceil(np.log2(max(m_ring, 2))))
    place_bytes = 3 * pad_n * 8 + pad_n * 14
    peak = next((v for k, v in HBM_PEAK.items() if k in str(device_kind).lower()), None)

    # ---- phase: MEASURED roofline (on-chip phase split) --------------
    # fetch-synced per-phase timings: rank-only vs full merge on one
    # chunk; placement = difference.  Combined with the byte model this
    # yields achieved HBM GB/s and a non-null hbm_frac with device
    # provenance (VERDICT r3 item 4: a measured number, not a model)
    if remaining() > 30 and os.environ.get("BENCH_SKIP_ROOFLINE") != "1":
        from loro_tpu.ops.fugue_batch import chain_rank_checksum_v

        impl = "pallas" if kernel_name == "pallas" else "xla"

        def timed(fn, reps=5):
            def fetch(o):
                np.asarray(o[0] if isinstance(o, tuple) else o)

            fetch(fn(batches[0]))
            ts = []
            for _ in range(reps):
                t1 = time.perf_counter()
                fetch(fn(batches[0]))
                ts.append(time.perf_counter() - t1)
            ts.sort()
            return ts[len(ts) // 2]

        try:
            t_rank_m = timed(lambda b: chain_rank_checksum_v(b, rank_impl=impl))
            t_full_m = timed(flagship_fn)
        except Exception as e:  # tpulint: disable=LT-EXC(roofline extra, never the headline)
            note(f"measured-roofline phase failed ({type(e).__name__}: {e})")
        else:
            t_rank_net = max(t_rank_m - rtt, 1e-4)
            t_full_net = max(t_full_m - rtt, 1e-4)
            t_place_net = max(t_full_net - t_rank_net, 1e-4)
            # the per-round HBM-gather row model only describes the xla
            # ranking path; the pallas ring rides VMEM (no per-round HBM
            # gathers), so a "measured gather rate" would be meaningless
            gather_rows_meas = (
                rank_rounds * m_ring * chunk / t_rank_net if impl == "xla" else None
            )
            ach_gbps = place_bytes * chunk / t_place_net / 1e9
            bank(
                "roofline_measured",
                rank_ms_measured=round(t_rank_net * 1e3, 1),
                place_ms_measured=round(t_place_net * 1e3, 1),
                gather_rows_per_sec_measured=(
                    round(gather_rows_meas) if gather_rows_meas is not None else None
                ),
                achieved_hbm_gbps_measured=round(ach_gbps, 1),
                hbm_frac=round(ach_gbps * 1e9 / peak, 4) if peak else None,
                roofline_measured_note=(
                    f"fetch-synced medians net of RTT on {platform}: rank-only vs "
                    "full merge per chunk; placement bytes from the documented "
                    "floor model (3 sort passes x 8B + 14B stream per row); "
                    "hbm_frac = placement-phase achieved/peak (ranking rides "
                    "VMEM on the pallas path); gather_rows_per_sec_measured vs "
                    "the ~80-100M rows/s v5e random-gather ceiling"
                ),
            )
            note(
                f"measured roofline: rank {t_rank_net*1e3:.0f}ms place "
                f"{t_place_net*1e3:.0f}ms -> {ach_gbps:.1f} GB/s"
                + (f" ({ach_gbps*1e9/peak:.1%} of peak)" if peak else "")
            )

    # ---- phase: roofline / bytes-moved accounting (model) ------------
    # (byte-model constants shared with the measured phase above)
    if kernel_name == "pallas":
        rank_bytes = 2 * m_ring * 4  # HBM load + store; rounds ride VMEM
    else:
        rank_bytes = rank_rounds * m_ring * 8
    ops_per_doc = float(np.mean(per_doc_ops))
    bytes_per_op = (rank_bytes + place_bytes) / ops_per_doc
    achieved = bytes_per_op * kernel_ops_s
    gather_rows = None
    if kernel_ops_s:
        # every ranking round gathers m rows; chunk docs per launch
        t_per_doc = 1.0 / (kernel_ops_s / ops_per_doc)
        gather_rows = rank_rounds * m_ring / t_per_doc
    bank(
        "roofline",
        ring_tokens_per_doc=m_ring,
        rank_rounds=rank_rounds,
        gather_rows_per_sec=round(gather_rows) if gather_rows else None,
        hbm_bytes_per_op_model=round(bytes_per_op, 1),
        achieved_hbm_gbps_model=round(achieved / 1e9, 1),
        hbm_frac_model=round(achieved / peak, 4) if peak else None,
        roofline_note=(
            "analytic lower-bound byte model (rank ring + placement sort floor); "
            f"{kernel_name} ranking is VMEM-resident on the pallas path, so the "
            "HBM fraction covers the streaming phases; gather_rows_per_sec is "
            "the ranking-loop row rate vs the ~80-100M random-gather rows/s "
            "HBM ceiling measured on v5e"
        ),
    )

    # ---- phase: richtext config (BASELINE config 4, banked extra) ----
    if remaining() > 75 and os.environ.get("BENCH_SKIP_RT") != "1":
        try:
            note("richtext phase (BASELINE config 4)...")
            rt_ops_s = bench_richtext(emit=False)
            note(f"richtext: {rt_ops_s / 1e6:.1f}M ops/s")
            bank(
                "richtext",
                richtext_value=round(rt_ops_s),
                richtext_unit="ops/s (concurrent marks+edits merge, correctness-gated)",
                richtext_vs_baseline=round(rt_ops_s / RUST_SINGLE_THREAD_OPS_PER_SEC, 2),
            )
        except Exception as e:  # an extra, never the headline  # tpulint: disable=LT-EXC(richtext extra, never the headline)
            note(f"richtext phase failed ({type(e).__name__}: {e})")

    # ---- phase: end-to-end ingest pipeline ---------------------------
    from loro_tpu.native import available as native_available

    if (
        native_available()
        and variants
        and not os.environ.get("BENCH_SKIP_E2E")
        and e2e_docs_req >= chunk
        and pad_c < 0xFFFF
        and remaining() > 45
    ):
        note("e2e phase: payload decode -> SoA -> upload -> merge, pipelined...")
        from concurrent.futures import ThreadPoolExecutor

        from loro_tpu.core.ids import ContainerID, ContainerType
        from loro_tpu.ops.columnar import extract_seq_from_payload
        from loro_tpu.ops.fugue_batch import (
            chain_merge_docs_packed_checksum,
            pack_chain_doc_into,
            packed_row_bytes,
        )

        cid = ContainerID.root("text", ContainerType.Text)
        payloads = [(v["payload"], v["n_ops"]) for v in variants]
        row_w = packed_row_bytes(pad_c, pad_n)

        def decode_one(i: int):
            # the native explode releases the GIL, so decode threads
            # overlap each other AND the async device merges
            pl, p_ops = payloads[i % len(payloads)]
            exd = extract_seq_from_payload(pl, cid)
            row = np.empty(row_w, np.uint8)
            pack_chain_doc_into(chain_columns(exd, pad_n=pad_n, pad_c=pad_c), row)
            return row, p_ops

        sync(
            chain_merge_docs_packed_checksum(
                jax.device_put(np.zeros((chunk, row_w), np.uint8)), pad_c, pad_n
            )
        )
        n_workers = min(8, os.cpu_count() or 1)
        e2e_docs = (min(e2e_docs_req, docs_total) // chunk) * chunk
        e2e_done = 0
        e2e_ops = 0
        out = None
        secs = min(e2e_budget_s, remaining() - 20)
        pool = ThreadPoolExecutor(max_workers=n_workers)
        try:
            t0 = time.perf_counter()
            futs = [pool.submit(decode_one, i) for i in range(min(3 * chunk, e2e_docs))]
            next_submit = len(futs)
            while e2e_done < e2e_docs and (time.perf_counter() - t0) < secs:
                group = futs[e2e_done : e2e_done + chunk]
                docs = []
                for j, f in enumerate(group):
                    c, p_ops = f.result()
                    docs.append(c)
                    e2e_ops += p_ops
                    futs[e2e_done + j] = None  # release decoded columns
                while next_submit < e2e_docs and next_submit < e2e_done + 3 * chunk:
                    futs.append(pool.submit(decode_one, next_submit))
                    next_submit += 1
                dev = jax.device_put(np.stack(docs))  # one put per chunk
                out = chain_merge_docs_packed_checksum(dev, pad_c, pad_n)  # async
                e2e_done += chunk
            if out is not None:
                sync(out)
            e2e_dt = time.perf_counter() - t0
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
        if e2e_done:
            e2e_ops_s = e2e_ops / e2e_dt
            note(
                f"e2e: {e2e_done} docs in {e2e_dt:.1f}s "
                f"({n_workers} decode threads overlapping device merges)"
            )
            bank(
                "e2e",
                e2e_value=round(e2e_ops_s),
                e2e_unit="ops/s (payload decode -> SoA -> upload -> merge)",
                e2e_vs_baseline=round(e2e_ops_s / RUST_SINGLE_THREAD_OPS_PER_SEC, 2),
                e2e_note=(
                    f"{n_workers} decode worker(s) on a {os.cpu_count()}-core host; "
                    "upload rides a network tunnel in this environment; production "
                    "co-located hosts ship over PCIe"
                ),
            )

    # ---- phase: resident-fleet ingest (host funnel, r4 verdict #5) ----
    # steady-state rows/s through DeviceDocBatch.append_payloads on a
    # FIXED synthetic fleet (seeded, 768-row epochs — the batch size at
    # which the per-epoch dispatch floor is amortized).  Mostly host
    # work, so it runs in both device and cpu_fallback modes.
    if remaining() > 40 and os.environ.get("BENCH_SKIP_RESIDENT") != "1":
        try:
            import random as _random

            from loro_tpu import LoroDoc
            from loro_tpu.doc import strip_envelope
            from loro_tpu.parallel.server import ResidentServer

            note("resident-fleet phase: 32 docs x 6 epochs x ~768 rows...")
            _rng = _random.Random(0x5E51DE17)
            _doc = LoroDoc(peer=1)
            _t = _doc.get_text("t")
            _eps = []
            for _e in range(6):
                _vv = _doc.oplog_vv()
                made = 0
                while made < 768:
                    L = len(_t)
                    if L > 8 and _rng.random() < 0.15:
                        p0 = _rng.randrange(L - 1)
                        dl = min(_rng.randint(1, 3), L - p0)
                        _t.delete(p0, dl)
                        made += dl
                    else:
                        run = _rng.randint(1, 12)
                        _t.insert(_rng.randint(0, L), "abcdefghijkl"[:run])
                        made += run
                _doc.commit()
                _eps.append(strip_envelope(_doc.export_updates(_vv)))
            import jax.numpy as _jnp

            # ResidentServer (not the bare batch): the ingest rounds
            # feed the server.epoch_seconds histogram the sidecar ships
            _srv = ResidentServer("text", 32, capacity=1 << 14)
            _cid = _doc.get_text("t").id
            _rates = []
            _rows_ep = 32 * 768
            for _e, _pl in enumerate(_eps):
                _t0 = time.perf_counter()
                _srv.ingest([_pl] * 32, _cid)
                # scalar drain fetch: block_until_ready does NOT
                # synchronize under the axon tunnel (CLAUDE.md) — the
                # async scatter must drain through a fetch or the timed
                # window excludes the device work
                np.asarray(_jnp.count_nonzero(_srv.batch.cols.valid))
                _rates.append(_rows_ep / (time.perf_counter() - _t0))
            _rates.sort()
            assert _srv.batch.texts()[0] == _t.to_string()  # correctness gate
            bank(
                "resident",
                resident_rows_per_sec=round(_rates[len(_rates) // 2]),
                resident_rows_per_sec_best=round(_rates[-1]),
                resident_note=(
                    "median per-epoch resident ingest (order maintenance + "
                    "native id maps + block scatter) on a 32-doc fleet, "
                    "768-row epochs, oracle-gated; each epoch drains the "
                    "device queue through one scalar fetch (tunnel RTT "
                    "included in the window)"
                ),
            )
            note(
                f"resident ingest: median {_rates[len(_rates)//2]/1e3:.0f}k "
                f"rows/s (best {_rates[-1]/1e3:.0f}k)"
            )

            # -- pipelined A/B (ISSUE 5 tentpole): serving-granularity
            # sync rounds (192 rows — the regime where the per-round
            # launch + drain floor dominates) through (a) serial ingest
            # and (b) PipelinedIngest (round coalescing + stage/commit
            # overlap).  INTERLEAVED blocks: serial and pipelined take
            # turns on the same round blocks, so ambient load hits both
            # paths alike (the r4 load-confounding lesson); the
            # differential gate (byte-identical batch state) makes the
            # A/B apples-to-apples by construction.
            _rng2 = _random.Random(0x5E51DE18)
            _doc2 = LoroDoc(peer=2)
            _t2 = _doc2.get_text("t")
            SYNC_ROWS, N_WARM, BLOCK, NBLK, CO = 192, 8, 16, 3, 8
            _srounds = []
            for _e in range(N_WARM + BLOCK * NBLK):
                _vv = _doc2.oplog_vv()
                made = 0
                while made < SYNC_ROWS:
                    L = len(_t2)
                    if L > 8 and _rng2.random() < 0.15:
                        p0 = _rng2.randrange(L - 1)
                        dl = min(_rng2.randint(1, 3), L - p0)
                        _t2.delete(p0, dl)
                        made += dl
                    else:
                        run = _rng2.randint(1, 12)
                        _t2.insert(_rng2.randint(0, L), "abcdefghijkl"[:run])
                        made += run
                _doc2.commit()
                _srounds.append(strip_envelope(_doc2.export_updates(_vv)))
            _cid2 = _doc2.get_text("t").id
            _rows_sync = 32 * SYNC_ROWS
            note(
                f"resident pipelined A/B: {NBLK} interleaved blocks of "
                f"{BLOCK} {SYNC_ROWS}-row sync rounds, coalesce={CO}..."
            )
            _ss = ResidentServer("text", 32, capacity=1 << 15)
            _ps = ResidentServer("text", 32, capacity=1 << 15)
            _ex = _ps.pipeline(cid=_cid2, coalesce=CO, depth=2)
            for _pl in _srounds[:N_WARM]:  # warm compiles off the clock
                _ss.ingest([_pl] * 32, _cid2)
                np.asarray(_jnp.count_nonzero(_ss.batch.cols.valid))
                _ex.submit([_pl] * 32)
            _ex.flush()
            np.asarray(_jnp.count_nonzero(_ps.batch.cols.valid))
            _sr = []
            _cr = []
            for _b in range(NBLK):
                _blk = _srounds[N_WARM + _b * BLOCK : N_WARM + (_b + 1) * BLOCK]
                for _pl in _blk:  # serial turn: per-round rates
                    _t0 = time.perf_counter()
                    _ss.ingest([_pl] * 32, _cid2)
                    np.asarray(_jnp.count_nonzero(_ss.batch.cols.valid))
                    _sr.append(_rows_sync / (time.perf_counter() - _t0))
                _t0 = time.perf_counter()  # pipelined turn: one stream
                for _pl in _blk:
                    _ex.submit([_pl] * 32)
                _ex.flush()
                np.asarray(_jnp.count_nonzero(_ps.batch.cols.valid))
                _cr.append(BLOCK * _rows_sync / (time.perf_counter() - _t0))
            _sr.sort()
            _cr.sort()
            _ser_med = _sr[len(_sr) // 2]
            _pipe_med = _cr[len(_cr) // 2]
            # differential gate: coalesced state is byte-for-byte the
            # serial state, and both match the host oracle
            assert _ps.batch.export_state() == _ss.batch.export_state(), \
                "pipelined resident state diverged from serial"
            assert _ps.batch.texts()[0] == _t2.to_string()
            bank(
                "resident_pipeline",
                resident_sync_rows_per_sec=round(_ser_med),
                resident_pipeline_rows_per_sec=round(_pipe_med),
                resident_pipeline_speedup=round(_pipe_med / _ser_med, 2),
                pipeline=_ex.report(),
                resident_pipeline_note=(
                    f"same-run INTERLEAVED A/B at serving granularity "
                    f"({SYNC_ROWS}-row sync rounds, 32-doc fleet, {NBLK} "
                    f"alternating blocks of {BLOCK}): serial = per-round "
                    f"ingest + drain fetch (median across rounds); "
                    f"pipelined = PipelinedIngest stream, coalesce={CO}, "
                    "stage/commit overlap (median across blocks); batch "
                    "state asserted byte-identical across paths, "
                    "oracle-gated"
                ),
            )
            note(
                f"resident pipelined: {_pipe_med/1e3:.0f}k rows/s vs serial "
                f"{_ser_med/1e3:.0f}k ({_pipe_med/_ser_med:.2f}x)"
            )
            if os.environ.get("BENCH_DURABLE") == "1":
                # durable sub-phase: same epochs on a smaller fleet
                # through the WAL (fsync'd per round) + one mid-run
                # checkpoint, then a reopen with bounded replay — the
                # `persist` sidecar banks the wal/fsync histograms
                import shutil as _shutil
                import tempfile as _tempfile

                from loro_tpu.persist import recover_server as _recover

                from loro_tpu.obs import metrics as _obsm

                _ddir = _tempfile.mkdtemp(prefix=".durable_bench_")
                _gdir = _tempfile.mkdtemp(prefix=".durable_group_")
                try:
                    _fs = _obsm.counter("persist.wal_fsyncs_total")
                    # the A/B counts INGEST-path fsyncs: the checkpoint
                    # call's control-record syncs (marker/rotation/meta/
                    # prune) are identical in both modes and excluded
                    _n_pr0 = _fs.get(mode="per_round")
                    _ck_pr = 0.0
                    # auto_checkpoint off: its mid-ingest control
                    # syncs would blur the ingest-path fsync count (the
                    # explicit mid-run checkpoint covers the ladder)
                    _dsrv = ResidentServer(
                        "text", 8, capacity=1 << 14, durable_dir=_ddir,
                        auto_checkpoint=False,
                    )
                    _d0 = time.perf_counter()
                    for _e, _pl in enumerate(_eps):
                        _dsrv.ingest([_pl] * 8, _cid)
                        if _e == len(_eps) // 2:
                            _c0 = _fs.get(mode="per_round")
                            _dsrv.checkpoint()
                            _ck_pr = _fs.get(mode="per_round") - _c0
                    np.asarray(_jnp.count_nonzero(_dsrv.batch.cols.valid))
                    _dsec = time.perf_counter() - _d0
                    _dsrv.close()
                    _n_pr = _fs.get(mode="per_round") - _n_pr0 - _ck_pr
                    _rec = _recover(_ddir)
                    assert _rec.batch.texts()[0] == _t.to_string()
                    _rec.close()
                    # group-commit A/B: same rounds + checkpoint through
                    # durable_fsync="group" (fsync_window=4) — equal
                    # round count, a fraction of the fsyncs
                    _n_gr0 = _fs.get(mode="group")
                    _ck_gr = 0.0
                    _gsrv = ResidentServer(
                        "text", 8, capacity=1 << 14, durable_dir=_gdir,
                        durable_fsync="group", fsync_window=4,
                        auto_checkpoint=False,
                    )
                    _g0 = time.perf_counter()
                    for _e, _pl in enumerate(_eps):
                        _gsrv.ingest([_pl] * 8, _cid)
                        if _e == len(_eps) // 2:
                            _c0 = _fs.get(mode="group")
                            _gsrv.checkpoint()
                            _ck_gr = _fs.get(mode="group") - _c0
                    np.asarray(_jnp.count_nonzero(_gsrv.batch.cols.valid))
                    _gsec = time.perf_counter() - _g0
                    _gsrv.close()
                    _n_gr = _fs.get(mode="group") - _n_gr0 - _ck_gr
                    _grec = _recover(_gdir)
                    assert _grec.batch.texts()[0] == _t.to_string()
                    assert _grec.epoch >= _gsrv.durable_epoch
                    _grec.close()
                    bank(
                        "resident_durable",
                        resident_durable_rows_per_sec=round(
                            8 * 768 * len(_eps) / _dsec
                        ),
                        resident_durable_replayed_rounds=(
                            _rec.last_recovery.rounds_replayed
                        ),
                        resident_durable_fsyncs=round(_n_pr),
                        resident_durable_group_fsyncs=round(_n_gr),
                        resident_durable_group_rows_per_sec=round(
                            8 * 768 * len(_eps) / _gsec
                        ),
                        resident_durable_note=(
                            "resident ingest with durable_dir, then "
                            "recover_server reopen gated on the oracle — "
                            "A/B at equal round count: per-round WAL fsync "
                            f"({round(_n_pr)} ingest-path fsyncs) vs "
                            "durable_fsync='group' fsync_window=4 "
                            f"({round(_n_gr)} ingest-path fsyncs, "
                            "acked-epoch watermark honored across the "
                            "reopen); checkpoint-driven control-record "
                            "syncs are identical in both modes and "
                            "excluded; the persist.* entries of the "
                            "metrics sidecar carry the wal/fsync "
                            "histograms"
                        ),
                    )
                    note(
                        f"durable resident ingest: {8*768*len(_eps)/_dsec/1e3:.0f}k "
                        f"rows/s, {round(_n_pr)} fsyncs; group commit "
                        f"{8*768*len(_eps)/_gsec/1e3:.0f}k rows/s, "
                        f"{round(_n_gr)} fsyncs; reopen replayed "
                        f"{_rec.last_recovery.rounds_replayed} rounds"
                    )
                finally:
                    _shutil.rmtree(_ddir, ignore_errors=True)
                    _shutil.rmtree(_gdir, ignore_errors=True)
        except Exception as e:  # tpulint: disable=LT-EXC(resident extra, never the headline)
            note(f"resident phase failed ({type(e).__name__}: {e})")

    # ---- phase: sync front-end (BENCH_SYNC=1, ISSUE 7) ----------------
    # the repo's first end-to-end many-writers-many-readers benchmark:
    # concurrent sessions push client update blobs through the SyncServer
    # fan-in (batched into pipelined resident groups), committed epochs
    # fan out, readers pull deltas.  Banks sessions, pushes/sec and
    # p50/p99 push-to-visible latency into the `sync` sidecar.
    if remaining() > 30 and os.environ.get("BENCH_SYNC") == "1":
        try:
            import random as _random

            from loro_tpu import LoroDoc
            from loro_tpu.obs import metrics as _obsm
            from loro_tpu.sync import SyncServer

            S_DOCS, S_WRITERS, S_EPOCHS = 8, 2, 6
            n_sess = S_DOCS * S_WRITERS
            note(
                f"sync phase: {n_sess} writer sessions x {S_DOCS} docs x "
                f"{S_EPOCHS} epochs through the fan-in..."
            )
            _rng3 = _random.Random(0x5E51DE19)
            _clients = []  # [doc][writer] replicas
            for i in range(S_DOCS):
                b = LoroDoc(peer=3000 + 10 * i)
                b.get_text("t").insert(0, f"sync bench base {i}")
                b.commit()
                reps = [b]
                for w in range(1, S_WRITERS):
                    r = LoroDoc(peer=3000 + 10 * i + w)
                    r.import_(b.export_snapshot())
                    reps.append(r)
                _clients.append(reps)
            _scid = _clients[0][0].get_text("t").id
            _ssrv = SyncServer(
                "text", S_DOCS, cid=_scid, capacity=1 << 14,
                coalesce=8, max_queue=128,
            )
            _sess = [[_ssrv.connect(sid=f"d{i}w{w}")
                      for w in range(S_WRITERS)] for i in range(S_DOCS)]
            _smarks = [[{} for _ in range(S_WRITERS)]
                       for _ in range(S_DOCS)]
            _boot = []
            for i in range(S_DOCS):
                _boot.append(_sess[i][0].push(
                    i, _clients[i][0].export_updates({})
                ))
                _smarks[i][0] = _clients[i][0].oplog_vv()
                for w in range(1, S_WRITERS):
                    _sess[i][w]._vv[i] = _clients[i][w].oplog_vv()
                    _smarks[i][w] = _clients[i][w].oplog_vv()
            for _tk in _boot:
                _tk.epoch(120)
            _p2v = _obsm.histogram("sync.push_to_visible_seconds")
            _pushes = 0
            _s0 = time.perf_counter()
            for _e in range(S_EPOCHS):
                _tks = []
                for i in range(S_DOCS):
                    for w in range(S_WRITERS):
                        d = _clients[i][w]
                        t = d.get_text("t")
                        made = 0
                        while made < 96:
                            L = len(t)
                            if L > 8 and _rng3.random() < 0.15:
                                p0 = _rng3.randrange(L - 1)
                                dl = min(_rng3.randint(1, 3), L - p0)
                                t.delete(p0, dl)
                                made += dl
                            else:
                                run = _rng3.randint(1, 12)
                                t.insert(_rng3.randint(0, L),
                                         "abcdefghijkl"[:run])
                                made += run
                        d.commit()
                        _tks.append(_sess[i][w].push(
                            i, d.export_updates(_smarks[i][w])
                        ))
                        _smarks[i][w] = d.oplog_vv()
                        _pushes += 1
                for _tk in _tks:
                    _tk.epoch(120)
                # the many-readers half: every session pulls the delta
                # and integrates it (cross-writer merge)
                for i in range(S_DOCS):
                    for w in range(S_WRITERS):
                        _clients[i][w].import_(_sess[i][w].pull(i))
                        _smarks[i][w] = _clients[i][w].oplog_vv()
            _ssec = time.perf_counter() - _s0
            _ssrv.flush()
            # convergence gate: replicas agree and match the resident
            _stexts = _ssrv.texts()
            for i in range(S_DOCS):
                want = _clients[i][0].get_text("t").to_string()
                assert _clients[i][1].get_text("t").to_string() == want
                assert _stexts[i] == want, f"sync bench doc {i} diverged"
            _p50 = _p2v.quantile(0.50) or 0.0
            _p99 = _p2v.quantile(0.99) or 0.0
            _pull_b = _obsm.histogram("sync.pull_bytes").summary()
            _srep = _ssrv.report()
            _srep.update(
                docs=S_DOCS, epochs=S_EPOCHS,
                push_to_visible_ms_p50=round(_p50 * 1e3, 2),
                push_to_visible_ms_p99=round(_p99 * 1e3, 2),
                pull_bytes_mean=round(_pull_b["mean"], 1),
                pulls=_pull_b["count"],
                note=(
                    "many-writers-many-readers: 2 writer sessions per doc "
                    "push ~96-row client deltas through the bounded fan-in "
                    "(pipelined resident groups), every session pulls + "
                    "integrates per epoch; p50/p99 = push submit -> "
                    "committed + oracle-visible; convergence gated vs the "
                    "resident reads"
                ),
            )
            _ssrv.close()
            # trace sidecar (ISSUE 14): the stage decomposition of the
            # push-to-visible headline — per-stage mean ms (the stages
            # telescope, so their means sum to the p2v mean over the
            # same tickets), one exemplar trace id per stage, and the
            # flight ring state
            from loro_tpu.obs import flight as _flight

            _stage_h = _obsm.histogram("trace.push_stage_seconds")
            _tstages = {}
            for _row in _stage_h.snapshot()["values"]:
                _stg = _row["labels"].get("stage")
                if _stg is None:
                    continue
                _n = _row["count"]
                _ent = _tstages.setdefault(
                    _stg, {"count": 0, "sum_ms": 0.0}
                )
                _ent["count"] += _n
                _ent["sum_ms"] += _row["sum"] * 1e3
                _ex = _row.get("exemplars") or {}
                if _ex:
                    _ent["exemplar"] = list(_ex.values())[-1]
            for _ent in _tstages.values():
                _ent["mean_ms"] = round(
                    _ent.pop("sum_ms") / max(_ent["count"], 1), 3
                )
            _trace_side = {
                "stages": _tstages,
                "stage_sum_mean_ms": round(
                    sum(s["mean_ms"] for s in _tstages.values()), 3
                ),
                "p2v_mean_ms": round(_p2v.summary()["mean"] * 1e3, 3),
                "flight_recorded": _flight.recorder().recorded_total,
                "flight_capacity": _flight.recorder().capacity,
                "note": (
                    "per-stage push latency attribution "
                    "(trace.push_stage_seconds): queue_wait -> "
                    "coalesce_wait -> stage -> commit -> fsync -> "
                    "fanout telescope to push-to-visible; exemplar = "
                    "a trace id that landed in the stage's slowest "
                    "populated bucket"
                ),
            }
            bank(
                "sync",
                sync_sessions=n_sess,
                sync_pushes_per_sec=round(_pushes / _ssec, 1),
                sync_push_to_visible_ms_p50=round(_p50 * 1e3, 2),
                sync_push_to_visible_ms_p99=round(_p99 * 1e3, 2),
                sync=_srep,
                trace=_trace_side,
            )
            note(
                f"sync: {n_sess} sessions, {_pushes/_ssec:.0f} pushes/s, "
                f"push-to-visible p50 {_p50*1e3:.1f}ms p99 {_p99*1e3:.1f}ms"
            )
        except Exception as e:  # tpulint: disable=LT-EXC(sync extra, never the headline)
            note(f"sync phase failed ({type(e).__name__}: {e})")

    # ---- phase: batched read plane (BENCH_SYNC_READERS=N, ISSUE 11) ---
    # reader-heavy serving A/B: N concurrent reader sessions pull every
    # epoch from two identically-fed text SyncServers — one with the
    # batched device read plane (concurrent pulls coalesce into one
    # export launch per window, identical frames shared), one pinned to
    # the per-doc host oracle (read_batch=False).  Banks the
    # sync_pulls_per_sec flagship pair + p50/p99 pull latency + the
    # `readplane` sidecar, and asserts the count guard: one export
    # launch per coalesced window.  CPU-mesh numbers in CI; chip
    # numbers pending pool return (probe-compile the select shapes in
    # a disposable run first, per CLAUDE.md).
    if remaining() > 30 and os.environ.get("BENCH_SYNC_READERS"):
        try:
            import random as _random
            from concurrent.futures import ThreadPoolExecutor as _TPE

            from loro_tpu import LoroDoc
            from loro_tpu.sync import SyncServer

            n_readers = int(os.environ["BENCH_SYNC_READERS"])
            R_DOCS, R_EPOCHS, R_EDITS = 4, 6, 192
            note(
                f"read-plane phase: {n_readers} readers x {R_DOCS} docs x "
                f"{R_EPOCHS} epochs, batched-device vs host-oracle..."
            )
            _rng4 = _random.Random(0x4EADB10C)
            _wdocs = []
            for i in range(R_DOCS):
                b = LoroDoc(peer=4000 + i)
                b.get_text("t").insert(0, f"read plane base {i}")
                b.commit()
                _wdocs.append(b)
            _rcid = _wdocs[0].get_text("t").id
            _arms = ("device", "oracle")
            _rsrv = {
                "device": SyncServer("text", R_DOCS, cid=_rcid,
                                     capacity=1 << 14, max_queue=128),
                "oracle": SyncServer("text", R_DOCS, cid=_rcid,
                                     capacity=1 << 14, max_queue=128,
                                     read_batch=False),
            }
            _wsess = {a: [_rsrv[a].connect(sid=f"w{i}")
                          for i in range(R_DOCS)] for a in _arms}
            _marks = [{} for _ in range(R_DOCS)]
            _boot = []
            for i in range(R_DOCS):
                pl = _wdocs[i].export_updates({})
                for a in _arms:
                    _boot.append(_wsess[a][i].push(i, pl))
                _marks[i] = _wdocs[i].oplog_vv()
            for _tk in _boot:
                _tk.epoch(120)
            _rdrs = {a: [_rsrv[a].connect(sid=f"r{k}")
                         for k in range(n_readers)] for a in _arms}
            # persistent reader pools (thread SPAWN cost is common-mode
            # noise that would swamp the serving difference) + a warm
            # round excluded from timing that seeds the reader
            # frontiers (steady-state serving is the thing being
            # measured).  The SERIAL seeding pulls ride the device but
            # only ever form size-1 windows — the 16/32/64 request
            # buckets and the dirty-doc scatter delta stay cold — so
            # warm_read_plane pre-compiles those shapes, or the first
            # timed epoch banks a multi-hundred-ms XLA compile as
            # serving latency
            _pools = {a: _TPE(max_workers=n_readers) for a in _arms}
            for a in _arms:
                for k in range(n_readers):
                    _rdrs[a][k].pull(k % R_DOCS)
            _rsrv["device"].warm_read_plane(n_readers)
            _lat = {a: [] for a in _arms}
            _wall = {a: 0.0 for a in _arms}
            _pull_n = {a: 0 for a in _arms}

            def _mk_pull(a):
                sess, lats = _rdrs[a], _lat[a]

                def _pull_one(k):
                    t0p = time.perf_counter()
                    sess[k].pull(k % R_DOCS)
                    lats.append(time.perf_counter() - t0p)
                return _pull_one

            for _e in range(R_EPOCHS):
                _tks = []
                for i in range(R_DOCS):
                    d = _wdocs[i]
                    t = d.get_text("t")
                    for _ in range(R_EDITS):
                        L = len(t)
                        t.insert(_rng4.randint(0, L), "abcdef"[:_rng4.randint(1, 6)])
                    d.commit()
                    pl = d.export_updates(_marks[i])
                    for a in _arms:
                        _tks.append(_wsess[a][i].push(i, pl))
                    _marks[i] = d.oplog_vv()
                for _tk in _tks:
                    _tk.epoch(120)
                # interleave arm order per epoch (decorrelate ambient load)
                for a in (_arms if _e % 2 == 0 else _arms[::-1]):
                    _fn = _mk_pull(a)
                    _t0a = time.perf_counter()
                    list(_pools[a].map(_fn, range(n_readers)))
                    _wall[a] += time.perf_counter() - _t0a
                    _pull_n[a] += n_readers
            # convergence + count guard
            _dt = _rsrv["device"].texts()
            _ot = _rsrv["oracle"].texts()
            assert _dt == _ot, "read-plane A/B servers diverged"
            _rbrep = _rsrv["device"].report()["readbatch"]
            assert _rbrep["launches"] <= _rbrep["windows"] <= _rbrep["pulls"], \
                "count guard: at most one export launch per pull window"
            if n_readers >= 8:
                # coalescing must actually bite at reader-storm sizes
                # (a solo reader legitimately gets one window per pull)
                assert _rbrep["windows"] < _rbrep["pulls"], \
                    "count guard: windows did not coalesce concurrent pulls"
            def _pctl(xs, q):
                xs = sorted(xs)
                return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0
            _dev_ps = _pull_n["device"] / max(_wall["device"], 1e-9)
            _ora_ps = _pull_n["oracle"] / max(_wall["oracle"], 1e-9)
            _side = {
                "readers": n_readers,
                "docs": R_DOCS,
                "epochs": R_EPOCHS,
                "device_pulls_per_sec": round(_dev_ps, 1),
                "oracle_pulls_per_sec": round(_ora_ps, 1),
                "oracle_pull_ms_p50": round(_pctl(_lat["oracle"], 0.50) * 1e3, 2),
                "oracle_pull_ms_p99": round(_pctl(_lat["oracle"], 0.99) * 1e3, 2),
                "readbatch": _rbrep,
                "note": (
                    "N concurrent reader sessions pull per epoch against "
                    "identically-fed servers; device = batched read plane "
                    "(window coalescing + shared frames, one selection "
                    "launch per window), oracle = per-doc host LoroDoc "
                    "exports under the server lock; pulls/s over the "
                    "concurrent-pull wall time, arm order interleaved"
                ),
            }
            for a in _arms:
                _pools[a].shutdown()
                _rsrv[a].close()
            bank(
                "readplane",
                sync_readers=n_readers,
                sync_pulls_per_sec=round(_dev_ps, 1),
                sync_pulls_per_sec_oracle=round(_ora_ps, 1),
                sync_read_speedup=round(_dev_ps / max(_ora_ps, 1e-9), 2),
                sync_pull_ms_p50=round(_pctl(_lat["device"], 0.50) * 1e3, 2),
                sync_pull_ms_p99=round(_pctl(_lat["device"], 0.99) * 1e3, 2),
                readplane=_side,
            )
            note(
                f"read plane: {n_readers} readers, device {_dev_ps:.0f} "
                f"pulls/s vs oracle {_ora_ps:.0f} pulls/s "
                f"({_dev_ps / max(_ora_ps, 1e-9):.2f}x), "
                f"{_rbrep['windows']} windows / {_rbrep['launches']} launches"
            )
        except Exception as e:  # tpulint: disable=LT-EXC(read-plane extra, never the headline)
            note(f"read-plane phase failed ({type(e).__name__}: {e})")

    # ---- phase: network edge (BENCH_NET=1|N, ISSUE 16) ----------------
    # the socket-fronted serving shape: N real TCP connections (one
    # NetClient + replica LoroDoc thread each) push columnar deltas
    # through the asyncio NetServer into the SyncServer fan-in, block
    # on PUSH_ACK (sent only after commit, carrying the durable
    # watermark + trace id) and pull + integrate the cross-client
    # delta.  Banks connections, pushes/s over the wire and the
    # CLIENT-observed p50/p99 push-to-ack latency — a strict superset
    # of push-to-visible whose net.ack/net.send stage marks telescope
    # into the trace.push_stage_seconds breakdown.  Convergence is
    # gated: after a full-fleet barrier every replica's final pull
    # must land it byte-equal to the resident read.  BENCH_NET=N>1
    # sets the connection count (default 64).
    if remaining() > 30 and os.environ.get("BENCH_NET"):
        try:
            import random as _random
            import threading as _threading

            from loro_tpu import LoroDoc
            from loro_tpu.net import NetClient, NetServer
            from loro_tpu.obs import metrics as _obsm
            from loro_tpu.sync import SyncServer

            _nn = int(os.environ["BENCH_NET"])
            n_conns = _nn if _nn > 1 else 64
            N_DOCS, N_EPOCHS, N_EDITS = 8, 4, 48
            note(
                f"net phase: {n_conns} socket connections x {N_DOCS} "
                f"docs x {N_EPOCHS} epochs through the TCP edge..."
            )
            _nbases = []
            for i in range(N_DOCS):
                b = LoroDoc(peer=6000 + i)
                b.get_text("t").insert(0, f"net bench base {i}")
                b.commit()
                _nbases.append(b)
            _ncid = _nbases[0].get_text("t").id
            _nsrv = SyncServer("text", N_DOCS, cid=_ncid,
                               capacity=1 << 14, coalesce=8,
                               max_queue=256)
            _nseed = _nsrv.connect(sid="net-seed")
            _nboot = [_nseed.push(i, _nbases[i].export_updates({}))
                      for i in range(N_DOCS)]
            for _tk in _nboot:
                _tk.epoch(120)
            _nsrv.warm_read_plane(min(n_conns, 64))
            _net = NetServer(_nsrv, max_connections=n_conns + 8)
            _nlat = [[] for _ in range(n_conns)]
            _npush = [0] * n_conns
            _ntend = [0.0] * n_conns
            _nfinal = [None] * n_conns
            _nerrs = []
            _go = _threading.Barrier(n_conns + 1)
            _acked = _threading.Barrier(n_conns)

            def _conn_worker(k):
                rng = _random.Random(0x0E7B000 + k)
                di = k % N_DOCS
                d = LoroDoc(peer=6100 + k)
                d.import_(_nbases[di].export_snapshot())
                cli = NetClient("127.0.0.1", _net.port, "text",
                                client_id=f"bench-{k}", timeout=120.0)
                try:
                    cli.connect()
                    d.import_(cli.pull(di))  # seed the wire frontier
                    _go.wait(120)
                    mark = d.oplog_vv()
                    for _e in range(N_EPOCHS):
                        t = d.get_text("t")
                        for _ in range(N_EDITS):
                            L = len(t)
                            t.insert(rng.randint(0, L),
                                     "abcdef"[:rng.randint(1, 6)])
                        d.commit()
                        pl = d.export_updates(mark)
                        t0p = time.perf_counter()
                        cli.push(di, pl)
                        _nlat[k].append(time.perf_counter() - t0p)
                        _npush[k] += 1
                        mark = d.oplog_vv()
                        d.import_(cli.pull(di))
                        mark = d.oplog_vv()
                    _ntend[k] = time.perf_counter()
                    # every connection's pushes are acked past here, so
                    # one more pull sees the whole fleet's ops
                    _acked.wait(300)
                    d.import_(cli.pull(di))
                    _nfinal[k] = d.get_text("t").to_string()
                except Exception as e:  # tpulint: disable=LT-EXC(worker failure is re-raised by the phase after join)
                    _nerrs.append(e)
                    _go.abort()
                    _acked.abort()
                finally:
                    cli.close()

            _nthreads = [
                _threading.Thread(target=_conn_worker, args=(k,),
                                  name=f"bench-net-{k}", daemon=True)
                for k in range(n_conns)
            ]
            for _t in _nthreads:
                _t.start()
            _go.wait(120)
            _nt0 = time.perf_counter()
            for _t in _nthreads:
                _t.join(600)
            if _nerrs:
                raise _nerrs[0]
            _nwall = max(_ntend) - _nt0
            _nsrv.flush()
            _ntexts = _nsrv.texts()
            for k in range(n_conns):
                assert _nfinal[k] == _ntexts[k % N_DOCS], \
                    f"net bench conn {k} diverged from the resident read"
            _nall = sorted(x for xs in _nlat for x in xs)

            def _npctl(q):
                return (_nall[min(len(_nall) - 1, int(q * len(_nall)))]
                        if _nall else 0.0)

            _ntotal = sum(_npush)
            _nps = _ntotal / max(_nwall, 1e-9)
            _np50, _np99 = _npctl(0.50), _npctl(0.99)
            # server-side attribution: the socket stages ride the same
            # trace.push_stage_seconds histogram as the fan-in stages
            _nstage_h = _obsm.histogram("trace.push_stage_seconds")
            _nstages = {}
            for _row in _nstage_h.snapshot()["values"]:
                _stg = _row["labels"].get("stage")
                if not (_stg or "").startswith("net."):
                    continue
                _ent = _nstages.setdefault(
                    _stg, {"count": 0, "sum_ms": 0.0})
                _ent["count"] += _row["count"]
                _ent["sum_ms"] += _row["sum"] * 1e3
            for _ent in _nstages.values():
                _ent["mean_ms"] = round(
                    _ent.pop("sum_ms") / max(_ent["count"], 1), 3)
            _nack = _obsm.histogram("net.push_to_ack_seconds")
            _nrep = _net.report()
            _net.close()
            _nsrv.close()
            _nside = {
                "connections": n_conns,
                "docs": N_DOCS,
                "epochs": N_EPOCHS,
                "pushes": _ntotal,
                "pushes_per_sec": round(_nps, 1),
                "push_to_ack_ms_p50_server": round(
                    (_nack.quantile(0.50) or 0.0) * 1e3, 2),
                "push_to_ack_ms_p99_server": round(
                    (_nack.quantile(0.99) or 0.0) * 1e3, 2),
                "net_stages": _nstages,
                "server": _nrep,
                "note": (
                    "N threads each own a REAL TCP connection + replica "
                    "doc; per epoch they push a columnar delta, block on "
                    "PUSH_ACK (commit + durable watermark ride the ack) "
                    "and pull-integrate; p50/p99 = client-side push "
                    "submit -> ack receipt over the socket; net.ack/"
                    "net.send stage marks telescope into the push "
                    "breakdown; convergence gated vs the resident read "
                    "after a full-fleet ack barrier"
                ),
            }
            bank(
                "net",
                net_connections=n_conns,
                net_pushes_per_sec=round(_nps, 1),
                net_push_to_visible_ms_p50=round(_np50 * 1e3, 2),
                net_push_to_visible_ms_p99=round(_np99 * 1e3, 2),
                net=_nside,
            )
            note(
                f"net: {n_conns} connections, {_nps:.0f} pushes/s, "
                f"push-to-ack p50 {_np50*1e3:.1f}ms p99 {_np99*1e3:.1f}ms"
            )
        except Exception as e:  # tpulint: disable=LT-EXC(net extra, never the headline)
            note(f"net phase failed ({type(e).__name__}: {e})")

    # ---- phase: WAL-shipping replication (BENCH_REPL=1|N, ISSUE 12) ---
    # read scale-OUT, measured in the deployment shape: leader A serves
    # ALL N readers alone (the single-leader line); leader B ships its
    # WAL to a follower in a SEPARATE PROCESS (.visible-marker tail
    # visibility, own GIL/core/read plane) and the same N readers split
    # N/2 in-process on B + N/2 in the follower child, both halves
    # serving CONCURRENTLY.  Both leaders are fed identical pushes.
    # Banks aggregate repl_pulls_per_sec vs the single-leader line, the
    # cross-process push-to-follower-visible lag, and the promotion
    # downtime (leader retired -> first durable write on the promoted
    # follower).  BENCH_REPL=N>1 sets the reader count (default 32).
    if remaining() > 60 and os.environ.get("BENCH_REPL"):
        _rctl = None
        _rproc = None
        try:
            import random as _random
            import subprocess as _subprocess
            import tempfile as _tempfile
            from concurrent.futures import ThreadPoolExecutor as _TPE

            from loro_tpu import LoroDoc, replication
            from loro_tpu.replication import Follower
            from loro_tpu.sync import SyncServer

            _rn = int(os.environ["BENCH_REPL"])
            n_readers = _rn if _rn > 1 else 32
            _half = n_readers // 2
            P_DOCS, P_EPOCHS, P_EDITS = 4, 6, 128
            note(
                f"replication phase: {n_readers} readers x {P_DOCS} docs "
                f"x {P_EPOCHS} epochs, single leader vs leader + "
                "cross-process follower..."
            )
            _rng5 = _random.Random(0x4EB11CA)
            _rctl = _tempfile.mkdtemp(prefix="bench_repl_")
            _pdocs = []
            for i in range(P_DOCS):
                b = LoroDoc(peer=5000 + i)
                b.get_text("t").insert(0, f"repl base {i}")
                b.commit()
                _pdocs.append(b)
            _pcid = _pdocs[0].get_text("t").id

            def _mk_lead(tag):
                return SyncServer(
                    "text", P_DOCS, cid=_pcid, capacity=1 << 14,
                    max_queue=128, durable_dir=os.path.join(_rctl, tag),
                    durable_fsync="group", fsync_window=8,
                )

            _leadA, _leadB = _mk_lead("A"), _mk_lead("B")
            replication.enable(_leadB.resident, "bench-leader")
            _pwA = [_leadA.connect(sid=f"w{i}") for i in range(P_DOCS)]
            _pwB = [_leadB.connect(sid=f"w{i}") for i in range(P_DOCS)]
            _pmarks = [{} for _ in range(P_DOCS)]
            _boot = []
            for i in range(P_DOCS):
                pl = _pdocs[i].export_updates({})
                _boot += [_pwA[i].push(i, pl), _pwB[i].push(i, pl)]
                _pmarks[i] = _pdocs[i].oplog_vv()
            for _tk in _boot:
                _tk.epoch(120)
            for _s in (_leadA, _leadB):
                _s.flush()
                _s.resident.flush_durable()
            # spawn the follower child over leader B's directory (its
            # jax import runs while we warm the parent-side planes)
            with open(os.path.join(_rctl, "child.cfg"), "w") as f:
                json.dump({
                    "leader_dir": os.path.join(_rctl, "B"),
                    "follower_dir": os.path.join(_rctl, "F"),
                    "readers": n_readers - _half, "docs": P_DOCS,
                    "epochs": P_EPOCHS,
                }, f)
            _renv = dict(os.environ)
            _renv["BENCH_REPL_CHILD"] = _rctl
            _renv.pop("BENCH_CHECKPOINT", None)
            with open(os.path.join(_rctl, "child.log"), "ab") as _clog:
                _rproc = _subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__)],
                    env=_renv, stdout=_clog, stderr=_clog,
                    start_new_session=True,
                )
            _solo = [_leadA.connect(sid=f"s{k}") for k in range(n_readers)]
            _aggL = [_leadB.connect(sid=f"bl{k}") for k in range(_half)]
            for k, s in enumerate(_solo):
                s.pull(k % P_DOCS)
            for k, s in enumerate(_aggL):
                s.pull(k % P_DOCS)
            _leadA.warm_read_plane(n_readers)
            _leadB.warm_read_plane(n_readers)

            def _wait_file(path, deadline_s, what):
                t0w = time.time()
                while not os.path.exists(path):
                    err = os.path.join(_rctl, "child.err")
                    if os.path.exists(err):
                        with open(err) as f:
                            raise RuntimeError(
                                f"repl child failed: {f.read()[:500]}"
                            )
                    if _rproc.poll() is not None:
                        raise RuntimeError(
                            f"repl child exited rc={_rproc.returncode} "
                            f"before {what}"
                        )
                    if time.time() - t0w > deadline_s:
                        raise RuntimeError(f"repl child: {what} timed out")
                    time.sleep(0.005)

            _wait_file(os.path.join(_rctl, "child.ready"), 180,
                       "bootstrap")
            _pool = _TPE(max_workers=n_readers)
            _wall = {"solo": 0.0, "agg": 0.0}
            _pulls = {"solo": 0, "agg": 0}
            _lags = []

            def _pull_solo(k):
                _solo[k].pull(k % P_DOCS)

            def _pull_aggL(k):
                _aggL[k].pull(k % P_DOCS)

            # epoch 0 is an UNTIMED warm epoch: the child's replay path
            # jit-compiles its real payload shapes on the first shipped
            # round, which would otherwise bank one ~300ms compile as
            # serving lag (the read-plane warm lesson, PR 11)
            _timed = {"on": False}
            for _e in range(P_EPOCHS):
                _tks = []
                for i in range(P_DOCS):
                    d = _pdocs[i]
                    t = d.get_text("t")
                    for _ in range(P_EDITS):
                        L = len(t)
                        t.insert(_rng5.randint(0, L),
                                 "abcdef"[:_rng5.randint(1, 6)])
                    d.commit()
                    pl = d.export_updates(_pmarks[i])
                    _tks += [_pwA[i].push(i, pl), _pwB[i].push(i, pl)]
                    _pmarks[i] = d.oplog_vv()
                for _tk in _tks:
                    _tk.epoch(120)
                for _s in (_leadA, _leadB):
                    _s.flush()
                    _s.resident.flush_durable()  # publishes .visible

                def _run_agg():
                    # child goes first (its catch_up overlaps nothing
                    # timed), then the parent half serves concurrently
                    # with the child's half
                    _gop = os.path.join(_rctl, f"e{_e}.go")
                    with open(_gop + ".tmp", "w") as f:
                        json.dump({"epoch": _leadB.resident.epoch}, f)
                    os.replace(_gop + ".tmp", _gop)  # atomic: child polls
                    _t0a = time.perf_counter()
                    list(_pool.map(_pull_aggL, range(_half)))
                    _pwall = time.perf_counter() - _t0a
                    _wait_file(os.path.join(_rctl, f"e{_e}.done"), 90,
                               f"epoch {_e}")
                    with open(os.path.join(_rctl, "child.out")) as f:
                        rec = json.loads(f.read().splitlines()[_e])
                    if _timed["on"]:
                        _lags.append(rec["lag_s"] * 1e3)
                        _wall["agg"] += max(_pwall, rec["pull_wall_s"])
                        _pulls["agg"] += n_readers

                def _run_solo():
                    _t0a = time.perf_counter()
                    list(_pool.map(_pull_solo, range(n_readers)))
                    if _timed["on"]:
                        _wall["solo"] += time.perf_counter() - _t0a
                        _pulls["solo"] += n_readers

                for _arm in (("solo", "agg") if _e % 2 == 0
                             else ("agg", "solo")):
                    (_run_solo if _arm == "solo" else _run_agg)()
                _timed["on"] = True
            _wait_file(os.path.join(_rctl, "child.final"), 60,
                       "final state")
            with open(os.path.join(_rctl, "child.final")) as f:
                _cfinal = json.load(f)
            _rproc.wait(timeout=60)
            _rproc = None
            assert _cfinal["texts"] == _leadB.resident.texts() \
                == _leadA.resident.texts(), \
                "replication A/B: follower diverged from the leaders"
            # promotion downtime: a second (in-process) follower takes
            # over leader B — retire -> first durable write accepted
            _fol2 = Follower(os.path.join(_rctl, "B"),
                             os.path.join(_rctl, "F2"),
                             leader=_leadB.resident)
            _fol2.catch_up()
            _t0p = time.perf_counter()
            _leadB.close()
            _prom = _fol2.promote("bench-survivor")
            _wd = _pdocs[0]
            _wt = _wd.get_text("t")
            _wt.insert(0, "post-promotion ")
            _wd.commit()
            _ws = _fol2.sync.connect()
            _ws.push(0, _wd.export_updates(_pmarks[0])).epoch(120)
            _down_ms = (time.perf_counter() - _t0p) * 1e3
            assert _prom.texts()[0] == _wt.to_string(), \
                "post-promotion push did not land"
            _fol2.close()
            _leadA.close()
            _pool.shutdown()

            def _pctl5(xs, q):
                xs = sorted(xs)
                return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0
            _solo_ps = _pulls["solo"] / max(_wall["solo"], 1e-9)
            _agg_ps = _pulls["agg"] / max(_wall["agg"], 1e-9)
            _side = {
                "readers": n_readers,
                "docs": P_DOCS,
                "epochs": P_EPOCHS,
                "warm_epochs": 1,
                "leader_pulls_per_sec": round(_solo_ps, 1),
                "aggregate_pulls_per_sec": round(_agg_ps, 1),
                "lag_ms_p50": round(_pctl5(_lags, 0.50), 2),
                "lag_ms_p99": round(_pctl5(_lags, 0.99), 2),
                "promotion_downtime_ms": round(_down_ms, 1),
                "follower": _cfinal.get("report"),
                "note": (
                    "two identically-fed durable group-commit text "
                    "leaders: A serves all N readers (single-leader "
                    "line); B ships WAL to a follower in a separate "
                    "process (.visible marker tail) and N/2 in-process "
                    "+ N/2 follower-process readers serve concurrently "
                    "— per-epoch agg wall = max(parent half, follower "
                    "half); lag = cross-process marker catch_up to the "
                    "pushed epoch; epoch 0 is an untimed warm epoch "
                    "(child replay-path compile); downtime = leader "
                    "close -> promoted follower's first durable write"
                ),
            }
            bank(
                "repl",
                repl_readers=n_readers,
                repl_pulls_per_sec=round(_agg_ps, 1),
                repl_pulls_per_sec_leader_only=round(_solo_ps, 1),
                repl_read_scaling_x=round(_agg_ps / max(_solo_ps, 1e-9), 2),
                repl_lag_ms_p50=round(_pctl5(_lags, 0.50), 2),
                repl_lag_ms_p99=round(_pctl5(_lags, 0.99), 2),
                repl_promotion_downtime_ms=round(_down_ms, 1),
                repl=_side,
            )
            note(
                f"replication: {n_readers} readers, single leader "
                f"{_solo_ps:.0f} pulls/s vs leader+follower "
                f"{_agg_ps:.0f} pulls/s "
                f"({_agg_ps / max(_solo_ps, 1e-9):.2f}x), lag p50 "
                f"{_pctl5(_lags, 0.50):.1f}ms, promotion {_down_ms:.0f}ms"
            )
            import shutil as _shutil

            _shutil.rmtree(_rctl, ignore_errors=True)
        except Exception as e:  # tpulint: disable=LT-EXC(replication extra, never the headline)
            note(f"replication phase failed ({type(e).__name__}: {e})")
            if _rproc is not None and _rctl is not None:
                try:
                    # cooperative stop; the child is a CPU process, but
                    # never signal mid-anything on principle
                    with open(os.path.join(_rctl, "stop"), "w") as f:
                        f.write("stop")
                    _rproc.wait(timeout=30)
                except Exception:  # tpulint: disable=LT-EXC(best-effort child teardown on an already-failed phase)
                    pass
            # best-effort teardown: later phases must never time their
            # runs against this phase's leaked worker threads, and a
            # failed run must not strand its control dir in /tmp
            _rlocals = locals()
            for _rname in ("_pool", "_fol2", "_leadA", "_leadB"):
                _robj = _rlocals.get(_rname)
                if _robj is None:
                    continue
                try:
                    if _rname == "_pool":
                        _robj.shutdown(wait=False)
                    else:
                        _robj.close()
                except Exception:  # tpulint: disable=LT-EXC(best-effort teardown on an already-failed phase)
                    pass
            if _rctl is not None:
                import shutil as _shutil

                _shutil.rmtree(_rctl, ignore_errors=True)

    # ---- phase: sharded resident fleet (BENCH_SHARDS=N, ISSUE 8) ------
    # doc-batch parallelism as the distributed axis: the same serving-
    # granularity rounds through a 1-shard vs an N-shard
    # ShardedResidentServer (per-shard PipelinedIngest executors, so
    # coalesced groups launch concurrently across the mesh's doc rows).
    # Banks shard_scaling_x + the `shard` sidecar.  Needs >= N doc rows
    # (the 8-device CPU mesh in CI; chip numbers pending pool return —
    # probe-compile sharded shapes in a disposable run per CLAUDE.md).
    if remaining() > 30 and os.environ.get("BENCH_SHARDS"):
        try:
            import random as _random

            from loro_tpu import LoroDoc
            from loro_tpu.doc import strip_envelope
            from loro_tpu.parallel.mesh import make_mesh as _make_mesh
            from loro_tpu.parallel.sharded import ShardedResidentServer

            n_sh = int(os.environ["BENCH_SHARDS"])
            _smesh = _make_mesh()
            rows_axis = int(np.asarray(_smesh.devices).shape[0])
            if rows_axis < n_sh or rows_axis % n_sh:
                note(
                    f"shard phase skipped: mesh doc axis {rows_axis} "
                    f"cannot host {n_sh} shards (run on the CPU mesh: "
                    "JAX_PLATFORMS=cpu XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8)"
                )
            else:
                SH_DOCS, SH_ROWS, SH_WARM, SH_BLOCK, SH_NBLK = 32, 192, 6, 8, 3
                note(
                    f"shard phase: {n_sh} shards vs 1, {SH_DOCS} docs x "
                    f"{SH_BLOCK * SH_NBLK} {SH_ROWS}-row rounds..."
                )
                _rng4 = _random.Random(0x5E51DE20)
                _doc4 = LoroDoc(peer=4)
                _t4 = _doc4.get_text("t")
                _shrounds = []
                for _e in range(SH_WARM + SH_BLOCK * SH_NBLK):
                    _vv = _doc4.oplog_vv()
                    made = 0
                    while made < SH_ROWS:
                        L = len(_t4)
                        if L > 8 and _rng4.random() < 0.15:
                            p0 = _rng4.randrange(L - 1)
                            dl = min(_rng4.randint(1, 3), L - p0)
                            _t4.delete(p0, dl)
                            made += dl
                        else:
                            run = _rng4.randint(1, 12)
                            _t4.insert(_rng4.randint(0, L),
                                       "abcdefghijkl"[:run])
                            made += run
                    _doc4.commit()
                    _shrounds.append(strip_envelope(_doc4.export_updates(_vv)))
                _cid4 = _doc4.get_text("t").id
                _rows_round = SH_DOCS * SH_ROWS
                import jax.numpy as _jnp

                def _mk_fleet(k):
                    f = ShardedResidentServer(
                        "text", SH_DOCS, shards=k, mesh=_smesh,
                        capacity=1 << 15,
                    )
                    return f, f.pipeline(cid=_cid4, coalesce=8, depth=2)

                def _drain_fleet(f):
                    for _s in f.shards:
                        np.asarray(_jnp.count_nonzero(_s.batch.cols.valid))

                _f1, _x1 = _mk_fleet(1)
                _fn, _xn = _mk_fleet(n_sh)
                for _pl in _shrounds[:SH_WARM]:  # compiles off the clock
                    _x1.submit([_pl] * SH_DOCS)
                    _xn.submit([_pl] * SH_DOCS)
                _x1.flush()
                _xn.flush()
                _drain_fleet(_f1)
                _drain_fleet(_fn)
                _r1 = []
                _rn = []
                for _b in range(SH_NBLK):  # interleaved turns (r4 lesson)
                    _blk = _shrounds[
                        SH_WARM + _b * SH_BLOCK : SH_WARM + (_b + 1) * SH_BLOCK
                    ]
                    for _ex, _fl, _acc in ((_x1, _f1, _r1), (_xn, _fn, _rn)):
                        _t0 = time.perf_counter()
                        for _pl in _blk:
                            _ex.submit([_pl] * SH_DOCS)
                        _ex.flush()
                        _drain_fleet(_fl)
                        _acc.append(
                            SH_BLOCK * _rows_round
                            / (time.perf_counter() - _t0)
                        )
                _r1.sort()
                _rn.sort()
                _m1 = _r1[len(_r1) // 2]
                _mn = _rn[len(_rn) // 2]
                # correctness gate: both fleets serve the host text
                assert _f1.texts() == _fn.texts()
                assert _fn.texts()[0] == _t4.to_string()
                _scaling = _mn / _m1
                _srep = _xn.report()
                _srep.update(
                    docs=SH_DOCS, rows_per_round=SH_ROWS,
                    rows_per_sec_1shard=round(_m1),
                    rows_per_sec=round(_mn),
                    scaling_x=round(_scaling, 2),
                    scaling_efficiency=round(_scaling / n_sh, 3),
                    note=(
                        f"interleaved A/B at serving granularity "
                        f"({SH_ROWS}-row rounds, {SH_DOCS} docs, "
                        f"{SH_NBLK} alternating blocks of {SH_BLOCK}): "
                        f"1-shard vs {n_sh}-shard ShardedResidentServer, "
                        "per-shard pipelines (coalesce=8), reads gated "
                        "equal across fleets and vs the host doc"
                    ),
                )
                _f1.close()
                _fn.close()
                bank(
                    "shard",
                    shard_count=n_sh,
                    shard_rows_per_sec=round(_mn),
                    shard_scaling_x=round(_scaling, 2),
                    shard=_srep,
                )
                note(
                    f"sharded: {n_sh} shards {_mn/1e3:.0f}k rows/s vs "
                    f"1 shard {_m1/1e3:.0f}k ({_scaling:.2f}x, "
                    f"eff {_scaling/n_sh:.2f})"
                )
        except Exception as e:  # tpulint: disable=LT-EXC(shard extra, never the headline)
            note(f"shard phase failed ({type(e).__name__}: {e})")

    # ---- phase: tiered doc residency (BENCH_TIER=1, ISSUE 10) ----------
    # the HBM-capacity story: 32 docs over 4 hot device slots under a
    # skewed (90/10) access trace — the tiered server serves almost all
    # traffic from the hot set while warm/cold docs hold no device rows.
    # Banks tier_hit_rate, revive-latency percentiles and the
    # tiered-vs-all-hot ingest A/B (interleaved blocks, r4 lesson) plus
    # an all-hits hot-path block whose ratio gates the <=10% overhead
    # acceptance (docs/RESIDENCY.md).
    if remaining() > 30 and os.environ.get("BENCH_TIER") == "1":
        try:
            import random as _random

            import jax.numpy as _jnp

            from loro_tpu import LoroDoc
            from loro_tpu.doc import strip_envelope
            from loro_tpu.parallel.residency import TieredResidentServer
            from loro_tpu.parallel.server import ResidentServer

            T_DOCS, T_HOT, T_ROWS = 32, 4, 96
            T_BLOCK, T_NBLK, T_HOTBLK = 12, 3, 12
            note(
                f"tier phase: {T_DOCS} docs over {T_HOT} hot slots, "
                f"90/10 skewed {T_ROWS}-row rounds..."
            )
            _rng5 = _random.Random(0x5E51DE21)
            _tdocs = []
            for i in range(T_DOCS):
                d = LoroDoc(peer=5000 + i)
                d.get_text("t").insert(0, f"tier bench doc {i} base")
                d.commit()
                _tdocs.append(d)
            _tcid = _tdocs[0].get_text("t").id
            _tmarks = [{} for _ in range(T_DOCS)]

            def _tier_delta(di):
                d = _tdocs[di]
                t = d.get_text("t")
                made = 0
                while made < T_ROWS:
                    L = len(t)
                    if L > 8 and _rng5.random() < 0.15:
                        p0 = _rng5.randrange(L - 1)
                        dl = min(_rng5.randint(1, 3), L - p0)
                        t.delete(p0, dl)
                        made += dl
                    else:
                        run = _rng5.randint(1, 12)
                        t.insert(_rng5.randint(0, L), "abcdefghijkl"[:run])
                        made += run
                d.commit()
                pl = strip_envelope(d.export_updates(_tmarks[di]))
                _tmarks[di] = d.oplog_vv()
                return pl

            def _round(di, pl):
                ups = [None] * T_DOCS
                ups[di] = pl
                return ups

            _hot_srv = ResidentServer("text", T_DOCS, capacity=1 << 14)
            _tier_srv = TieredResidentServer(
                "text", T_DOCS, hot_slots=T_HOT, capacity=1 << 14
            )

            def _drain(srv):
                dev = getattr(srv.batch, "device_batch", srv.batch)
                np.asarray(_jnp.count_nonzero(dev.cols.valid))

            # base rounds (full history, one doc per round) + compile
            # warm-up ride off the clock for both fleets
            for i in range(T_DOCS):
                pl = strip_envelope(_tdocs[i].export_updates({}))
                _tmarks[i] = _tdocs[i].oplog_vv()
                for srv in (_hot_srv, _tier_srv):
                    srv.ingest(_round(i, pl), _tcid)
                    _drain(srv)
            # core strictly inside the hot budget: LRU keeps it resident
            # across the 10% tail misses (the run-locality premise)
            _skew_core = list(range(T_HOT - 1))

            def _pick():
                if _rng5.random() < 0.90:
                    return _rng5.choice(_skew_core)
                return _rng5.randrange(T_DOCS)

            # warm block OFF the clock: first release/landing compiles
            # + the skew's steady state (bench rule: compiles never ride
            # a timed window)
            for _ in range(T_BLOCK):
                di = _pick()
                pl = _tier_delta(di)
                for srv in (_hot_srv, _tier_srv):
                    srv.ingest(_round(di, pl), _tcid)
                    _drain(srv)
            _rep0 = _tier_srv.residency.report()
            _rev0 = len(_tier_srv.residency.revive_s)
            _rh, _rt = [], []
            for _b in range(T_NBLK):  # interleaved turns (r4 lesson)
                _blk = [(_pick(),) for _ in range(T_BLOCK)]
                _blk = [(di, _tier_delta(di)) for (di,) in _blk]
                for _srv, _acc in ((_hot_srv, _rh), (_tier_srv, _rt)):
                    _t0 = time.perf_counter()
                    for di, pl in _blk:
                        _srv.ingest(_round(di, pl), _tcid)
                        _drain(_srv)
                    _acc.append(
                        T_BLOCK * T_ROWS / (time.perf_counter() - _t0)
                    )
            # all-hits hot-path block: rounds over docs that are hot
            # RIGHT NOW in the tiered fleet — the <=10%-overhead gate
            _hot_now = _tier_srv.residency.tiers()["hot"]
            _hblk = [
                (di, _tier_delta(di))
                for di in (_rng5.choice(_hot_now) for _ in range(T_HOTBLK))
            ]
            _hp = []
            for _srv in (_hot_srv, _tier_srv):
                _t0 = time.perf_counter()
                for di, pl in _hblk:
                    _srv.ingest(_round(di, pl), _tcid)
                    _drain(_srv)
                _hp.append(T_HOTBLK * T_ROWS / (time.perf_counter() - _t0))
            # correctness gate: both fleets serve the host docs
            assert _tier_srv.texts() == _hot_srv.texts() == [
                d.get_text("t").to_string() for d in _tdocs
            ], "tiered fleet diverged"
            _rh.sort()
            _rt.sort()
            _mh = _rh[len(_rh) // 2]
            _mt = _rt[len(_rt) // 2]
            _trep = _tier_srv.residency.report()
            # WINDOWED stats: only the timed skewed blocks (the
            # lifetime counters include the 32 base-round misses and
            # off-clock warm-up, which are not what the trace measures)
            _w_touch = (_trep["hits"] + _trep["misses"]
                        - _rep0["hits"] - _rep0["misses"])
            _w_hits = _trep["hits"] - _rep0["hits"]
            _hit_rate = round(_w_hits / _w_touch, 4) if _w_touch else 1.0
            _w_rev = sorted(_tier_srv.residency.revive_s[_rev0:])
            _p = lambda q: round(
                (_w_rev[min(len(_w_rev) - 1, int(q * len(_w_rev)))]
                 if _w_rev else 0.0) * 1e3, 3)
            _rev_p50, _rev_p99 = _p(0.50), _p(0.99)
            _trep.update(
                rows_per_round=T_ROWS,
                skew="90/10 over a 3-doc core",
                window_hit_rate=_hit_rate,
                window_revive_ms_p50=_rev_p50,
                window_revive_ms_p99=_rev_p99,
                rows_per_sec_all_hot=round(_mh),
                rows_per_sec_tiered=round(_mt),
                hot_path_rows_per_sec_all_hot=round(_hp[0]),
                hot_path_rows_per_sec_tiered=round(_hp[1]),
                note=(
                    f"interleaved A/B at serving granularity ({T_ROWS}-"
                    f"row single-doc rounds, {T_DOCS} docs, {T_NBLK} "
                    f"alternating blocks of {T_BLOCK}): always-hot "
                    f"ResidentServer vs hot_slots={T_HOT} tiered server "
                    "under a 90/10 skewed trace (one off-clock warm "
                    "block takes release/landing compiles + skew "
                    "steady-state); hit rate and revive percentiles are "
                    "WINDOWED to the timed blocks; hot-path block "
                    "touches only currently-hot docs (the <=10% "
                    "overhead gate); reads gated equal across fleets "
                    "and vs host docs"
                ),
            )
            bank(
                "tier",
                tier_hit_rate=_hit_rate,
                tier_revive_ms_p50=_rev_p50,
                tier_revive_ms_p99=_rev_p99,
                tier_rows_per_sec=round(_mt),
                tier_all_hot_rows_per_sec=round(_mh),
                tier_vs_all_hot=round(_mt / _mh, 3),
                tier_hot_path_ratio=round(_hp[1] / _hp[0], 3),
                tier=_trep,
            )
            note(
                f"tiered: {_mt/1e3:.0f}k rows/s vs all-hot "
                f"{_mh/1e3:.0f}k ({_mt/_mh:.2f}x), windowed hit rate "
                f"{_hit_rate:.2f}, revive p50 {_rev_p50:.1f}ms p99 "
                f"{_rev_p99:.1f}ms, hot-path ratio {_hp[1]/_hp[0]:.2f}"
            )
        except Exception as e:  # tpulint: disable=LT-EXC(tier extra, never the headline)
            note(f"tier phase failed ({type(e).__name__}: {e})")

    # ---- phase: fleet health plane (BENCH_HEALTH=1, ISSUE 17) ---------
    # the observability tax, measured: a HealthPlane sampling THIS
    # process's full registry (every phase above left its counters,
    # labeled rows and histograms behind) — mean/p50/p99 ns per tick
    # over ~200 ticks, plus the heat accountant's rebalancer feed
    # (top-K docs, per-shard skew ratio).  When no serving phase fed
    # the accountant, a seeded zipfian stand-in load makes the skew
    # number meaningful.  Count-guarded: the sampled device-launch
    # counters must not move across the ticks (the sampler never
    # touches the device).
    if remaining() > 10 and os.environ.get("BENCH_HEALTH") == "1":
        try:
            from loro_tpu.obs import heat as _heat
            from loro_tpu.obs import metrics as _obsm
            from loro_tpu.obs.health import HealthPlane as _HealthPlane

            def _launch_total() -> float:
                out = 0.0
                for _mm in _obsm.registry().metrics():
                    if _mm.name in ("fleet.device_launches_total",
                                    "resilience.launches_total"):
                        out += sum(r["value"]
                                   for r in _mm.snapshot()["values"])
                return out

            _acct = _heat.accountant()
            if not _acct.report()["docs_top"]:
                import random as _random

                _hrng = _random.Random(17)
                for _ in range(512):
                    _di = min(int(_hrng.paretovariate(1.2)) - 1, 63)
                    _heat.tick_doc(_di, "push")
                    _heat.tick_shard(_di % 4, "ingest", of=4)
            _plane = _HealthPlane(window_s=60.0)
            _plane.tick()  # warm: first sample builds the flatten dicts
            _hl0 = _launch_total()
            _tick_ns = []
            for _ in range(200):
                _t0 = time.perf_counter_ns()
                _plane.tick()
                _tick_ns.append(time.perf_counter_ns() - _t0)
            _hlaunches = _launch_total() - _hl0
            _tick_ns.sort()
            _hst = _plane.status()
            _hrep = _hst["heat"]
            _mean_ns = int(sum(_tick_ns) / len(_tick_ns))
            bank(
                "health",
                health_tick_ns=_mean_ns,
                health_skew_ratio=_hrep["skew_ratio"],
                health={
                    "ticks": _hst["ticks"],
                    "tick_ns_p50": _tick_ns[len(_tick_ns) // 2],
                    "tick_ns_p99": _tick_ns[int(len(_tick_ns) * 0.99)],
                    "verdict": _hst["verdict"],
                    "open_alerts": len(_hst["alerts"]),
                    "tracked_docs": _hrep["tracked_docs"],
                    "n_shards": _hrep["n_shards"],
                    "skew_ratio": _hrep["skew_ratio"],
                    "docs_top": _hrep["docs_top"][:4],
                    "revive_per_s": _hrep["revive_per_s"],
                    "launches_during_ticks": _hlaunches,
                },
            )
            note(
                f"health: {_mean_ns / 1e3:.0f}us/tick mean "
                f"(p99 {_tick_ns[int(len(_tick_ns) * 0.99)] / 1e3:.0f}us "
                f"over {len(_tick_ns)} ticks), skew {_hrep['skew_ratio']}"
                f", launches during ticks {_hlaunches:.0f}"
            )
        except Exception as e:  # tpulint: disable=LT-EXC(health extra, never the headline)
            note(f"health phase failed ({type(e).__name__}: {e})")

    bank("done", partial=None)
    emit_record(_final_record())


# ---------------------------------------------------------------------------
# guarded parent
# ---------------------------------------------------------------------------


def _tunnel_alive(timeout_s: float = 75.0) -> bool:
    """Fast liveness probe: a tiny jit + host fetch in a subprocess,
    NEVER signaled on timeout (a signal mid-launch is what wedges the
    tunnel — the probe must not cause the wedge it detects).  The
    canonical implementation lives in loro_tpu.resilience.probe; the
    inline twin below keeps the parent working even if the repo import
    itself is broken (the parent must ALWAYS emit a JSON line)."""
    try:
        from loro_tpu.resilience.probe import tunnel_alive

        return tunnel_alive(timeout_s)
    except Exception:  # tpulint: disable=LT-EXC(inline probe twin must work even when the repo import is broken)
        pass
    import subprocess

    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "x = jax.jit(lambda v: v + 1)(jnp.zeros(8, jnp.int32));"
        "print(int(np.asarray(x)[0]))"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # abandonable: never signaled
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        return False


def _child_log_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_children.log"
    )


def _last_json_record(path: str) -> dict | None:
    """Last line of `path` that parses as a JSON object with a 'metric'
    key, re-merged with its `sidecars_for` companion line (emit_record
    splits them).  Scans backwards so a child that printed diagnostics
    after its record can't corrupt the result."""
    try:
        with open(path, "rb") as f:
            text = f.read().decode("utf-8", "replace")
    except OSError:
        return None
    rec = None
    for line in reversed([ln for ln in text.splitlines() if ln.strip()]):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(obj, dict):
            continue
        if rec is None:
            if "metric" in obj:
                rec = obj
        elif obj.get("sidecars_for") == rec.get("metric"):
            side = dict(obj)
            side.pop("sidecars_for", None)
            rec.pop("sidecars", None)
            rec.update(side)
            break
    return rec


def _emit_terminal_failure(reason: str) -> None:
    """The parent's last-resort record: the driver must ALWAYS get one
    parseable JSON line, even when both the device run and the CPU
    fallback produced nothing (round-4 post-mortem: parsed=null)."""
    cfg = os.environ.get("BENCH_CONFIG", "text")
    metric = (
        "ops_merged_per_sec_per_chip [bench_failed]"
        if cfg == "text"
        else f"{cfg}_bench [bench_failed]"
    )
    rec = {
        "metric": metric,
        "value": 0,
        "unit": "ops/s",
        "vs_baseline": 0.0,
        "failure": reason,
    }
    if cfg == "text":
        rec["baseline_band"] = BASELINE_BAND
        rec["baseline_note"] = BASELINE_NOTE
    emit_record(_ambient_fields(rec))


def _run_capture_child(
    env: dict, timeout_s: int, out_path: str
) -> tuple[dict | None, int | None]:
    """Spawn a bench child with stdout -> out_path and stderr -> the
    shared child log, wait up to timeout_s, and return (the child's
    JSON record or None, its return code or None on timeout).  The
    child is NEVER signaled: it may hold an in-flight TPU launch or
    compile, and signaling those wedges the axon tunnel for the whole
    session (CLAUDE.md).  On timeout it is simply abandoned in its own
    session."""
    import subprocess

    with open(out_path, "wb") as out, open(_child_log_path(), "ab") as log:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=out,
            stderr=log,
            start_new_session=True,
        )
    rc: int | None = None
    try:
        proc.wait(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        pass  # abandon without signals
    return _last_json_record(out_path), rc


def _repl_child_main() -> None:
    """BENCH_REPL_CHILD=<ctl_dir>: the replication bench's follower
    PROCESS — a cross-process hot standby over the leader's durable
    directory (``.visible``-marker tail visibility, the real deployment
    shape: its own GIL, its own core, its own read plane).  File
    protocol under ctl_dir: ``child.cfg`` in, ``child.ready`` out,
    then per epoch wait ``e<N>.go`` (JSON ``{"epoch": target}``),
    catch up to the target, serve one reader fan-out, append a line to
    ``child.out`` and write ``e<N>.done``; ``child.final`` carries the
    differential texts + follower report.  Always CPU platform — a
    read replica must never contend for the leader's accelerator (and
    two processes on one TPU can wedge the tunnel)."""
    ctl = os.environ["BENCH_REPL_CHILD"]

    def _fail(e: BaseException) -> None:
        import traceback

        with open(os.path.join(ctl, "child.err"), "w") as f:
            f.write(f"{type(e).__name__}: {e}\n{traceback.format_exc()}")

    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from concurrent.futures import ThreadPoolExecutor

        from loro_tpu.replication import Follower

        with open(os.path.join(ctl, "child.cfg")) as f:
            cfg = json.load(f)
        n, docs = int(cfg["readers"]), int(cfg["docs"])
        fol = Follower(cfg["leader_dir"], cfg["follower_dir"],
                       follower_id="bench-child", leader=None)
        readers = [fol.sync.connect(sid=f"cr{k}") for k in range(n)]
        for k, s in enumerate(readers):
            s.pull(k % docs)
        fol.warm_read_plane(n)
        pool = ThreadPoolExecutor(max_workers=n)
        with open(os.path.join(ctl, "child.ready"), "w") as f:
            f.write("ready")
        with open(os.path.join(ctl, "child.out"), "a") as out:
            for e in range(int(cfg["epochs"])):
                go = os.path.join(ctl, f"e{e}.go")
                stop = os.path.join(ctl, "stop")
                t0w = time.time()
                while not os.path.exists(go):
                    if os.path.exists(stop) or time.time() - t0w > 300:
                        return  # parent stopped (or died): exit clean
                    time.sleep(0.001)
                with open(go) as f:
                    target = int(json.load(f)["epoch"])
                t0 = time.perf_counter()
                deadline = t0 + 60.0
                while (fol.applied_epoch < target
                       and time.perf_counter() < deadline):
                    fol.catch_up()
                    if fol.applied_epoch < target:
                        time.sleep(0.001)
                lag_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                list(pool.map(lambda k: readers[k].pull(k % docs),
                              range(n)))
                wall = time.perf_counter() - t0
                out.write(json.dumps({
                    "e": e, "applied": fol.applied_epoch,
                    "lag_s": round(lag_s, 6),
                    "pull_wall_s": round(wall, 6), "pulls": n,
                }) + "\n")
                out.flush()
                with open(os.path.join(ctl, f"e{e}.done"), "w") as f:
                    f.write("done")
        final = {"texts": fol.resident.texts(), "report": fol.report()}
        pool.shutdown()
        fol.close()
        fpath = os.path.join(ctl, "child.final")
        with open(fpath + ".tmp", "w") as f:
            json.dump(final, f)
        os.replace(fpath + ".tmp", fpath)  # atomic: the parent polls
    except BaseException as e:  # tpulint: disable=LT-EXC(subprocess boundary: the parent reads child.err, a silent death would hang it)
        _fail(e)
        raise


def main_guarded() -> None:
    """Run main() in a subprocess with a watchdog.  The child banks an
    incremental checkpoint after every phase; on timeout the parent
    emits the newest banked device measurement instead of discarding
    the run.  CPU fallback happens ONLY when no device number exists.

    Artifact contract (round-4 post-mortem): the parent is the ONLY
    process ever writing to the real stdout/stderr, every child's
    streams go to files, and the parent's last line is ALWAYS a JSON
    record — no abandoned child can pollute the driver's capture
    25 minutes after the parent exits."""
    import glob
    import subprocess

    base = os.path.dirname(os.path.abspath(__file__))
    for stale in glob.glob(os.path.join(base, ".bench_out_*.jsonl")) + glob.glob(
        os.path.join(base, ".bench_checkpoint_*.json*")
    ):
        # only reap files whose embedded owner pid is provably dead — a
        # live pid means a CONCURRENT invocation (e.g. the watcher
        # ladder), and a non-pid name (a BENCH_CHECKPOINT override that
        # happens to match the glob) is not ours to judge
        m = re.search(r"_(\d+)\.(?:jsonl|json(?:\.cpu)?)$", stale)
        if not m:
            continue
        try:
            os.kill(int(m.group(1)), 0)  # raises if pid is gone
            continue
        except ProcessLookupError:
            pass
        except OSError:
            continue  # pid exists but not ours; leave it alone
        try:
            os.unlink(stale)
        except OSError:
            pass
    if os.environ.get("BENCH_CONFIG", "text") != "text":
        # secondary configs: child prints its own JSON; parent captures,
        # validates, and re-emits it (never signals — the child may be
        # mid-TPU-launch)
        env2 = dict(os.environ, BENCH_INNER="1")
        # pid-unique path: an abandoned child from a PREVIOUS invocation
        # may still hold an fd to a shared name and write its late record
        # into OUR capture (the stdout twin of the r4 stderr post-mortem)
        out_path = os.path.join(
            base, f".bench_out_{os.environ['BENCH_CONFIG']}_{os.getpid()}.jsonl"
        )
        rec, rc = _run_capture_child(
            env2, int(os.environ.get("BENCH_TIMEOUT", "780")), out_path
        )
        if rec is not None:
            emit_record(_ambient_fields(rec))
        else:
            how = (
                "timed out (child abandoned unsignaled)"
                if rc is None
                else f"exited rc={rc}"
            )
            _emit_terminal_failure(
                f"secondary config {os.environ['BENCH_CONFIG']} produced no "
                f"JSON: {how}"
            )
        return

    # pid-unique: an abandoned unsignaled child from a PREVIOUS run may
    # unwedge minutes later and bank ITS phases — a shared checkpoint
    # name would let run 1's measurement surface as run 2's result
    ckpt = os.environ.get("BENCH_CHECKPOINT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f".bench_checkpoint_{os.getpid()}.json",
    )
    for stale in (ckpt, ckpt + ".cpu"):
        try:
            os.unlink(stale)
        except FileNotFoundError:
            pass

    timeout_s = int(os.environ.get("BENCH_TIMEOUT", "780"))
    env = dict(os.environ, BENCH_INNER="1", BENCH_CHECKPOINT=ckpt)
    env.setdefault("BENCH_CHILD_DEADLINE", str(max(60, timeout_s - 120)))

    def read_ckpt() -> dict | None:
        try:
            with open(ckpt) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    probe_wanted = not os.environ.get("BENCH_SKIP_PROBE") and not os.environ.get(
        "JAX_PLATFORMS"
    )
    fallback_reason = None
    if probe_wanted and not _tunnel_alive():
        fallback_reason = "ambient device failed the 75s liveness probe (wedged tunnel?)"
        os.environ["BENCH_PROBE_OUTCOME"] = env["BENCH_PROBE_OUTCOME"] = "dead"
        print(
            "bench: ambient device failed the 75s liveness probe "
            "(wedged tunnel?); cpu fallback without burning the watchdog",
            file=sys.stderr,
        )
    else:
        # the child banks the probe outcome into its resilience sidecar
        env["BENCH_PROBE_OUTCOME"] = "alive" if probe_wanted else "skipped"
        # child stdout -> devnull: the parent is the only JSON emitter
        # (the child's record arrives via the checkpoint file).  stderr
        # -> log file, NOT inherited: an abandoned child dumping its
        # backend-init traceback ~25 min later must never reach the
        # driver's captured stream (round-4 post-mortem: parsed=null).
        with open(_child_log_path(), "ab") as _log:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=_log,
                start_new_session=True,  # survives parent exit if abandoned
            )
        rc = None
        try:
            proc.wait(timeout=timeout_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = None
        ck = read_ckpt()
        if rc == 0 and ck and ck.get("last_phase") == "done":
            emit_record(assemble_record(ck))
            return
        device_banked = bool(
            ck and ck.get("value") and not str(ck.get("device", "")).startswith("cpu")
        )
        if rc is None:
            if device_banked:
                # do NOT signal the child: SIGTERM mid-flight is what
                # wedges the tunnel (CLAUDE.md post-mortems).  Abandon
                # it (own session), emit the banked device number.
                print(
                    f"bench: device run exceeded {timeout_s}s; emitting the "
                    f"banked checkpoint (last phase: {ck.get('last_phase')}) "
                    "and abandoning the child without signals",
                    file=sys.stderr,
                )
                ck.setdefault(
                    "partial", f"run timed out after phase {ck.get('last_phase')}"
                )
                emit_record(assemble_record(ck))
                return
            where = (
                f"after phase {ck.get('last_phase')}" if ck
                else "before first contact (no phase banked: tunnel dead at first device op?)"
            )
            fallback_reason = f"device run exceeded {timeout_s}s, wedged {where}"
            print(
                f"bench: device run exceeded {timeout_s}s with nothing banked, "
                f"wedged {where}; cpu fallback",
                file=sys.stderr,
            )
            # abandon WITHOUT signals: the child may be mid-TPU-launch,
            # and SIGTERM mid-launch wedges the tunnel (CLAUDE.md); it
            # is in its own session and exits on its own if it unwedges
        elif rc == 0 and ck:
            # finished but didn't reach "done" (deadline-skipped phases)
            emit_record(assemble_record(ck))
            return
        else:
            if device_banked:
                print(
                    f"bench: device run failed rc={rc}; emitting banked "
                    f"checkpoint (last phase: {ck.get('last_phase')})",
                    file=sys.stderr,
                )
                ck.setdefault("partial", f"child failed rc={rc} after {ck.get('last_phase')}")
                emit_record(assemble_record(ck))
                return
            fallback_reason = (
                f"device child failed rc={rc} after phase "
                f"{ck.get('last_phase') if ck else None}"
                + ("" if ck else " — backend init raised: pool down?")
            )
            print(f"bench: device run failed rc={rc}; cpu fallback", file=sys.stderr)
    env_cpu = dict(env, JAX_PLATFORMS="cpu", BENCH_LABEL="cpu_fallback")
    # mirror into the parent's environ too: assemble_record/_ambient_fields
    # read these when the PARENT emits a record from the .cpu checkpoint
    os.environ["BENCH_LABEL"] = "cpu_fallback"
    if fallback_reason:
        env_cpu["BENCH_WEDGE_INFO"] = fallback_reason
        os.environ["BENCH_WEDGE_INFO"] = fallback_reason
    env_cpu["BENCH_CHECKPOINT"] = ckpt + ".cpu"
    env_cpu.setdefault("BENCH_BUDGET", "180")
    # histogram placement measures ~7% faster than the sort formulation
    # on the 1-core CPU fallback (the TPU default stays sort: measured
    # 2x the other way on v5e); both are differential-tested equal
    env_cpu.setdefault("PLACE_ALGO", "scatter")
    rec, cpu_rc = _run_capture_child(
        env_cpu,
        int(os.environ.get("BENCH_TIMEOUT", "780")),
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f".bench_out_cpu_{os.getpid()}.jsonl",
        ),
    )
    if rec is not None:
        emit_record(_ambient_fields(rec))
    else:
        ck_cpu = None
        try:
            with open(ckpt + ".cpu") as f:
                ck_cpu = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            pass
        how = "timed out" if cpu_rc is None else f"exited rc={cpu_rc}"
        if ck_cpu and ck_cpu.get("value"):
            ck_cpu.setdefault("partial", f"cpu fallback {how}; banked checkpoint")
            emit_record(assemble_record(ck_cpu))
        else:
            _emit_terminal_failure(
                f"cpu fallback produced no JSON ({how}) and banked no value"
            )


if __name__ == "__main__":
    if os.environ.get("BENCH_REPL_CHILD"):
        _repl_child_main()
    elif os.environ.get("BENCH_INNER") or os.environ.get("BENCH_NO_GUARD"):
        main()
    else:
        main_guarded()
