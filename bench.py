#!/usr/bin/env python
"""North-star benchmark: batched concurrent import of the automerge-perf
trace across a fleet of documents (BASELINE.md config 3).

Per doc, this performs the work of the reference's
`OpLog::import -> DiffCalculator -> apply` replay of the full trace
(reference harness: crates/loro-internal/benches/text_r.rs B4): resolve
the final Fugue sequence order of every element (insert integration +
tombstones) and materialize the visible document.  The fleet dimension
is the TPU win: all documents merge in one XLA launch per chunk.

Prints ONE JSON line:
  {"metric": ..., "value": ops_merged_per_sec, "unit": ..., "vs_baseline": ...}

Baseline denominator: single-threaded reference (Rust) B4 import
throughput.  The reference repo publishes no numbers (BASELINE.md);
Rust is not installed in this image, so we use 2.0e6 ops/s — an
estimate on the generous side for loro's snapshot-import fast path on
this trace (~130ms for 260k ops).
"""
import json
import os
import sys
import time

import numpy as np

RUST_SINGLE_THREAD_OPS_PER_SEC = 2.0e6  # see module docstring


def _emit(metric: str, ops_per_sec: float) -> None:
    label = os.environ.get("BENCH_LABEL")
    if label:
        metric = f"{metric} [{label}]"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(ops_per_sec),
                "unit": "ops/s",
                "vs_baseline": round(ops_per_sec / RUST_SINGLE_THREAD_OPS_PER_SEC, 2),
            }
        ),
        flush=True,
    )


def bench_map() -> None:
    """BASELINE config 1: batched LWW-map concurrent import."""
    import jax
    import numpy as np

    from loro_tpu.ops.lww import MapOpCols, lww_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "1024"))
    m = int(os.environ.get("BENCH_MAP_OPS", "65536"))
    s = int(os.environ.get("BENCH_MAP_SLOTS", "4096"))
    rng = np.random.default_rng(0)
    cols = MapOpCols(
        slot=rng.integers(0, s, (docs, m)).astype(np.int32),
        lamport=rng.integers(0, 1 << 20, (docs, m)).astype(np.int32),
        peer=rng.integers(0, 64, (docs, m)).astype(np.int32),
        value_idx=np.arange(docs * m, dtype=np.int32).reshape(docs, m) % (1 << 20),
        valid=np.ones((docs, m), bool),
    )
    dev = MapOpCols(*[jax.device_put(a) for a in cols])
    out = lww_merge_batch(dev, s)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        out = lww_merge_batch(dev, s)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    _emit(f"lww_map ops merged/sec ({docs}-doc batch, {m} ops/doc)", docs * m / dt)


def bench_tree() -> None:
    """BASELINE config 5: deep hierarchy, concurrent move/reparent."""
    import jax
    import numpy as np

    from loro_tpu.ops.tree_batch import TreeOpCols, tree_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "1024"))
    n_nodes = int(os.environ.get("BENCH_TREE_NODES", "512"))
    m = int(os.environ.get("BENCH_TREE_MOVES", "2048"))
    rng = np.random.default_rng(0)
    target = rng.integers(0, n_nodes, (docs, m)).astype(np.int32)
    parent = rng.integers(-2, n_nodes, (docs, m)).astype(np.int32)
    cols = TreeOpCols(
        target=target, parent=parent, valid=np.ones((docs, m), bool)
    )
    dev = TreeOpCols(*[jax.device_put(a) for a in cols])
    d_max = int(os.environ.get("BENCH_TREE_DEPTH", "64"))
    out = tree_merge_batch(dev, n_nodes, d_max)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = tree_merge_batch(dev, n_nodes, d_max)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    _emit(f"tree moves merged/sec ({docs}-doc batch, {m} moves/doc)", docs * m / dt)


def bench_movable() -> None:
    """BASELINE config ~4/5 hybrid: movable-list concurrent move/set."""
    import jax
    import numpy as np

    from loro_tpu.ops.fugue_batch import SeqColumns
    from loro_tpu.ops.movable_batch import MovableCols, movable_merge_batch

    docs = int(os.environ.get("BENCH_DOCS", "256"))
    s = int(os.environ.get("BENCH_SLOTS", "8192"))  # slots per doc
    n_elems = s // 2
    rng = np.random.default_rng(0)
    # synthetic but structurally real: first half = insert slots
    # (right-spine), second half = move slots pointing at random elems
    parent = np.concatenate(
        [np.arange(-1, n_elems - 1, dtype=np.int32), rng.integers(0, n_elems, s - n_elems).astype(np.int32)]
    )
    elem = np.concatenate(
        [np.arange(n_elems, dtype=np.int32), rng.integers(0, n_elems, s - n_elems).astype(np.int32)]
    )
    lam = np.concatenate(
        [np.arange(n_elems, dtype=np.int32), rng.integers(n_elems, 4 * n_elems, s - n_elems).astype(np.int32)]
    )
    seq = SeqColumns(
        parent=np.broadcast_to(parent, (docs, s)).copy(),
        side=np.ones((docs, s), np.int32),
        peer=np.zeros((docs, s), np.int32),
        counter=np.broadcast_to(np.arange(s, dtype=np.int32), (docs, s)).copy(),
        deleted=np.zeros((docs, s), bool),
        content=np.broadcast_to(elem, (docs, s)).copy(),
        valid=np.ones((docs, s), bool),
    )
    cols = MovableCols(
        seq=SeqColumns(*[jax.device_put(a) for a in seq]),
        lamport=jax.device_put(np.broadcast_to(lam, (docs, s)).copy()),
        set_elem=jax.device_put(np.broadcast_to(np.arange(n_elems, dtype=np.int32), (docs, n_elems)).copy()),
        set_lamport=jax.device_put(np.zeros((docs, n_elems), np.int32)),
        set_peer=jax.device_put(np.zeros((docs, n_elems), np.int32)),
        set_value=jax.device_put(np.broadcast_to(np.arange(n_elems, dtype=np.int32), (docs, n_elems)).copy()),
        set_valid=jax.device_put(np.ones((docs, n_elems), bool)),
    )
    out = movable_merge_batch(cols, n_elems)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = movable_merge_batch(cols, n_elems)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    _emit(f"movable_list ops merged/sec ({docs}-doc batch, {s} slots/doc)", docs * s / dt)


def bench_size() -> None:
    """Encoded-size harness (reference: examples/benches/mergeable_size
    + encode.rs): bytes per op for updates / snapshot / state-only on
    the automerge trace prefix."""
    from loro_tpu import ExportMode, LoroDoc
    from loro_tpu.bench_utils import load_automerge_patches

    n_txn = int(os.environ.get("BENCH_TXN_LIMIT", "20000"))
    patches, _ = load_automerge_patches(limit=n_txn)
    doc = LoroDoc(peer=1)
    t = doc.get_text("text")
    for pos, dels, ins in patches:
        if dels:
            t.delete(pos, dels)
        if ins:
            t.insert(pos, ins)
    doc.commit()
    updates = len(doc.export_updates())
    snapshot = len(doc.export(ExportMode.Snapshot))
    state_only = len(doc.export(ExportMode.StateOnly))
    n_ops = len(patches)
    print(
        json.dumps(
            {
                "metric": f"update bytes/op ({n_ops} ops; snapshot={snapshot}B state_only={state_only}B)",
                "value": round(updates / n_ops, 2),
                "unit": "bytes/op",
                "vs_baseline": 1.0,
            }
        ),
        flush=True,
    )


def main() -> None:
    # bench runs on the real chip (ambient platform) by default; an
    # explicit JAX_PLATFORMS env must win even though the axon plugin
    # overrides it at the config level
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    config = os.environ.get("BENCH_CONFIG", "text")
    if config == "map":
        return bench_map()
    if config == "tree":
        return bench_tree()
    if config == "movable":
        return bench_movable()
    if config == "size":
        return bench_size()

    from loro_tpu.bench_utils import automerge_final_text, automerge_seq_extract
    from loro_tpu.ops.columnar import chain_columns
    from loro_tpu.ops.fugue_batch import (
        ChainColumns,
        chain_merge_docs,
        chain_merge_docs_checksum,
        pad_bucket,
    )

    # conservative defaults: one modest-size compile + small uploads (a
    # killed mid-flight TPU launch can wedge the tunnel — CLAUDE.md);
    # scale up with BENCH_DOCS/BENCH_CHUNK when the chip budget allows
    docs_total = int(os.environ.get("BENCH_DOCS", "64"))
    chunk = int(os.environ.get("BENCH_CHUNK", "8"))
    limit = os.environ.get("BENCH_TXN_LIMIT")
    limit = int(limit) if limit else None

    def note(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    from loro_tpu.ops.columnar import contract_chains

    note("bench: extracting trace (cached after first run)...")
    ex, n_ops = automerge_seq_extract(limit=limit)
    n_chains = contract_chains(ex).n_chains
    cols1 = chain_columns(ex, pad_n=pad_bucket(ex.n), pad_c=pad_bucket(n_chains))

    # broadcast one trace across the chunk's doc axis (each doc pays the
    # full merge; contents identical — the kernel can't exploit that)
    batched = ChainColumns(*[np.broadcast_to(a, (chunk,) + a.shape).copy() for a in cols1])
    note(f"bench: uploading {chunk}-doc chunk ({ex.n} elements/doc)...")
    dev_cols = ChainColumns(*[jax.device_put(a) for a in batched])

    # correctness: one doc's materialized text == ground truth
    note("bench: compiling + correctness check...")
    codes, counts = chain_merge_docs(dev_cols)
    got = "".join(map(chr, np.asarray(codes[0])[: int(counts[0])]))
    want = automerge_final_text(limit=limit)
    assert got == want, f"device merge mismatch: {len(got)} vs {len(want)} chars"
    note("bench: timing...")

    # timed region: merge launches covering docs_total documents; merged
    # state stays on device, only per-doc checksums return
    n_chunks = max(1, docs_total // chunk)
    warm = chain_merge_docs_checksum(dev_cols)
    jax.block_until_ready(warm)
    t0 = time.perf_counter()
    out = None
    for _ in range(n_chunks):
        out = chain_merge_docs_checksum(dev_cols)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    docs_done = n_chunks * chunk
    _emit(
        "ops_merged_per_sec_per_chip (automerge-perf trace, "
        f"{docs_done}-doc concurrent import)",
        docs_done * n_ops / dt,
    )


def main_guarded() -> None:
    """Run main() in a subprocess with a watchdog: a wedged TPU tunnel
    (see CLAUDE.md) must not hang the bench forever.  On timeout, retry
    on the virtual CPU backend with an honest 'cpu_fallback' label."""
    import subprocess

    def run_graceful(cmd, env, timeout_s):
        # Never SIGKILL a JAX child mid-TPU-launch (CLAUDE.md: it can
        # wedge the axon tunnel for the whole session).  SIGTERM and
        # give the runtime a long grace window to unwind the launch.
        proc = subprocess.Popen(cmd, env=env)
        try:
            proc.wait(timeout=timeout_s)
            return proc.returncode
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                print(
                    "bench: child ignored SIGTERM; leaving it to finish "
                    "rather than SIGKILL a mid-flight TPU launch",
                    file=sys.stderr,
                )
                proc.wait()
            return None  # distinct from any real returncode (incl. signal -N)

    timeout_s = int(os.environ.get("BENCH_TIMEOUT", "900"))
    env = dict(os.environ, BENCH_INNER="1")
    rc = run_graceful([sys.executable, os.path.abspath(__file__)], env, timeout_s)
    if rc == 0:
        return
    if rc is None:
        print(f"bench: device run exceeded {timeout_s}s (wedged tunnel?); cpu fallback", file=sys.stderr)
    else:
        print(f"bench: device run failed rc={rc}; cpu fallback", file=sys.stderr)
    env_cpu = dict(env, JAX_PLATFORMS="cpu", BENCH_LABEL="cpu_fallback")
    run_graceful([sys.executable, os.path.abspath(__file__)], env_cpu, timeout_s)


if __name__ == "__main__":
    if os.environ.get("BENCH_INNER") or os.environ.get("BENCH_NO_GUARD"):
        main()
    else:
        main_guarded()
